#!/usr/bin/env python3
"""Benchmarks — headline + the full reproducible suite.

Default invocation (the driver contract) prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
— the CIFAR-10 ResNet training throughput per chip.

``--suite`` re-measures EVERY row of docs/benchmarks.md and prints one JSON
line per row (plus the headline line last, so the driver's single-line
parse still works by reading the final line). No benchmark number in the
docs lives outside this file: each row of the table is a ``--suite`` row.

BASELINE.md: the reference publishes no performance numbers at all (it is a
control-plane operator; its compute lived in user MXNet images). The
BASELINE.json target metric is "CIFAR-10 steps/sec/chip vs GPU spec" — the
GPU spec being the reference's single-GPU CIFAR example
(/root/reference/README.md:126-167, `alpha.kubernetes.io/nvidia-gpu: 1`,
NVIDIA K80-class, 2017-era MXNet). Published MXNet ResNet/CIFAR-10 numbers
for that setup cluster around ~1.2k images/sec, which we pin as the
baseline denominator below (documented assumption, reference ships none).

Measurement hygiene (the driver's TPU is reached through a network tunnel
whose artifacts a real TPU VM does not have — ~100 ms RTT per host sync,
~0.3 GB/s effective host→device bandwidth):
- batches are pre-staged in HBM and cycled, so the timed region measures
  the training step, not the tunnel's transfer bandwidth (a real input
  pipeline overlaps host I/O behind the step via prefetch);
- the timing fence is a ``device_get`` of a final value — a value fetch
  cannot complete before the dependent computation chain does on any
  backend, whereas ``block_until_ready`` was observed returning early
  through the tunnel and would inflate results ~10x.

MFU accounting (the ``lm_*`` rows): model FLOPs per step =
6 * params * tokens (fwd+bwd param matmuls) + 12 * L * B * T^2 * d / 2
(causal attention, fwd+bwd, the /2 because a causal kernel skips the
masked half). Remat recompute is *excluded* — MFU counts useful FLOPs
only, so remat configs pay their recompute as lost utilization, which is
the honest accounting. Peak for the v5e chip: 197 bf16 TFLOPS.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time


# The reference's GPU config throughput assumption (see module docstring).
BASELINE_IMAGES_PER_SEC = 1200.0
V5E_PEAK_TFLOPS = 197.0


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true",
                   help="tiny CPU-friendly config (smoke test, not a "
                        "benchmark); with --suite, runs every suite row at "
                        "smoke shapes")
    p.add_argument("--suite", action="store_true",
                   help="re-measure every docs/benchmarks.md row: CIFAR "
                        "headline, LM ladder + flagship MFU, raw matmul "
                        "ceiling, flash-vs-XLA attention at long T, and the "
                        "control-plane (operator) rows")
    p.add_argument("--control-plane", action="store_true",
                   help="run ONLY the control-plane rows (no JAX/TPU "
                        "needed): reads-per-reconcile budget, steady-state "
                        "reconcile latency, parallel-vs-sequential gang "
                        "creation against the in-process apiserver; exits "
                        "nonzero if the zero-read budget regresses")
    p.add_argument("--fleet", action="store_true",
                   help="run ONLY the fleet-scheduler rows (no JAX/TPU "
                        "needed): ~5k TPUJobs driven through the "
                        "slice-inventory admission queue over the "
                        "in-process apiserver with sharded reconcile "
                        "workers; exits nonzero if p99 reconcile latency, "
                        "the status-write budget, or the zero-read steady "
                        "state regresses (--quick: a few hundred jobs)")
    p.add_argument("--drain", action="store_true",
                   help="run ONLY the cooperative-drain rows (no JAX/TPU "
                        "needed): planned restart vs hard preemption "
                        "lost-step-seconds over the real trainer machinery "
                        "with an injected clock, plus the drain-deadline "
                        "hard-kill backstop; exits nonzero if a "
                        "cooperative drain costs more than one checkpoint "
                        "interval (or more than the hard reference), or "
                        "the never-ACKed drain fails to reach Done")
    p.add_argument("--churn", action="store_true",
                   help="run the create-run-delete churn soak: >=200 "
                        "cycles through the real operator with the "
                        "joblife witness on — zero per-job state "
                        "residue, flat /metrics series count, bounded "
                        "RSS, or exit nonzero")
    p.add_argument("--cluster", action="store_true",
                   help="run the kwok-style fake-cluster storm soak (no "
                        "JAX/TPU needed): the REAL operator over node/"
                        "kubelet state machines with discovered slice "
                        "inventory, hit by seeded chaos storms (slice "
                        "preemption, node flaps, API-fault bursts, pod "
                        "kills, slow kubelets); exits nonzero unless the "
                        "fleet fully drains — zero leaked pods, zero "
                        "stuck Queued, zero joblife violations, flat "
                        "series count, bounded RSS, bounded during-storm "
                        "reconcile p99 (--quick: ~1k pods; full: 10k "
                        "pods / 5k jobs)")
    p.add_argument("--seed", type=int, default=1234,
                   help="storm-schedule seed for --cluster; the whole "
                        "kill/flap schedule derives from it, so a failing "
                        "seed replays bit-identically")
    p.add_argument("--checkpoint", action="store_true",
                   help="run ONLY the checkpoint durability micro-rows "
                        "(CPU-hostable): verified-save + restore latency vs "
                        "state size, and the corrupt-latest fallback-scan "
                        "cost")
    p.add_argument("--startup", action="store_true",
                   help="run ONLY the warm-restart startup rows: cold vs "
                        "warm time-to-first-step on the transformer payload "
                        "(fresh subprocess each, shared persistent "
                        "compilation cache + checkpoint dir); exits nonzero "
                        "if the warm restart stops beating cold or the "
                        "cache stops hitting")
    p.add_argument("--store", action="store_true",
                   help="run ONLY the remote warm-start store rows: "
                        "fresh-node restart (cold local dirs, warm remote "
                        "store) TTFS vs a fully cold start, with the "
                        "prefetch hit and per-run goodput asserted, plus "
                        "the write-behind step-time guard (uploads must "
                        "never ride the step loop); exits nonzero on "
                        "regression")
    p.add_argument("--steptrace", action="store_true",
                   help="run ONLY the flight-recorder overhead guard "
                        "(CPU-hostable): the same step loop with the "
                        "per-step phase recorder on vs off, interleaved "
                        "windows; exits nonzero if recorder-on steady "
                        "step time exceeds recorder-off by more than 1% "
                        "(50 µs absolute floor)")
    p.add_argument("--dataplane", action="store_true",
                   help="run ONLY the self-tuning data-plane rows "
                        "(CPU-hostable): the autotune controller must "
                        "converge within 5%% of the best static prefetch "
                        "depth found by sweep inside the window budget, "
                        "the async host path must shave the measured "
                        "HOST-phase time, and recorder+autotune together "
                        "must hold the 1% overhead budget — exits nonzero "
                        "on regression")
    p.add_argument("--serve", action="store_true",
                   help="run ONLY the serving-mode rows (CPU-hostable): "
                        "the batched decode service under the synthetic "
                        "load generator, and the rolling weight reload "
                        "under sustained load — exits nonzero if any "
                        "decode step fails or the reload does not "
                        "complete")
    p.add_argument("--flagship", action="store_true",
                   help="run ONLY the flagship compute-path A/B rows "
                        "(CPU-hostable with --quick): each optimization "
                        "of the shared compute surface (remat policy, "
                        "fused loss, adam8, scan-over-blocks, AOT via the "
                        "warm cache) measured INDIVIDUALLY against the "
                        "seed path in interleaved windows with the "
                        "min-of-pairwise-delta discipline, plus one arm "
                        "with autotune + host pipeline + async host "
                        "engaged whose steptrace digest names the "
                        "dominant residue phase; exits nonzero if any "
                        "optimization regresses past its budget")
    p.add_argument("--startup-worker", default="", help=argparse.SUPPRESS)
    p.add_argument("--batch", type=int, default=0, help="override global batch")
    p.add_argument("--steps", type=int, default=0, help="override timed steps")
    return p.parse_args(argv)


def _device_get_fence(x):
    import jax

    return jax.device_get(x)


def _timed_steps(step_once, steps: int, warmup: int, windows: int) -> dict:
    """The shared timing harness every row uses: ``step_once() -> fence
    value`` runs ``warmup`` times, is fenced, then ``windows`` timed
    windows of ``steps`` calls each run, each window fenced by a
    ``device_get`` of its last value (module docstring: the tunnel makes
    ``block_until_ready`` unusable). One definition so a timing fix cannot
    miss a row."""
    val = None
    for _ in range(warmup):
        val = step_once()
    _device_get_fence(val)

    def window():
        t0 = time.perf_counter()
        v = None
        for _ in range(steps):
            v = step_once()
        _device_get_fence(v)
        return (time.perf_counter() - t0) / steps

    return _median_windows(window, windows)


def _median_windows(run_window, n_windows: int) -> dict:
    """Run ``run_window() -> seconds`` ``n_windows`` times and report the
    median with its run-to-run spread. Every suite row goes through this:
    the tunnel's few-percent jitter (and its occasional 10%+ outliers —
    the round-2 matmul row spread 77-85% of peak between runs) must be
    visible in the artifact, not silently passed through by a single
    measurement."""
    times = sorted(run_window() for _ in range(n_windows))
    med = times[len(times) // 2]
    return {
        "seconds": med,
        "windows": n_windows,
        "spread_pct": (round(100 * (times[-1] - times[0]) / med, 1)
                       if n_windows > 1 else 0.0),
    }


def _emit(row: dict) -> dict:
    print(json.dumps(row), flush=True)
    return row


# --- CIFAR headline ------------------------------------------------------------

def bench_cifar(quick: bool, batch_override: int = 0,
                steps_override: int = 0) -> dict:
    """The flagship classifier payload exactly as the operator launches it
    (tpu_operator/payload/cifar.py): ResNet-20, bf16 on the MXU, one jit."""
    import jax

    from tpu_operator.payload import cifar, data as data_mod, train

    n_devices = len(jax.devices())
    platform = jax.devices()[0].platform

    if quick:
        batch = batch_override or 64
        steps = steps_override or 5
        cfg = ["--blocks", "1", "--widths", "8", "16", "32"]
    else:
        batch = batch_override or 2048
        steps = steps_override or 60
        cfg = ["--blocks", "3", "--widths", "16", "32", "64"]  # ResNet-20

    cargs = cifar.parse_args(["--batch", str(batch), *cfg])
    mesh, _model, state, step, batches = cifar.build(cargs)

    # Pre-stage a handful of batches in HBM and cycle them: host RNG and the
    # tunnel's host→device path stay off the timed region (module
    # docstring); put_global_batch on an already-sharded array is a no-op.
    pregen = [data_mod.put_global_batch(mesh, *b)
              for b in itertools.islice(batches, 8)]
    cycled = itertools.cycle(pregen)

    # AOT-compile through the warm persistent cache BEFORE any timed
    # window (ROADMAP 1c), and report the compile as an out-of-window
    # field instead of letting first-window warmup absorb it. A stable
    # default cache dir makes the second bench invocation a warm
    # deserialize unless the operator injected its own cache volume.
    from tpu_operator.payload import compute

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/tpujob-bench-xla-cache")
    compiled, compile_seconds, cache_hit = compute.aot_compile_cached(
        step, state, pregen[0])
    if compiled is not None:
        step = compiled

    # Median of three timed windows (compile cost is paid once, before
    # the first window; each window still runs its own 5 warmup steps):
    # the tunnel adds a few percent of run-to-run jitter a single
    # window would pass straight through to the recorded number.
    rates = []
    for _ in range(1 if quick else 3):
        state, steps_per_sec = train.throughput(
            mesh, step, state, cycled, steps=steps, warmup=5
        )
        rates.append(steps_per_sec)
    rates.sort()
    images_per_sec = rates[len(rates) // 2] * batch
    per_chip = images_per_sec / n_devices

    return {
        "metric": f"cifar10_resnet20_bf16_images_per_sec_per_chip_{platform}",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMAGES_PER_SEC, 3),
        "compile_seconds": round(compile_seconds, 3),
        "compile_cache_hit": cache_hit,
    }


# --- flagship compute-path A/B rows --------------------------------------------

# Each optimization of the shared compute surface (payload/compute.py),
# measured INDIVIDUALLY against the seed path: (key, off-arm extra argv,
# on-arm extra argv, gate kind, (quick budget, full budget), floor µs).
# "step" gates the min-of-pairwise-delta steady-state REGRESSION of the
# on arm (budget in %) — the memory-for-compute trades (remat, int8
# moments) are allowed to cost steady-state time on a platform with no
# memory pressure (quick = CPU host, tiny shapes), the parity-expected
# ones (fused loss, AOT dispatch) are not. "compile" gates the on arm's
# build+first-step seconds against the off arm's (budget = max ratio %):
# scan-over-blocks' claim is compile time that stops scaling with depth,
# not steady-state speed (its While body costs loop overhead the seed
# path's inlined blocks don't pay — the documented trade), so its row
# runs at a DEEPER depth where the claim is testable and its steady-state
# regression is reported unbudgeted. adam8's off arm is plain adam — the
# honest 8-bit-vs-f32 comparison within the same optimizer family;
# everything else A/Bs against the unmodified seed argv.
FLAGSHIP_AB = (
    ("remat_dots", [], ["--remat-policy", "dots"], "step",
     (60.0, 60.0), 500.0),
    ("fused_loss", [], ["--fused-loss"], "step", (10.0, 5.0), 300.0),
    ("adam8", ["--optimizer", "adam"], ["--optimizer", "adam8"], "step",
     (60.0, 15.0), 500.0),
    ("scan_blocks", [], ["--scan-blocks"], "compile", (120.0, 125.0), 0.0),
)

# steptrace wire field -> the phase name the ISSUE-facing row reports.
_PHASE_NAMES = {"dataWait": "DATA", "dispatch": "DISPATCH",
                "compute": "COMPUTE", "checkpoint": "CHECKPOINT",
                "host": "HOST"}


def bench_flagship(quick: bool) -> list:
    """The --flagship gate: the shared compute path's optimizations, each
    A/B-measured individually against the seed flagship path, plus the
    autotune-engaged residue-attribution arm (ROADMAP item 1a).

    Discipline is PR 9's (bench_steptrace): both arms run the same loop
    shape over pre-staged HBM batches, in INTERLEAVED windows so clock
    drift and host contention land on both arms equally; the headline
    regression is the MINIMUM of the pairwise (on - off) deltas, clamped
    at zero — a real systematic cost is present in every pair, a
    contention burst is absent from at least one. Each arm owns its
    state (the step donates it; adam8/scan change the state tree).

    The final row runs the optimized path through the REAL train_loop
    with the self-tuning data plane engaged (TPUJOB_DATAPLANE_AUTOTUNE,
    host pipeline + async host live, a heartbeat reporter attached) and
    the PR-9 step recorder on, then attributes the residual step time to
    the dominant phase by the recorder's p50 digest — COMPUTE dominating
    is the honest "the remaining gap is compute-bound" answer; anything
    else names the subsystem to go after next."""
    import jax

    from tpu_operator.payload import cifar, compute
    from tpu_operator.payload import data as data_mod

    if quick:
        batch, steps, windows = 32, 30, 5
        cfg = ["--blocks", "2", "--widths", "8", "8", "8"]
    else:
        batch, steps, windows = 1024, 20, 5
        cfg = ["--blocks", "3", "--widths", "16", "32", "64"]
    base_argv = ["--batch", str(batch), *cfg]

    def build_arm(extra):
        cargs = cifar.parse_args(base_argv + list(extra))
        t0 = time.perf_counter()
        mesh, _model, state, step_fn, batches = cifar.build(cargs)
        pregen = [data_mod.put_global_batch(mesh, *b)
                  for b in itertools.islice(batches, 4)]
        arm = {"state": state, "step": step_fn,
               "cycled": itertools.cycle(pregen), "mesh": mesh}
        # First fenced step = trace + compile; timed per arm so the
        # compile-gated rows (scan_blocks) have their number, and always
        # outside every timed window.
        arm["state"], metrics = arm["step"](arm["state"],
                                            *next(arm["cycled"]))
        jax.device_get(metrics["loss"])
        arm["compile_seconds"] = time.perf_counter() - t0
        for _ in range(2):
            arm["state"], metrics = arm["step"](arm["state"],
                                                *next(arm["cycled"]))
        jax.device_get(metrics["loss"])
        return arm

    def run_window(arm, n_steps) -> float:
        t0 = time.perf_counter()
        metrics = None
        for _ in range(n_steps):
            arm["state"], metrics = arm["step"](arm["state"],
                                                *next(arm["cycled"]))
        jax.device_get(metrics["loss"])
        return (time.perf_counter() - t0) / n_steps

    def ab_row(key, off_arm, on_arm, gate, budget, floor_us, extra=None):
        # Compile-gated rows keep their (unbudgeted, informational)
        # steady-state measurement short — their deep config makes full
        # windows cost minutes for a number nothing gates on.
        n_windows, n_steps = (2, 10) if gate == "compile" else (windows,
                                                               steps)
        off_times, on_times = [], []
        for _ in range(n_windows):
            off_times.append(run_window(off_arm, n_steps))
            on_times.append(run_window(on_arm, n_steps))
        off = min(off_times)
        deltas = [on_t - off_t for off_t, on_t in zip(off_times, on_times)]
        regression = max(0.0, min(deltas))
        speedup = max(0.0, min(off_t - on_t for off_t, on_t
                               in zip(off_times, on_times)))
        row = {
            "metric": f"flagship_ab_{key}",
            "off_step_ms": round(off * 1e3, 4),
            "on_step_ms": round((off + regression - speedup) * 1e3, 4),
            "regression_pct": round(100.0 * regression / off, 2),
            "speedup_pct": round(100.0 * speedup / off, 2),
            "regression_us_per_step": round(regression * 1e6, 2),
            "compile_off_s": round(off_arm["compile_seconds"], 3),
            "compile_on_s": round(on_arm["compile_seconds"], 3),
            "windows": n_windows,
            "gate": gate,
            "budget": budget,
            "floor_us": floor_us,
            "unit": "pct",
            "value": round(100.0 * regression / off, 2),
        }
        row.update(extra or {})
        return row

    rows = []
    for key, off_extra, on_extra, gate, budgets, floor_us in FLAGSHIP_AB:
        # The compile-gated row runs DEEP (quick: blocks 6): the claim
        # under test is that scan's compile cost stops scaling with
        # depth, which two blocks per stage cannot distinguish (later
        # --blocks wins in argparse).
        depth = (["--blocks", "6"] if gate == "compile" and quick else [])
        off_arm = build_arm(depth + list(off_extra))
        on_arm = build_arm(depth + list(on_extra))
        rows.append(ab_row(
            key, off_arm, on_arm, gate, budgets[0 if quick else 1],
            floor_us))

    # AOT dispatch: the SAME seed program, jit-dispatched vs invoked as
    # the AOT executable compiled through the persistent cache — a
    # steady-state parity check (AOT's win is trace-time at step 0, paid
    # out-of-window here and reported alongside).
    off_arm = build_arm([])
    on_arm = build_arm([])
    # Same stable default cache dir as bench_cifar: the second invocation
    # (and every verify run after the first) exercises the WARM
    # persistent-cache deserialize path and reports the hit.
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/tpujob-bench-xla-cache")
    compiled, compile_seconds, cache_hit = compute.aot_compile_cached(
        on_arm["step"], on_arm["state"], next(on_arm["cycled"]))
    if compiled is not None:
        on_arm["step"] = compiled
    rows.append(ab_row(
        "aot", off_arm, on_arm, "step", 10.0, 300.0,
        extra={"aot_compile_seconds": round(compile_seconds, 3),
               "compile_cache_hit": cache_hit}))

    # -- the autotune-engaged residue-attribution arm -------------------------
    from tpu_operator.payload import autotune as autotune_mod
    from tpu_operator.payload import heartbeat as heartbeat_mod
    from tpu_operator.payload import steptrace as steptrace_mod
    from tpu_operator.payload import train

    residue_steps = 120 if quick else 200
    cargs = cifar.parse_args(base_argv + ["--fused-loss", "--log-every", "0"])
    mesh, _model, state, step_fn, batches = cifar.build(cargs)
    recorder = steptrace_mod.StepRecorder(capacity=4096)
    # A real reporter (no-op poster, never due mid-run: a due beat drains
    # the recorder's window digest, and this row wants the WHOLE run's
    # phase distribution) so the runtime's async-host hook is live.
    reporter = heartbeat_mod.HeartbeatReporter(
        "http://bench.invalid", "flagship", poster=lambda *_a: None,
        interval=3600.0)
    engaged = {autotune_mod.ENV_AUTOTUNE: "1",
               autotune_mod.ENV_WINDOW_STEPS: "16"}
    saved = {k: os.environ.get(k) for k in engaged}
    os.environ.update(engaged)
    try:
        state, _metrics = train.train_loop(
            mesh, step_fn, state, batches, residue_steps,
            heartbeat=reporter, steptrace=recorder, overlap=False)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    summary = recorder.summary()
    phases = summary["phases"] if summary else {}
    p50s = {name: phases[field]["p50Seconds"]
            for field, name in _PHASE_NAMES.items() if field in phases}
    residue_phase = max(p50s, key=p50s.get) if p50s else ""
    step_p50 = summary["stepP50Seconds"] if summary else 0.0
    rows.append({
        "metric": "flagship_residue_attribution",
        "engaged": ["autotune", "host_pipeline", "async_host"],
        # On the CPU backend jit dispatch is synchronous, so device
        # compute lands in the DISPATCH lap and COMPUTE (the deferred
        # fence) reads near zero — DISPATCH here is the CPU stand-in for
        # compute-bound. On a real TPU the dispatch lap is µs-scale and
        # COMPUTE carries the device time.
        "platform": jax.devices()[0].platform,
        "steps": summary["steps"] if summary else 0,
        "step_p50_ms": round(step_p50 * 1e3, 4),
        "images_per_sec": round(batch / step_p50, 1) if step_p50 else 0.0,
        "residue_phase": residue_phase,
        "phase_p50_ms": {name: round(t * 1e3, 4)
                         for name, t in sorted(p50s.items())},
        "phase_share_pct": {name: round(100.0 * t / max(step_p50, 1e-12), 1)
                            for name, t in sorted(p50s.items())},
        "unit": "phase",
        "value": residue_phase,
    })
    return rows


def _flagship_ok(rows: list) -> bool:
    ok = True
    for row in rows:
        if row["metric"] == "flagship_residue_attribution":
            if row["residue_phase"] not in _PHASE_NAMES.values():
                print(f"flagship residue attribution MISSING: {row}",
                      file=sys.stderr)
                ok = False
            continue
        if row["gate"] == "compile":
            ratio = 100.0 * row["compile_on_s"] / max(row["compile_off_s"],
                                                      1e-9)
            if ratio <= row["budget"]:
                continue
            print(f"flagship compile budget EXCEEDED: {row['metric']} "
                  f"on-arm build+compile {row['compile_on_s']} s vs off "
                  f"{row['compile_off_s']} s ({ratio:.0f}% > "
                  f"{row['budget']}%)", file=sys.stderr)
            ok = False
            continue
        over_pct = row["regression_pct"]
        over_us = row["regression_us_per_step"]
        if over_pct <= row["budget"] or over_us <= row["floor_us"]:
            continue
        print(f"flagship A/B budget EXCEEDED: {row['metric']} on-arm "
              f"{row['on_step_ms']} ms vs off {row['off_step_ms']} ms "
              f"({over_pct:.2f}% > {row['budget']}% and "
              f"{over_us:.1f} µs > {row['floor_us']} µs)", file=sys.stderr)
        ok = False
    return ok


# --- LM ladder / flagship MFU --------------------------------------------------

def lm_model_flops_per_step(n_matmul_params: int, batch: int, seq: int,
                            layers: int, dim: int) -> int:
    """Model FLOPs of one step (module docstring: 6NT + causal attention).
    ``n_matmul_params`` must exclude embedding tables: their forward is a
    gather and their backward a scatter-add, not 6N matmul FLOPs — counting
    them would inflate MFU by ~12% at the flagship config."""
    tokens = batch * seq
    return (6 * n_matmul_params * tokens
            + 12 * layers * batch * seq * seq * dim // 2)


def bench_lm(name: str, argv: list, steps: int, warmup: int = 3,
             windows: int = 3, live_input: bool = False) -> dict:
    """``live_input=False`` pre-stages 4 batches in HBM and cycles them, so
    the timed region isolates the training step from the measurement
    tunnel's host→device artifacts (module docstring). ``live_input=True``
    instead streams every batch through the production path —
    data.device_prefetch (depth 2) over the real iterator — so the row
    measures training WITH the input pipeline doing actual work, the way a
    job on a real TPU VM runs."""
    import jax

    from tpu_operator.payload import data as data_mod, transformer

    targs = transformer.parse_args(argv)
    mesh, _model, state, step, batches = transformer.build(targs)
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    n_params = sum(leaf.size for _path, leaf in flat)
    n_matmul_params = sum(
        leaf.size for path, leaf in flat
        if not any("embed" in str(getattr(k, "key", k)) for k in path))
    spec = transformer.lm_token_spec(mesh)
    if live_input:
        cycled = data_mod.device_prefetch(mesh, batches, spec=spec, depth=2)
    else:
        pregen = [data_mod.put_global_batch(mesh, *b, spec=spec)
                  for b in itertools.islice(batches, 4)]
        cycled = itertools.cycle(pregen)

    state_box = [state]

    def step_once():
        state_box[0], metrics = step(state_box[0], *next(cycled))
        return metrics["loss"]

    timing = _timed_steps(step_once, steps, warmup, windows)
    dt = timing["seconds"]

    flops = lm_model_flops_per_step(n_matmul_params, targs.batch,
                                    targs.seq_len, targs.layers, targs.dim)
    tflops = flops / dt / 1e12
    return {
        "metric": name,
        "value": round(targs.batch * targs.seq_len / dt),
        "unit": "tokens/sec",
        "params_M": round(n_params / 1e6, 1),
        "matmul_params_M": round(n_matmul_params / 1e6, 1),
        "step_ms": round(dt * 1e3, 1),
        "model_tflops": round(tflops, 1),
        "mfu_pct": round(100 * tflops / V5E_PEAK_TFLOPS, 1),
        "windows": timing["windows"],
        "spread_pct": timing["spread_pct"],
        "config": " ".join(argv),
    }


LM_LADDER = [
    ("lm_d512_L4", ["--dim", "512", "--layers", "4", "--heads", "8",
                    "--batch", "32", "--seq-len", "2048",
                    "--vocab", "32768"], 30),
    ("lm_d1024_L8", ["--dim", "1024", "--layers", "8", "--heads", "8",
                     "--batch", "16", "--seq-len", "2048",
                     "--vocab", "32768"], 20),
    # The flagship: largest config sustaining peak MFU on one v5e chip —
    # 541M params, dots-remat (matmul outputs resident, elementwise
    # recomputed), bf16 adam mu, batch 32 via 4 grad-accum microbatches.
    ("lm_flagship_d2048_L8", ["--dim", "2048", "--layers", "8",
                              "--heads", "16", "--batch", "32",
                              "--seq-len", "2048", "--vocab", "32768",
                              "--remat", "--remat-policy", "dots",
                              "--grad-accum", "4",
                              "--adam-mu-dtype", "bf16"], 10),
    # The same flagship with grouped-query attention (4 K/V heads serving
    # 16 query heads) on the kernel-native grouped-KV path, plus the
    # dots_attn remat policy (saves the flash kernel's named residuals so
    # the attention forward is not re-run in the backward): the best row.
    ("lm_flagship_gqa_kv4", ["--dim", "2048", "--layers", "8",
                             "--heads", "16", "--kv-heads", "4",
                             "--batch", "32", "--seq-len", "2048",
                             "--vocab", "32768",
                             "--remat", "--remat-policy", "dots_attn",
                             "--grad-accum", "4",
                             "--adam-mu-dtype", "bf16"], 10),
    # The flagship on int8 block-quantized adam moments (optimizers.adam8):
    # measured ~0.5% step-time cost for 1.8 GiB of optimizer HBM back
    # (0.92 GiB of moments vs 2.72 at bf16-mu, 3.63 at f32).
    ("lm_flagship_gqa_kv4_adam8", ["--dim", "2048", "--layers", "8",
                                   "--heads", "16", "--kv-heads", "4",
                                   "--batch", "32", "--seq-len", "2048",
                                   "--vocab", "32768",
                                   "--remat", "--remat-policy", "dots_attn",
                                   "--grad-accum", "4",
                                   "--optimizer", "adam8"], 10),
    # Model-level long context (the kernel-level rows cover attention
    # alone): the same architecture trained END TO END at 8k and 32k
    # tokens on one chip — the capability the flash kernels' O(T) memory
    # exists for. (The learned position table grows with seq-len — +13M
    # params at 8k, +63M at 32k — but embeddings are excluded from the
    # matmul-param MFU accounting, so the rows stay comparable.) 32k
    # needs full remat + the int8 optimizer's freed HBM (dots_attn at
    # 32k does not fit).
    ("lm_longctx_T8192_gqa", ["--dim", "2048", "--layers", "8",
                              "--heads", "16", "--kv-heads", "4",
                              "--batch", "8", "--seq-len", "8192",
                              "--vocab", "32768",
                              "--remat", "--remat-policy", "dots_attn",
                              "--grad-accum", "4",
                              "--adam-mu-dtype", "bf16"], 8),
    # Round 5: the [B, T, 32768] logits never materialize (--loss-chunk,
    # train.chunked_next_token_nll) and the freed HBM upgrades full remat
    # to the attn policy (flash residuals saved — the attention forward,
    # over half the FLOPs at 32k, is not re-run in the backward):
    # 46.6% -> 53.7% MFU measured. Saving MORE (q/k/v, the post-attn
    # residual — attn_block) fits but buys nothing: the step is
    # attention-kernel-bound (profile: 60.9% of busy), not recompute-bound.
    ("lm_longctx_T32768_gqa", ["--dim", "2048", "--layers", "8",
                               "--heads", "16", "--kv-heads", "4",
                               "--batch", "2", "--seq-len", "32768",
                               "--vocab", "32768", "--remat",
                               "--remat-policy", "attn",
                               "--grad-accum", "2",
                               "--optimizer", "adam8",
                               "--loss-chunk", "2048"], 4),
]

LM_LADDER_QUICK = [
    ("lm_quick", ["--dim", "64", "--layers", "2", "--heads", "2",
                  "--batch", "4", "--seq-len", "128", "--vocab", "256"], 3),
]


def _ensure_token_corpus(path: str, n_tokens: int, vocab: int) -> str:
    """Generate (once) a token corpus .npy for the real-data bench row —
    seeded, so the file is reproducible; uint16 (vocab < 65536), so 50M
    tokens cost 100 MB of disk and zero resident RAM via mmap."""
    import numpy as np

    if not os.path.exists(path):
        rng = np.random.default_rng(1234)
        np.save(path, rng.integers(0, vocab, size=n_tokens,
                                   dtype=np.uint16))
    return path


def bench_lm_realdata(quick: bool) -> dict:
    """The flagship GQA config re-measured with the REAL input pipeline
    active: a memory-mapped token file streamed through device_prefetch
    (production path) instead of pre-staged HBM batches. The delta vs the
    lm_flagship_gqa_kv4 row is the end-to-end input-pipeline cost."""
    if quick:
        cfg = ["--dim", "64", "--layers", "2", "--heads", "2",
               "--batch", "4", "--seq-len", "128", "--vocab", "256"]
        path = _ensure_token_corpus("/tmp/bench_tokens_quick.npy",
                                    200_000, 256)
        steps, windows = 3, 1
    else:
        cfg = list(LM_LADDER[3][1])  # lm_flagship_gqa_kv4
        path = _ensure_token_corpus("/tmp/bench_tokens_50m.npy",
                                    50_000_000, 32768)
        steps, windows = 10, 3
    row = bench_lm("lm_flagship_gqa_kv4_realdata" if not quick
                   else "lm_quick_realdata",
                   cfg + ["--data", path], steps, windows=windows,
                   live_input=True)
    row["input"] = "mmap token file via device_prefetch(depth=2)"
    return row


# --- MoE single-chip -----------------------------------------------------------

def bench_moe(quick: bool, windows: int = 3) -> list:
    """Single-chip MoE LM (all experts local — the dispatch and capacity
    bookkeeping run at full fidelity, only the all-to-all is a no-op):
    tokens/sec, MFU on *active* FLOPs, and the measured dropped-token
    fraction at the configured capacity factor. MFU accounting: expert
    FFN params count at 2/E weight (top-2 routing — each token activates
    two experts), so a config whose routed FLOPs equal the dense ladder's
    is directly comparable to it.

    Two rows: ``moe_e8_top2_single_chip`` at the near-init router (the
    round-3 row — its drop_frac ~0.5 shows what an *unbalanced* router
    costs), and ``moe_e8_top2_trained_router`` after 300 training steps,
    where the Switch aux loss has had time to act — the drop_frac pair is
    the measured proof the balancing loss converges (the trajectory test
    in tests/test_moe.py pins the same property on CPU)."""
    import jax

    from tpu_operator.payload import data as data_mod, moe

    if quick:
        argv = ["--dim", "64", "--layers", "2", "--heads", "2",
                "--experts", "4", "--batch", "4", "--seq-len", "128",
                "--vocab", "256", "--dtype", "f32"]
        steps, windows, train_steps = 3, 1, 5
        config_rev = "quick"
    else:
        # batch 8: the [E,G,C,D] expert buffers scale with G — batch 16 at
        # this config OOMs the 16G chip in HLO temps (measured), 8 fits.
        # heads 8 / kv 4 (head_dim 128): round 3 ran 16 heads of d_head 64,
        # whose half-width lanes made the attention kernels 33.9% of busy
        # time (profile_breakdown --payload moe); d_head 128 + grouped KV
        # is the TPU-native shape at the same model dim — +25% tokens/sec
        # with identical expert math.
        argv = ["--dim", "1024", "--layers", "8", "--heads", "8",
                "--kv-heads", "4", "--experts", "8", "--batch", "8",
                "--seq-len", "2048", "--vocab", "32768",
                "--capacity-factor", "1.25"]
        steps, train_steps = 20, 300
        config_rev = "r4-h8kv4"
    margs = moe.parse_args(argv)
    mesh, _model, state, step, batches = moe.build(margs)

    from jax.sharding import PartitionSpec as P

    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]

    def path_str(path):
        return "/".join(str(getattr(k, "key", k)) for k in path)

    n_params = sum(leaf.size for _p, leaf in flat)
    active = 0
    for path, leaf in flat:
        s = path_str(path)
        if "embed" in s:
            continue
        if "/moe/" in s and s.rsplit("/", 1)[-1] in ("w1", "w2"):
            active += leaf.size * 2 // margs.experts
        else:
            active += leaf.size
    pregen = [data_mod.put_global_batch(mesh, *b, spec=P("data", None))
              for b in itertools.islice(batches, 4)]
    cycled = itertools.cycle(pregen)

    state_box = [state]
    metrics_box = [None]

    def step_once():
        state_box[0], metrics_box[0] = step(state_box[0], *next(cycled))
        return metrics_box[0]["loss"]

    flops = lm_model_flops_per_step(active, margs.batch, margs.seq_len,
                                    margs.layers, margs.dim)

    def measure(metric):
        timing = _timed_steps(step_once, steps, warmup=3, windows=windows)
        metrics = metrics_box[0]  # from the last *measured* step
        dt = timing["seconds"]
        tflops = flops / dt / 1e12
        return {
            "metric": metric,
            "value": round(margs.batch * margs.seq_len / dt),
            "unit": "tokens/sec",
            "params_M": round(n_params / 1e6, 1),
            "active_matmul_params_M": round(active / 1e6, 1),
            "step_ms": round(dt * 1e3, 1),
            "model_tflops": round(tflops, 1),
            "mfu_pct": round(100 * tflops / V5E_PEAK_TFLOPS, 1),
            "drop_frac": round(float(metrics["drop_frac"]), 4),
            "capacity_factor": margs.capacity_factor,
            "train_step": int(jax.device_get(state_box[0].step)),
            "windows": timing["windows"],
            "spread_pct": timing["spread_pct"],
            "config": " ".join(argv),
            # Round-over-round tooling: the metric NAME predates round 4's
            # head-geometry change (16 h / d_head 64 -> 8 h / 4 kv /
            # d_head 128); rows with different config_rev are not the same
            # measurement and must not be diffed as one series.
            "config_rev": config_rev,
        }

    rows = [measure("moe_e8_top2_single_chip")]
    consumed = int(jax.device_get(state_box[0].step))
    for _ in range(max(0, train_steps - consumed)):
        step_once()
    rows.append(measure("moe_e8_top2_trained_router"))
    return rows


# --- pipeline scheduling overhead ----------------------------------------------

def bench_pipeline_overhead(quick: bool, windows: int = 3) -> list:
    """S=1 pipelines vs the dense transformer at the identical config:
    the pipeline machinery's pure scheduling cost — tick scan, stash
    bookkeeping, manual vjp — with zero stages to hide it behind. The
    honest floor for what --pipeline costs before its memory/scale wins
    buy anything back. Two rows: plain 1F1B (the round-3 number) and
    interleaved 1F1B at V=2 virtual stages, which adds the table-driven
    schedule and bigger stash buffers on top — the constant factor the
    analytic ~V× bubble shrink must beat on real multi-chip meshes."""
    import jax

    from tpu_operator.payload import data as data_mod, pipeline, transformer

    from jax.sharding import PartitionSpec as P

    if quick:
        shape = ["--dim", "64", "--layers", "2", "--heads", "2",
                 "--batch", "4", "--seq-len", "128", "--vocab", "256"]
        steps, windows = 3, 1
    else:
        shape = ["--dim", "1024", "--layers", "8", "--heads", "16",
                 "--batch", "16", "--seq-len", "2048", "--vocab", "32768"]
        steps = 15

    def timed(build_fn, parse, argv, spec):
        args = parse(argv)
        mesh, _m, state, step, batches = build_fn(args)
        pregen = [data_mod.put_global_batch(mesh, *b, spec=spec)
                  for b in itertools.islice(batches, 4)]
        cycled = itertools.cycle(pregen)
        state_box = [state]

        def step_once():
            state_box[0], metrics = step(state_box[0], *next(cycled))
            return metrics["loss"]

        return _timed_steps(step_once, steps, warmup=3, windows=windows)

    dense = timed(transformer.build, transformer.parse_args, shape,
                  P("data", None))

    def overhead_row(metric, extra):
        pipe = timed(pipeline.build, pipeline.parse_args, shape + extra,
                     P("data", None))
        overhead = 100 * (pipe["seconds"] / dense["seconds"] - 1)
        return {
            "metric": metric,
            "value": round(overhead, 1),
            "unit": "pct",
            "pipe_step_ms": round(pipe["seconds"] * 1e3, 1),
            "dense_step_ms": round(dense["seconds"] * 1e3, 1),
            "windows": pipe["windows"],
            "spread_pct": pipe["spread_pct"],
            "config": " ".join(shape + extra),
        }

    return [
        overhead_row("pipeline_s1_1f1b_overhead_vs_dense",
                     ["--pipeline", "1", "--microbatches", "4",
                      "--schedule", "1f1b"]),
        overhead_row("pipeline_s1_1f1b_interleaved_overhead",
                     ["--pipeline", "1", "--microbatches", "4",
                      "--schedule", "1f1b-interleaved",
                      "--virtual-stages", "2"]),
    ]


# --- raw matmul ceiling --------------------------------------------------------

def bench_matmul(quick: bool) -> dict:
    """Ceiling check: chained bf16 matmuls, one dispatch — what the chip
    gives a pure MXU workload through this framework's jit path. Model
    configs below this are bandwidth/overhead-bound, not framework-bound."""
    import jax
    import jax.numpy as jnp

    n = 1024 if quick else 8192
    chain = 2 if quick else 8
    steps = 2 if quick else 10

    @jax.jit
    def chained(x, w):
        for _ in range(chain):
            x = jnp.dot(x, w)
        return x

    key = jax.random.key(0)
    x = jax.random.normal(key, (n, n), jnp.bfloat16)
    w = jax.random.normal(key, (n, n), jnp.bfloat16)
    out_box = [x]

    def step_once():
        out_box[0] = chained(out_box[0], w)
        return out_box[0][0, 0]

    timing = _timed_steps(step_once, steps, warmup=1,
                          windows=1 if quick else 3)
    tflops = 2 * n * n * n * chain / timing["seconds"] / 1e12
    return {
        "metric": f"matmul_bf16_{n}cubed_x{chain}",
        "value": round(tflops, 1),
        "unit": "TFLOPS",
        "pct_of_peak": round(100 * tflops / V5E_PEAK_TFLOPS, 1),
        "windows": timing["windows"],
        "spread_pct": timing["spread_pct"],
    }


# --- flash attention vs fused-XLA at long T ------------------------------------

def bench_attention(quick: bool) -> list:
    """Train-step (fwd+bwd) attention at growing T: the Pallas flash path
    (O(T) memory both directions) vs XLA differentiating dense attention
    (O(T^2) scores), plus the grouped-KV (GQA kv4) kernel at each length.
    Rows report speedup; where the dense path cannot fit in HBM the flash
    row is the only one that runs — that is the long-context capability.
    ``xla_status`` records how the dense comparison ended: "ran",
    "oom" (attempted on-device and hit resource exhaustion — demonstrated,
    not estimated), or "skipped" (score tensors alone are several times
    HBM; attempting would only stall the suite)."""
    import jax
    import jax.numpy as jnp

    from tpu_operator.payload import flash_attention as fa
    from tpu_operator.payload import ring_attention as ring

    on_tpu = jax.default_backend() == "tpu"
    # Batch shrinks as T grows (tokens roughly constant, like a real
    # long-context config); the dense path runs only while its backward's
    # ~3 f32 [B,H,T,T] tensors fit a 16G chip.
    configs = [(256, 1, 2, 64)] if quick else [
        (2048, 4, 16, 128), (8192, 1, 16, 128), (32768, 1, 16, 128)]
    xla_budget_bytes = 12e9
    windows = 1 if quick else 3
    rows = []

    def timed_grad(fn, q, k, v, steps):
        # Differentiate wrt ALL of (q, k, v): a grad wrt q alone lets XLA
        # dead-code-eliminate the entire dK/dV kernel (pallas_call is
        # side-effect-free), so the round-4 "fwd_bwd" rows measured only
        # fwd + dQ — ~55-60% of the real backward. Training always needs
        # all three.
        loss = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2)))
        return _timed_steps(lambda: loss(q, k, v)[0][0, 0, 0, 0], steps,
                            warmup=5, windows=windows)

    for t, b, h, d in configs:
        key = jax.random.key(0)
        mk = lambda hh: jax.random.normal(key, (b, t, hh, d), jnp.bfloat16)
        q, k, v = mk(h), mk(h), mk(h)
        # Long windows: the tunnel pays a ~115 ms dispatch-latency ramp
        # after every fence (hack/attn_microbench.py docstring), so the
        # round-3 2-step windows at T=32768 were ramp-dominated — the
        # 13.9/17.5% spreads on the GQA rows were the harness, not the
        # kernel. Target ≥~0.8 s per window at measured per-step times
        # (T2048 ~3.2 ms → 400 steps ≈ 1.3 s; T8192 ~9.6 ms → 100 ≈
        # 1.0 s; T32768 ~87-101 ms → 25 ≈ 2.2 s): the first 40-step
        # revision still showed 11-13% spread on the short-T arms.
        steps = 3 if quick else max(25, 400 * 2048 // t)
        flash_fn = lambda q, k, v: fa.flash_attention(
            q, k, v, causal=True, use_pallas=on_tpu or None)
        xla_ms, xla_status = None, "ran"
        est_bytes = 3 * 4 * b * h * t * t
        if est_bytes <= 2 * xla_budget_bytes:
            # Within reach of HBM (or near it): actually attempt the dense
            # path and let the allocator decide — an OOM here is the
            # demonstrated result, not a paper estimate.
            try:
                xla = timed_grad(
                    lambda q, k, v: ring.reference_attention(q, k, v,
                                                             causal=True),
                    q, k, v, steps)
                xla_ms = xla["seconds"] * 1e3
            except Exception as e:  # XlaRuntimeError: RESOURCE_EXHAUSTED
                if "RESOURCE_EXHAUSTED" not in str(e).upper().replace(" ", "_"):
                    raise
                xla_status = "oom"
        else:
            xla_status = "skipped"

        if h % 4 == 0 and not quick:
            # MHA and grouped-KV (kv = h/4) interleaved A/B: windows
            # alternate M,G,M,G,… within one process, so tunnel drift
            # hits both arms equally and the speedup separates from
            # noise (VERDICT round-3 item 6).
            kg, vg = mk(h // 4), mk(h // 4)
            # Full grads (see timed_grad): wrt-q-only would DCE the dK/dV
            # kernel and measure ~60% of the backward.
            full = lambda q, k, v: jnp.sum(
                flash_fn(q, k, v).astype(jnp.float32) ** 2)
            loss_m = jax.jit(lambda q: jax.grad(full, (0, 1, 2))(q, k, v))
            loss_g = jax.jit(lambda q: jax.grad(full, (0, 1, 2))(q, kg, vg))

            def window(loss):
                jax.device_get(loss(q)[0][0, 0, 0, 0])  # warm re-entry
                t0 = time.perf_counter()
                v_ = None
                for _ in range(steps):
                    v_ = loss(q)
                jax.device_get(v_[0][0, 0, 0, 0])
                return (time.perf_counter() - t0) / steps

            for w in range(2):  # compile+warm both arms
                window(loss_m), window(loss_g)
            times_m, times_g = [], []
            for w in range(5):
                times_m.append(window(loss_m))
                times_g.append(window(loss_g))
            times_m.sort(), times_g.sort()
            med_m, med_g = times_m[2], times_g[2]
            spread = lambda ts, med: round(100 * (ts[-1] - ts[0]) / med, 1)
            flash_ms, gqa_ms = med_m * 1e3, med_g * 1e3
            rows.append({
                "metric": f"flash_attention_T{t}_fwd_bwd",
                "value": round(flash_ms, 2),
                "unit": "ms/step",
                "xla_ms": round(xla_ms, 2) if xla_ms is not None else None,
                "xla_status": xla_status,
                "speedup_vs_xla": (round(xla_ms / flash_ms, 2)
                                   if xla_ms is not None else None),
                "windows": 5,
                "spread_pct": spread(times_m, med_m),
                "shape": f"B{b} H{h} D{d}",
            })
            rows.append({
                "metric": f"flash_attention_T{t}_gqa_kv{h // 4}_fwd_bwd",
                "value": round(gqa_ms, 2),
                "unit": "ms/step",
                "speedup_vs_mha": round(flash_ms / gqa_ms, 2),
                "windows": 5,
                "spread_pct": spread(times_g, med_g),
                "ab_interleaved": True,
                "shape": f"B{b} H{h} KV{h // 4} D{d}",
            })
        else:
            flash = timed_grad(flash_fn, q, k, v, steps)
            flash_ms = flash["seconds"] * 1e3
            rows.append({
                "metric": f"flash_attention_T{t}_fwd_bwd",
                "value": round(flash_ms, 2),
                "unit": "ms/step",
                "xla_ms": round(xla_ms, 2) if xla_ms is not None else None,
                "xla_status": xla_status,
                "speedup_vs_xla": (round(xla_ms / flash_ms, 2)
                                   if xla_ms is not None else None),
                "windows": flash["windows"],
                "spread_pct": flash["spread_pct"],
                "shape": f"B{b} H{h} D{d}",
            })
    return rows


# --- control plane (the operator itself) ---------------------------------------

def _cp_make_job(name: str, replicas: int):
    """A WORKER-only TPUJob shaped like the megascale target."""
    from tpu_operator.apis.tpujob.v1alpha1 import types as t
    from tpu_operator.apis.tpujob.v1alpha1.defaults import set_defaults

    job = t.TPUJob(
        metadata={"name": name, "namespace": "default",
                  "uid": f"uid-{name}"},
        spec=t.TPUJobSpec(
            replica_specs=[t.TPUReplicaSpec(
                replicas=replicas,
                template={"spec": {"containers": [
                    {"name": "tpu", "image": "img:latest"}],
                    "restartPolicy": "OnFailure"}},
                tpu_replica_type=t.TPUReplicaType.WORKER)],
            runtime_id="b3nc",
            restart_backoff=t.RestartBackoffSpec(base_seconds=0),
        ),
    )
    set_defaults(job.spec)
    return job


def _cp_sync_listers(listers, cs) -> None:
    listers.tpujobs.replace(cs.tpujobs.list("default"))
    listers.pods.replace(cs.pods.list("default"))
    listers.services.replace(cs.services.list("default"))


def _cp_steady_job(replicas: int, with_listers: bool = True):
    """A Running ``replicas``-worker job at steady state: gang created, all
    pods Running, informer stores (when attached) caught up."""
    from tpu_operator.client.fake import FakeClientset
    from tpu_operator.client.informer import Listers, Store, add_child_indexes
    from tpu_operator.controller.events import EventRecorder
    from tpu_operator.trainer.training import TrainingJob

    cs = FakeClientset()
    job = _cp_make_job("steady", replicas)
    cs.tpujobs.create("default", job.to_dict())
    listers = None
    if with_listers:
        pods, services = Store(), Store()
        add_child_indexes(pods)
        add_child_indexes(services)
        listers = Listers(tpujobs=Store(), pods=pods, services=services)
        _cp_sync_listers(listers, cs)
    tj = TrainingJob(cs, EventRecorder(cs), job, listers=listers)
    tj.reconcile()  # creates the gang
    for pod in cs.pods.list("default"):
        pod["status"] = {"phase": "Running", "containerStatuses": [
            {"name": "tpu", "state": {"running": {}}}]}
        cs.pods.update("default", pod)
    if listers is not None:
        _cp_sync_listers(listers, cs)
    tj.reconcile()  # transitions to Running
    if listers is not None:
        _cp_sync_listers(listers, cs)
    return cs, tj


_CP_READ_VERBS = ("get", "list", "watch")


def _cp_reads_in(cs, fn) -> int:
    before = len(cs.actions)
    fn()
    return sum(1 for verb, _r, _ns, _n in cs.actions[before:]
               if verb in _CP_READ_VERBS)


def bench_cp_reads(quick: bool) -> dict:
    """Measured API reads per steady-state reconcile: the cache-backed path
    (informer indexers + one ReplicaSnapshot) vs the informer-less fallback
    (two label-selected LISTs + one job GET), against the seed's per-index
    shape (~4·N+1: one Service GET per index and a pod LIST per index in
    each of missing-index, status roll-up, and failure classification,
    plus the status-diff GET)."""
    n = 16 if quick else 256
    cs, tj = _cp_steady_job(n, with_listers=True)
    cached = _cp_reads_in(cs, tj.reconcile)
    cs2, tj2 = _cp_steady_job(n, with_listers=False)
    fallback = _cp_reads_in(cs2, tj2.reconcile)
    seed_shape = 4 * n + 1
    return {
        "metric": "api_reads_per_reconcile",
        "value": cached,
        "unit": "reads",
        "replicas": n,
        "fallback_no_informer": fallback,
        "seed_per_index_shape": seed_shape,
        # None (JSON null) when cached==0: float('inf') serializes as the
        # non-standard token `Infinity`, which strict JSON consumers of the
        # bench rows reject on exactly the healthy path.
        "reduction_vs_seed": (None if cached == 0
                              else round(seed_shape / cached, 1)),
    }


def bench_cp_steady_latency(quick: bool) -> dict:
    """p50 wall time of one steady-state reconcile pass (zero-RPC path) at
    the megascale replica count — pure in-memory classification cost."""
    n = 16 if quick else 256
    passes = 20 if quick else 100
    _cs, tj = _cp_steady_job(n, with_listers=True)
    times = []
    for _ in range(passes):
        t0 = time.perf_counter()
        tj.reconcile()
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    return {
        "metric": "reconcile_steady_p50_ms",
        "value": round(times[len(times) // 2], 3),
        "unit": "ms",
        "p90_ms": round(times[int(len(times) * 0.9)], 3),
        "replicas": n,
        "passes": passes,
    }


def bench_cp_gang_create(quick: bool) -> dict:
    """Gang bring-up wall time over the REAL wire: the in-process apiserver
    (testing/apiserver.py) serves HTTP to the production REST clientset;
    the same N-pod gang is created sequentially (createParallelism=1) and
    across the bounded pool (16), interleaved A/B so host jitter hits both
    arms. This is the ~N/16-vs-N RTT claim, measured.

    Localhost has no RTT to overlap (and both ends share one GIL), so the
    server injects a seeded mean-10 ms per-request latency via the chaos
    FlakyClientset — handler threads sleep off-GIL, standing in for the
    network + apiserver-processing time a real create pays."""
    import random

    from tpu_operator.apis.tpujob.v1alpha1.types import ControllerConfig
    from tpu_operator.client.fake import FakeClientset
    from tpu_operator.client.rest import Clientset, RestConfig
    from tpu_operator.controller.chaos import FlakyClientset
    from tpu_operator.testing.apiserver import ApiServerHarness
    from tpu_operator.trainer.training import TrainingJob

    n = 32 if quick else 256
    windows = 1 if quick else 3
    par = 16
    rtt_mean_s = 0.010  # uniform(0, 20 ms), seeded: same weather both arms

    backing = FakeClientset()
    flaky = FlakyClientset(backing, error_rate=0.0,
                           max_latency=2 * rtt_mean_s,
                           rng=random.Random(711))
    with ApiServerHarness(clientset=flaky) as srv:
        clientset = Clientset(RestConfig(host=srv.url))

        def one_gang(tag: str, parallelism: int) -> float:
            job = _cp_make_job(f"gang-{tag}", n)
            tj = TrainingJob(clientset, None, job,
                             config=ControllerConfig(
                                 create_parallelism=parallelism))
            tj.setup_replicas()
            t0 = time.perf_counter()
            tj.sync_pods_gang(0)
            dt = (time.perf_counter() - t0) * 1e3
            # free the backing store for the next window
            srv.clientset.pods.delete_collection("default")
            return dt

        seq_times, par_times = [], []
        for w in range(windows):
            seq_times.append(one_gang(f"s{w}", 1))
            par_times.append(one_gang(f"p{w}", par))
    seq_times.sort(), par_times.sort()
    seq_ms = seq_times[len(seq_times) // 2]
    par_ms = par_times[len(par_times) // 2]
    return {
        "metric": f"gang_create_{n}_wall_ms",
        "value": round(par_ms, 1),
        "unit": "ms",
        "sequential_ms": round(seq_ms, 1),
        "speedup_vs_sequential": round(seq_ms / par_ms, 2),
        "parallelism": par,
        "windows": windows,
        "injected_rtt_mean_ms": rtt_mean_s * 1e3,
        "transport": "in-process apiserver over HTTP (REST clientset)",
    }


def bench_control_plane(quick: bool) -> list:
    """The operator's own cost rows (no JAX involved). Returns the rows;
    the caller fails the run if the zero-read budget regressed."""
    return [
        bench_cp_reads(quick),
        bench_cp_steady_latency(quick),
        bench_cp_gang_create(quick),
    ]


# --- fleet scheduler (admission queue at ~5k jobs) ------------------------------

FLEET_SLICE_KEY = "cloud-tpus.google.com/v4:2x2x2"


def _fleet_job(name: str, queue: str, priority: int = 0) -> dict:
    """One single-worker TPUJob demanding one v4 2x2x2 slice."""
    from tpu_operator.apis.tpujob.v1alpha1 import types as t

    return t.TPUJob(
        metadata={"name": name, "namespace": "default"},
        spec=t.TPUJobSpec(
            replica_specs=[t.TPUReplicaSpec(
                replicas=1,
                template={"spec": {"containers": [
                    {"name": "tpu", "image": "img:latest",
                     "resources": {
                         "limits": {"cloud-tpus.google.com/v4": 4}}}],
                    "restartPolicy": "Never"}},
                tpu_replica_type=t.TPUReplicaType.WORKER)],
            runtime_id="flt1",
            tpu_topology="2x2x2",
            restart_backoff=t.RestartBackoffSpec(base_seconds=0),
            scheduling=t.SchedulingSpec(priority=priority, queue=queue),
        ),
    ).to_dict()


def _fleet_reads(metrics) -> float:
    """get+list RPCs issued by the operator's clientset, summed over
    resources (watch is the standing stream, not a steady-state read)."""
    kinds = ("TPUJob", "Pod", "Service", "Event", "Endpoints",
             "ConfigMap", "Lease")
    return sum(metrics.counter_value("api_requests_total",
                                     {"verb": verb, "resource": kind})
               for verb in ("get", "list") for kind in kinds)


def _fleet_status_puts(metrics) -> float:
    return sum(metrics.counter_value("api_requests_total",
                                     {"verb": verb, "resource": "TPUJob"})
               for verb in ("update", "update_status"))


def _hist_quantile_bound(metrics, name: str, q: float):
    """Upper-bound the q-quantile from a histogram's fixed buckets: the
    smallest bucket bound whose cumulative count covers q."""
    snap = metrics.histogram_snapshot(name)
    if not snap or not snap["count"]:
        return None, 0
    target = q * snap["count"]
    for bound, cum in snap["buckets"].items():
        if cum >= target:
            return (float("inf") if bound == "+Inf" else float(bound),
                    snap["count"])
    return float("inf"), snap["count"]


def bench_fleet(quick: bool) -> list:
    """~5k TPUJobs through the slice-inventory admission queue: the REAL
    operator (REST clientset, informers, sharded workqueue, fleet
    scheduler, writeback limiter) over the in-process apiserver. A
    kubelet-simulator thread succeeds every created pod, so jobs flow
    queue → admit → gang → Done and release their slice to the next wave.
    Asserted budgets (the CI contract): every job reaches Done, p99
    reconcile latency, status-PUT count per job, and ZERO get/list RPCs
    over a steady-state reconcile wave of the whole fleet (PR 3's
    zero-read contract, at fleet scale)."""
    import threading

    from tpu_operator.apis.tpujob.v1alpha1.types import ControllerConfig
    from tpu_operator.client.fake import FakeClientset
    from tpu_operator.client.informer import SharedInformerFactory
    from tpu_operator.client.rest import Clientset, RestConfig
    from tpu_operator.controller.controller import Controller
    from tpu_operator.testing.apiserver import ApiServerHarness

    jobs = 384 if quick else 5000
    capacity = 32 if quick else 128
    shards = 4
    deadline_s = 180 if quick else 900

    backing = FakeClientset()
    with ApiServerHarness(clientset=backing) as srv:
        clientset = Clientset(RestConfig(host=srv.url, timeout=30.0))
        config = ControllerConfig(
            slice_inventory={FLEET_SLICE_KEY: capacity})
        # resync long: steady state must be watch-driven, not re-list-driven.
        factory = SharedInformerFactory(clientset, "default",
                                        resync_period=600.0)
        controller = Controller(clientset, factory, config, "default",
                                shards=shards, writeback_qps=200.0)
        clientset.rest.metrics = controller.metrics
        metrics = controller.metrics

        stop = threading.Event()
        runner = threading.Thread(target=controller.run, args=(shards, stop),
                                  daemon=True)
        runner.start()

        # Both simulators are WATCH consumers, not list pollers: at 5k
        # retained pods a 20 Hz list poll deepcopies the world under the
        # fake store's global lock and starves the apiserver it shares.
        # The kubelet is the testing/cluster.py machine in its instant
        # profile — every operator-created pod succeeds in one status
        # write, exactly the old hand-rolled closure's behavior.
        from tpu_operator.testing.cluster import FakeCluster

        cluster = FakeCluster(backing)
        cluster.start()
        job_watch = backing.tpujobs.watch("default")
        done_names: set = set()

        def done_tracker() -> None:
            for _event_type, obj in job_watch:
                if (obj.get("status") or {}).get("phase") == "Done":
                    done_names.add((obj.get("metadata") or {}).get("name"))

        tracker = threading.Thread(target=done_tracker, daemon=True)
        tracker.start()

        try:
            t0 = time.perf_counter()
            for i in range(jobs):
                backing.tpujobs.create(
                    "default",
                    _fleet_job(f"fl-{i:05d}", queue=("a", "b")[i % 2]))
            submitted_s = time.perf_counter() - t0

            end = time.monotonic() + deadline_s
            while time.monotonic() < end and len(done_names) < jobs:
                time.sleep(0.25)
            done = len(done_names)
            wall_s = time.perf_counter() - t0
            if done < jobs:
                phases: dict = {}
                for j in backing.tpujobs.list("default"):
                    p = (j.get("status") or {}).get("phase") or "None"
                    phases[p] = phases.get(p, 0) + 1
                counters = metrics.snapshot()
                lost = []
                for j in backing.tpujobs.list("default"):
                    if (j.get("status") or {}).get("phase"):
                        continue
                    name = j["metadata"]["name"]
                    key = f"default/{name}"
                    cached = controller.job_informer.store.get("default",
                                                               name)
                    q = controller.queue
                    shard = q.shard_for(key)
                    dirty = key in q.shards[shard]._dirty
                    lost.append(f"{name}(cached={cached is not None},"
                                f"shard={shard},dirty={dirty})")
                    if len(lost) >= 5:
                        break
                job_rpcs = {verb: metrics.counter_value(
                    "api_requests_total",
                    {"verb": verb, "resource": "TPUJob"})
                    for verb in ("list", "watch", "get")}
                raise RuntimeError(
                    f"fleet bench stalled: {done}/{jobs} Done after "
                    f"{deadline_s}s; phases={phases}; "
                    f"queue_len={len(controller.queue)}; "
                    f"reconciles={counters.get('reconcile_total')}; "
                    f"errors={counters.get('reconcile_errors_total')}; "
                    f"retries={counters.get('workqueue_retries_total')}; "
                    f"lost={lost}; "
                    f"cache_jobs={len(controller.job_informer.store.keys())}; "
                    f"job_rpcs={job_rpcs}; "
                    f"watchers={len(backing.tpujobs._watchers)}; "
                    f"scheduler={controller.scheduler.summary()}")

            # Steady-state read budget: requeue the WHOLE fleet and let it
            # drain — every reconcile must be served from cache (PR 3's
            # zero-read contract surviving 5k-job scale).
            reads_before = _fleet_reads(metrics)
            for i in range(jobs):
                controller.queue.add(f"default/fl-{i:05d}")
            drain_end = time.monotonic() + 60
            while time.monotonic() < drain_end and len(controller.queue):
                time.sleep(0.1)
            time.sleep(0.5)  # in-flight items past the queue-length check
            steady_reads = _fleet_reads(metrics) - reads_before
        finally:
            stop.set()
            cluster.stop()
            job_watch.stop()
            runner.join(timeout=10.0)
            tracker.join(timeout=5.0)

    puts = _fleet_status_puts(metrics)
    p99_bound, reconciles = _hist_quantile_bound(
        metrics, "reconcile_duration_seconds", 0.99)
    adm_p50, admissions = _hist_quantile_bound(
        metrics, "tpujob_admission_latency_seconds", 0.50)
    counters = metrics.snapshot()
    return [
        {
            "metric": f"fleet_{jobs}_jobs_to_done_wall_s",
            "value": round(wall_s, 1),
            "unit": "s",
            "jobs": jobs,
            "slice_capacity": capacity,
            "shards": shards,
            "submit_s": round(submitted_s, 2),
            "jobs_per_sec": round(jobs / wall_s, 1),
            "transport": "in-process apiserver over HTTP (REST clientset)",
        },
        {
            "metric": "fleet_reconcile_p99_ms",
            "value": (round(p99_bound * 1e3, 1)
                      if p99_bound not in (None, float("inf")) else None),
            "unit": "ms",
            "reconciles": reconciles,
            "budget_ms": 500.0,
            "note": "upper bound from fixed histogram buckets",
        },
        {
            "metric": "fleet_status_puts_per_job",
            "value": round(puts / jobs, 2),
            "unit": "puts/job",
            "total_puts": int(puts),
            "budget_per_job": 8.0,
        },
        {
            "metric": "fleet_steady_state_reads",
            "value": int(steady_reads),
            "unit": "reads",
            "wave": jobs,
            "budget": 0,
        },
        {
            "metric": "fleet_admission_latency_p50_s",
            "value": (round(adm_p50, 2)
                      if adm_p50 not in (None, float("inf")) else None),
            "unit": "s",
            "admissions": admissions,
            "preemptions": int(counters.get("tpujob_preemptions_total", 0)),
            "note": "upper bound from fixed histogram buckets",
        },
    ]


def _fleet_ok(rows: list) -> bool:
    """The CI contract (hack/verify.sh runs --fleet --quick): the whole
    fleet reaches Done (bench_fleet raises otherwise), p99 reconcile stays
    under budget, status PUTs stay within the per-job budget, and the
    steady-state reconcile wave issues zero read RPCs."""
    ok = True
    for row in rows:
        if row["metric"] == "fleet_reconcile_p99_ms":
            if row["value"] is None or row["value"] > row["budget_ms"]:
                print(f"FAIL: fleet reconcile p99 {row['value']} ms over "
                      f"budget {row['budget_ms']} ms", file=sys.stderr)
                ok = False
        if row["metric"] == "fleet_status_puts_per_job" \
                and row["value"] > row["budget_per_job"]:
            print(f"FAIL: {row['value']} status PUTs/job over budget "
                  f"{row['budget_per_job']}", file=sys.stderr)
            ok = False
        if row["metric"] == "fleet_steady_state_reads" and row["value"] != 0:
            print(f"FAIL: steady-state fleet wave issued {row['value']} "
                  f"read RPCs (budget: 0)", file=sys.stderr)
            ok = False
    return ok


# --- cooperative drain rows -----------------------------------------------------

def _drain_scenario():
    """A Running single-slice gang over the REAL TrainingJob machinery
    with an injected trainer clock. Returns (cs, controller, tj, clock);
    the caller must restore ``training._now``."""
    from tpu_operator.apis.tpujob.v1alpha1 import types as t
    from tpu_operator.client.fake import FakeClientset
    from tpu_operator.client.informer import SharedInformerFactory
    from tpu_operator.controller.controller import Controller
    from tpu_operator.trainer.training import TrainingJob
    from tpu_operator.util.util import format_rfc3339

    class _Clock:
        def __init__(self):
            self.t = 1_700_000_000.0

        def __call__(self):
            return format_rfc3339(self.t)

        def advance(self, dt):
            self.t += dt

    clock = _Clock()
    cs = FakeClientset()
    controller = Controller(cs, SharedInformerFactory(cs, resync_period=0),
                            heartbeat_persist_interval=0.0)
    controller.scheduler.update_inventory({FLEET_SLICE_KEY: 1})
    job_dict = _fleet_job("bench-drain", queue="default")
    job_dict["spec"]["drain"] = {"deadlineSeconds": 2,
                                 "resizeDebounceSeconds": 0}
    from tpu_operator.apis.tpujob.v1alpha1 import types as types_mod
    job = types_mod.TPUJob.from_dict(job_dict)
    cs.tpujobs.create("default", job.to_dict())
    tj = TrainingJob(cs, controller.recorder, job,
                     metrics=controller.metrics,
                     scheduler=controller.scheduler)
    controller.jobs["default/bench-drain"] = tj
    tj.reconcile()
    _drain_mark_pods(cs, {"running": {}})
    tj.reconcile()
    assert tj.job.status.phase == "Running", tj.job.status.phase
    return cs, controller, tj, clock


def _drain_mark_pods(cs, state, phase=None):
    phase = phase or ("Running" if "running" in state else "Failed")
    for pod in cs.pods.list("default"):
        if (pod.get("status") or {}).get("phase") in ("Failed", "Succeeded"):
            continue
        pod["status"] = {"phase": phase, "containerStatuses": [
            {"name": "tpu", "state": state}]}
        cs.pods.update("default", pod)


def bench_drain(quick: bool) -> list:
    """Cooperative-drain step-seconds accounting over the real
    controller/trainer machinery with an injected clock (no JAX, no
    sleeps). Three scenarios:

    - **cooperative**: a gang mid-checkpoint-interval (last durable save
      ``interval`` steps ago) is drained; the payload ACKs a boundary
      step, runs the verified save, exits planned. The ledger's
      ``lostSteps`` must price the restart at <= one checkpoint interval
      (the protocol's whole claim) — and in the simulated schedule, at
      zero.
    - **hard** (reference): the identical gang is preempted the old way;
      its restart discards every step since the last periodic save.
    - **deadline expiry**: a drain the payload never ACKs hard-kills at
      ``spec.drain.deadlineSeconds`` and the job still reaches Done.
    """
    from tpu_operator.trainer import training

    sec_per_step = 1.0
    interval_steps = 50 if quick else 200
    last_save = 1000
    now_step = last_save + interval_steps - 20  # mid-interval
    rows: list = []
    orig_now = training._now
    try:
        # Scenario 1: cooperative drain.
        cs, controller, tj, clock = _drain_scenario()
        training._now = clock
        controller.record_heartbeat("default", "bench-drain", {
            "time": clock(), "step": now_step, "attempt": 0,
            "processId": 0})
        tj.job.status.checkpoint = {"lastCheckpointStep": last_save}
        tj.request_drain("maintenance", "bench: planned restart")
        rid = tj.job.status.drain["id"]
        clock.advance(0.5)
        controller.record_heartbeat("default", "bench-drain", {
            "time": clock(), "step": now_step + 1, "attempt": 0,
            "processId": 0, "drainAck": {"id": rid, "step": now_step + 1}})
        # The gang-agreed verified save lands at the boundary step...
        tj.job.status.checkpoint = {"lastCheckpointStep": now_step + 1}
        clock.advance(0.5)
        # ...and every process exits EXIT_PLANNED (160).
        _drain_mark_pods(cs, {"terminated": {"exitCode": 160}})
        tj.reconcile()
        rec = tj.job.status.failures[-1]
        assert rec.kind == "planned", rec
        coop_lost = (rec.lost_steps or 0) * sec_per_step
        drain_hist = controller.metrics.histogram_snapshot(
            "job_drain_seconds",
            labels={"namespace": "default", "name": "bench-drain"})
        planned = controller.metrics.counter_value(
            "job_planned_restarts_total",
            labels={"namespace": "default", "name": "bench-drain",
                    "reason": "maintenance"})
        rows.append({"metric": "drain_coop_lost_step_seconds",
                     "value": coop_lost,
                     "budget_s": interval_steps * sec_per_step,
                     "interval_steps": interval_steps})
        rows.append({"metric": "drain_latency_seconds",
                     "value": (drain_hist or {}).get("sum"),
                     "observations": (drain_hist or {}).get("count")})
        rows.append({"metric": "drain_planned_restarts",
                     "value": planned})

        # Scenario 2: the hard-preemption reference on identical state.
        cs, controller, tj, clock = _drain_scenario()
        training._now = clock
        controller.record_heartbeat("default", "bench-drain", {
            "time": clock(), "step": now_step, "attempt": 0,
            "processId": 0})
        tj.job.status.checkpoint = {"lastCheckpointStep": last_save}
        _drain_mark_pods(cs, {"terminated": {"exitCode": 137}})
        tj.reconcile()
        rec = tj.job.status.failures[-1]
        assert rec.kind == "preemption", rec
        hard_lost = (rec.lost_steps or 0) * sec_per_step
        rows.append({"metric": "drain_hard_lost_step_seconds",
                     "value": hard_lost})

        # Scenario 3: deadline expiry still converges to Done.
        cs, controller, tj, clock = _drain_scenario()
        training._now = clock
        tj.request_drain("maintenance", "bench: wedged payload")
        clock.advance(3.0)  # past deadlineSeconds=2, no ACK, no exit
        tj.reconcile()
        expired = (tj.job.status.drain or {}).get("state") == "Expired"
        tj.reconcile()  # re-gang
        _drain_mark_pods(cs, {"running": {}})
        tj.reconcile()
        _drain_mark_pods(cs, {"terminated": {"exitCode": 0}},
                         phase="Succeeded")
        tj.reconcile()
        done = tj.job.status.phase == "Done"
        rows.append({"metric": "drain_deadline_expiry_done",
                     "value": 1.0 if (expired and done) else 0.0})
    finally:
        training._now = orig_now
    return rows


def _drain_ok(rows: list) -> bool:
    """The CI contract (hack/verify.sh runs --drain --quick): a
    cooperative drain costs at most one checkpoint interval of lost
    step-seconds (and never more than the hard-preemption reference),
    exactly one planned restart is billed with its latency observed, and
    a never-ACKed drain still reaches Done through the deadline."""
    ok = True
    by = {row["metric"]: row for row in rows}
    coop = by.get("drain_coop_lost_step_seconds", {})
    if coop.get("value") is None or coop["value"] > coop.get("budget_s", 0):
        print(f"FAIL: cooperative drain lost {coop.get('value')} "
              f"step-seconds, over the one-checkpoint-interval budget "
              f"{coop.get('budget_s')}", file=sys.stderr)
        ok = False
    hard = by.get("drain_hard_lost_step_seconds", {}).get("value")
    if hard is None or coop.get("value", 0) > hard:
        print(f"FAIL: cooperative drain ({coop.get('value')}) lost more "
              f"than the hard-preemption reference ({hard})",
              file=sys.stderr)
        ok = False
    if by.get("drain_planned_restarts", {}).get("value") != 1:
        print("FAIL: expected exactly one planned restart billed",
              file=sys.stderr)
        ok = False
    lat = by.get("drain_latency_seconds", {})
    if lat.get("observations") != 1 or not lat.get("value"):
        print("FAIL: job_drain_seconds not observed for the completed "
              "drain", file=sys.stderr)
        ok = False
    if by.get("drain_deadline_expiry_done", {}).get("value") != 1.0:
        print("FAIL: never-ACKed drain did not expire to Done",
              file=sys.stderr)
        ok = False
    return ok


def bench_churn(quick: bool) -> list:
    """Create-run-delete churn soak: batches of jobs cycled through the
    REAL operator (REST clientset over the in-process apiserver, kubelet
    sim succeeding pods, status server attached) with the joblife
    witness ON. Every job posts heartbeats (step/cadence/dataPlane) so
    the per-job state paths — heartbeat stash, gang cadence, goodput/
    prefetch/autotune series — are all populated before its deletion;
    each deletion reconcile then sweeps every `# per-job:` container and
    the metric registry for residue. The gate (ROADMAP item 5's "no
    leaked metric series and bounded memory" as an enforced budget):
    ZERO witness violations across >=200 create-delete cycles, a FLAT
    registry series count after the warmup batches, and bounded RSS
    growth."""
    import gc
    import threading

    from tpu_operator.apis.tpujob.v1alpha1.types import ControllerConfig
    from tpu_operator.client.fake import FakeClientset
    from tpu_operator.client.informer import SharedInformerFactory
    from tpu_operator.client.rest import Clientset, RestConfig
    from tpu_operator.controller.controller import Controller
    from tpu_operator.controller.statusserver import StatusServer
    from tpu_operator.testing.apiserver import ApiServerHarness
    from tpu_operator.util import joblife

    joblife.enable()
    joblife.reset()
    batch = 8
    batches = 27 if quick else 75   # 216 / 600 create-delete cycles
    capacity = 4                    # half of each batch parks Queued first
    warmup_batches = 2              # series/RSS baselines after this many
    rss_budget_mb = 48.0 if quick else 80.0
    batch_deadline_s = 30.0

    def rss_mb() -> float:
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return float(line.split()[1]) / 1024.0
        except OSError:
            pass
        return 0.0

    backing = FakeClientset()
    series_base = rss_base = None
    cycles = 0
    t0 = time.perf_counter()
    with ApiServerHarness(clientset=backing) as srv:
        clientset = Clientset(RestConfig(host=srv.url, timeout=30.0))
        config = ControllerConfig(
            slice_inventory={FLEET_SLICE_KEY: capacity})
        factory = SharedInformerFactory(clientset, "default",
                                        resync_period=600.0)
        controller = Controller(clientset, factory, config, "default",
                                shards=2)
        clientset.rest.metrics = controller.metrics
        metrics = controller.metrics
        status = StatusServer(0, controller=controller, metrics=metrics)
        status.start()

        stop = threading.Event()
        runner = threading.Thread(target=controller.run, args=(2, stop),
                                  daemon=True)
        runner.start()

        # testing/cluster.py's instant-profile kubelet (the same machine
        # --fleet and --cluster drive) succeeds every pod in one write.
        from tpu_operator.testing.cluster import FakeCluster

        cluster = FakeCluster(backing)
        cluster.start()

        def wait_until(cond, what: str) -> None:
            end = time.monotonic() + batch_deadline_s
            while time.monotonic() < end:
                if cond():
                    return
                time.sleep(0.02)
            phases: dict = {}
            for j in backing.tpujobs.list("default"):
                p = (j.get("status") or {}).get("phase") or "None"
                phases[p] = phases.get(p, 0) + 1
            raise RuntimeError(
                f"churn soak stalled waiting for {what}; phases={phases}; "
                f"scheduler={controller.scheduler.summary()}; "
                f"queue_len={len(controller.queue)}")

        try:
            for b in range(batches):
                names = [f"cj-{b:03d}-{i}" for i in range(batch)]
                for i, name in enumerate(names):
                    backing.tpujobs.create(
                        "default",
                        _fleet_job(name, queue=("a", "b")[i % 2]))

                def all_done() -> bool:
                    phases = {j["metadata"]["name"]:
                              (j.get("status") or {}).get("phase")
                              for j in backing.tpujobs.list("default")}
                    return all(phases.get(n) == "Done" for n in names)

                wait_until(all_done, f"batch {b} Done")
                # Populate the per-job telemetry state for one member:
                # process 0's full stream (heartbeat stash, goodput,
                # prefetch gauge, autotune counters) plus a process-1
                # cadence beat (gang-cadence map + straggler gauge).
                for pid in (0, 1):
                    ok, msg = status.record_heartbeat({
                        "namespace": "default", "name": names[0],
                        "processId": pid, "step": 10 + pid,
                        "stepTimeSeconds": 0.1, "loss": 1.0,
                        "stepTiming": {"steps": 10,
                                       "stepP95Seconds": 0.1,
                                       "stepLocalP95Seconds": 0.01},
                        "dataPlane": {"prefetchDepth": 2,
                                      "adjustments": {"prefetchUp": 1}},
                    })
                    if not ok:
                        raise RuntimeError(f"churn heartbeat refused: {msg}")
                for name in names:
                    backing.tpujobs.delete("default", name)
                wait_until(lambda: len(controller.jobs) == 0,
                           f"batch {b} deletion reconciles")
                wait_until(lambda: not any(
                    metrics.job_series("default", n) for n in names),
                    f"batch {b} metric prune")
                controller.run_gc_once()  # orphaned pods/services
                cycles += batch
                if joblife.violation_count():
                    break  # fail fast; the rows below carry the report
                if b + 1 == warmup_batches:
                    gc.collect()
                    series_base = metrics.series_count()
                    rss_base = rss_mb()
        finally:
            stop.set()
            cluster.stop()
            status.stop()
            runner.join(timeout=10.0)

    gc.collect()
    wall_s = time.perf_counter() - t0
    violations = joblife.violation_count()
    residual = joblife.total_entries()
    series_growth = (metrics.series_count() - series_base
                     if series_base is not None else None)
    rss_growth = (rss_mb() - rss_base if rss_base is not None else None)
    return [
        {
            "metric": "churn_create_delete_cycles",
            "value": cycles,
            "unit": "cycles",
            "batches": batches,
            "batch": batch,
            "slice_capacity": capacity,
            "wall_s": round(wall_s, 1),
            "transport": "in-process apiserver over HTTP (REST clientset)",
        },
        {
            "metric": "churn_joblife_violations",
            "value": violations,
            "unit": "violations",
            "budget": 0,
            "note": (joblife.report()[:2000] if violations else
                     "every deletion sweep came back clean"),
        },
        {
            "metric": "churn_joblife_residual_entries",
            "value": residual,
            "unit": "entries",
            "budget": 0,
            "counts": {k: v for k, v in joblife.counts().items() if v},
        },
        {
            "metric": "churn_metric_series_growth",
            "value": series_growth,
            "unit": "series",
            "budget": 0,
            "baseline_series": series_base,
        },
        {
            "metric": "churn_rss_growth_mb",
            "value": round(rss_growth, 1) if rss_growth is not None else None,
            "unit": "MB",
            "budget_mb": rss_budget_mb,
            "baseline_mb": round(rss_base, 1) if rss_base else None,
        },
    ]


def _churn_ok(rows: list) -> bool:
    """The CI contract (hack/verify.sh runs --churn --quick): >=200
    create-delete cycles with zero joblife violations, zero residual
    tracked entries, a flat registry series count, and RSS growth under
    budget — any miss exits nonzero."""
    ok = True
    for row in rows:
        metric, value = row["metric"], row["value"]
        if metric == "churn_create_delete_cycles" and value < 200:
            print(f"FAIL: churn soak ran only {value} cycles (>=200 "
                  f"required)", file=sys.stderr)
            ok = False
        if metric in ("churn_joblife_violations",
                      "churn_joblife_residual_entries") \
                and (value is None or value != 0):
            print(f"FAIL: {metric} = {value} (budget 0): "
                  f"{row.get('note') or row.get('counts')}",
                  file=sys.stderr)
            ok = False
        if metric == "churn_metric_series_growth" \
                and (value is None or value != 0):
            print(f"FAIL: /metrics series count grew by {value} across "
                  f"the churn soak (budget 0)", file=sys.stderr)
            ok = False
        if metric == "churn_rss_growth_mb" \
                and (value is None or value > row["budget_mb"]):
            print(f"FAIL: RSS grew {value} MB across the churn soak "
                  f"(budget {row['budget_mb']} MB)", file=sys.stderr)
            ok = False
    return ok


# --- kwok-style fake cluster: seeded storm soak ---------------------------------

def _cluster_job(name: str, queue: str) -> dict:
    """One 2-worker TPUJob gang on a v4 2x2x2 slice — 2 pods per job, the
    10k-pod / 5k-job soak shape."""
    from tpu_operator.apis.tpujob.v1alpha1 import types as t

    return t.TPUJob(
        metadata={"name": name, "namespace": "default"},
        spec=t.TPUJobSpec(
            replica_specs=[t.TPUReplicaSpec(
                replicas=2,
                template={"spec": {"containers": [
                    {"name": "tpu", "image": "img:latest",
                     "resources": {
                         "limits": {"cloud-tpus.google.com/v4": 4}}}],
                    "restartPolicy": "Never"}},
                tpu_replica_type=t.TPUReplicaType.WORKER)],
            runtime_id="clu1",
            tpu_topology="2x2x2",
            restart_backoff=t.RestartBackoffSpec(base_seconds=0),
            scheduling=t.SchedulingSpec(priority=0, queue=queue),
        ),
    ).to_dict()


def _hist_delta_quantile_bound(before, after, q: float):
    """Like :func:`_hist_quantile_bound`, but over the DELTA between two
    snapshots of the same histogram — the during-a-window quantile of a
    histogram that accumulates for the whole run (the storm-window p99)."""
    if not after:
        return None, 0
    prior = before or {"count": 0, "buckets": {}}
    count = after["count"] - prior.get("count", 0)
    if count <= 0:
        return None, 0
    target = q * count
    for bound, cum in after["buckets"].items():
        if cum - prior["buckets"].get(bound, 0) >= target:
            return (float("inf") if bound == "+Inf" else float(bound), count)
    return float("inf"), count


def bench_cluster(quick: bool, seed: int = 1234) -> list:
    """Degradation-asserting fleet soak over the kwok-style fake cluster
    (testing/cluster.py): the REAL operator — REST clientset behind a
    FlakyClientset, informers, sharded workqueue, fleet scheduler with
    node-DISCOVERED slice inventory — drives 2-pod gangs through fake
    node/kubelet state machines (scheduling latency, Running/Ready,
    heartbeats through the real status server) while a SEEDED
    StormController lands slice-preemption waves, node NotReady flaps
    inside the inventory-debounce window, an API-fault burst, a chaos
    pod-kill sweep, a slow-kubelet window and a node drain-and-return.
    The gate: after the storm the fleet must FULLY drain — every job
    Done, zero stuck Queued, preemptions actually happened, reconcile
    p99 bounded DURING the storm window, and after deleting everything:
    zero leaked pods, zero joblife violations/residue, a flat /metrics
    series count and bounded RSS growth. The whole storm schedule is a
    pure function of ``seed`` — a failing run replays bit-identically
    from its printed seed (docs/design.md)."""
    import gc
    import random
    import threading

    from tpu_operator.apis.tpujob.v1alpha1.types import ControllerConfig
    from tpu_operator.client.fake import FakeClientset
    from tpu_operator.client.informer import SharedInformerFactory
    from tpu_operator.client.rest import Clientset, RestConfig
    from tpu_operator.controller.chaos import ChaosMonkey, FlakyClientset
    from tpu_operator.controller.controller import Controller
    from tpu_operator.controller.statusserver import StatusServer
    from tpu_operator.testing.apiserver import ApiServerHarness
    from tpu_operator.testing.cluster import (FakeCluster, KubeletProfile,
                                              StormController, make_nodes)
    from tpu_operator.util import joblife

    joblife.enable()
    joblife.reset()
    jobs = 500 if quick else 5000          # x2 pods: ~1k / 10k pods
    node_count = 64 if quick else 256
    slices = 32 if quick else 128          # 2 hosts per slice
    # Oversubscribe discovered capacity (one slice per job at 2x2x2) so
    # warmup parks jobs Queued in BOTH queues: the first parking creates
    # the tpujob_queue_depth gauge series, which must exist before the
    # series baseline or the main run reads as metric growth.
    warm_jobs = slices + 8
    shards = 4
    deadline_s = 240 if quick else 900
    cleanup_deadline_s = 120 if quick else 300
    rss_budget_mb = 96.0 if quick else 128.0
    debounce_s = 1.0

    # >=3 required storm waves (slice preemption, node-flap window,
    # API-fault burst) plus a pod-kill sweep, a slow-kubelet window and a
    # drain-and-return. Offsets are seconds from storm start; flap
    # down-time sits INSIDE the inventory debounce window, so the
    # scheduler must absorb it without release/re-admit churn.
    if quick:
        waves = (
            (0.0, "preempt", {"count": max(1, slices // 4),
                              "sweeps": 5, "interval": 0.4}),
            (1.0, "pod_kill", {}),
            (1.6, "pod_kill", {}),
            (2.5, "flap", {"count": max(2, node_count // 10),
                           "down_seconds": 0.3}),
            (3.5, "api_fault", {"rate": 0.1, "seconds": 2.5}),
            (6.5, "slow_kubelet", {"scale": 3.0, "seconds": 2.5}),
            (9.5, "drain", {"down_seconds": 1.5}),
        )
    else:
        waves = (
            (0.0, "preempt", {"count": slices // 4,
                              "sweeps": 8, "interval": 0.5}),
            (3.0, "pod_kill", {}),
            (4.0, "pod_kill", {}),
            (6.0, "flap", {"count": node_count // 8,
                           "down_seconds": 0.4}),
            (10.0, "api_fault", {"rate": 0.1, "seconds": 6.0}),
            (17.0, "slow_kubelet", {"scale": 3.0, "seconds": 6.0}),
            (24.0, "drain", {"down_seconds": 2.0}),
            (27.0, "preempt", {"count": slices // 4,
                               "sweeps": 8, "interval": 0.5}),
        )

    def rss_mb() -> float:
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return float(line.split()[1]) / 1024.0
        except OSError:
            pass
        return 0.0

    def rss_mb_trimmed() -> float:
        # Return freed glibc arenas to the OS first: the gate is about
        # RETAINED memory (leaks), not allocator high-water residue from
        # the 10k-pod peak.
        gc.collect()
        try:
            import ctypes
            ctypes.CDLL("libc.so.6").malloc_trim(0)
        except Exception:  # noqa: BLE001 — non-glibc platforms
            pass
        return rss_mb()

    backing = FakeClientset()
    # No verb audit log under soak churn (see FakeClientset.record_actions).
    backing.record_actions = False
    series_base = rss_base = None
    with ApiServerHarness(clientset=backing) as srv:
        clientset = Clientset(RestConfig(host=srv.url, timeout=30.0))
        # Calm weather until the storm raises error_rate; seeded so a
        # replayed seed injects the identical fault sequence.
        flaky = FlakyClientset(clientset, error_rate=0.0,
                               rng=random.Random(seed + 1))
        config = ControllerConfig(discover_slice_inventory=True,
                                  node_debounce_seconds=debounce_s)
        factory = SharedInformerFactory(flaky, "default",
                                        resync_period=600.0)
        controller = Controller(flaky, factory, config, "default",
                                shards=shards, writeback_qps=200.0)
        clientset.rest.metrics = controller.metrics
        metrics = controller.metrics
        flaky.metrics = metrics
        status = StatusServer(0, controller=controller, metrics=metrics)
        status.start()

        stop = threading.Event()
        runner = threading.Thread(target=controller.run,
                                  args=(shards, stop), daemon=True)
        runner.start()

        cluster = FakeCluster(
            backing,
            nodes=tuple(make_nodes(node_count, slices=slices)),
            profile=KubeletProfile(create_latency=0.02, run_seconds=0.25,
                                   heartbeat_interval=5.0),
            status_server=status)
        cluster.start()

        # The kill sweep goes through an UNWRAPPED clientset: the monkey
        # is weather, not the operator, and must not eat injected faults.
        monkey = ChaosMonkey(Clientset(RestConfig(host=srv.url,
                                                  timeout=30.0)),
                             "default", level=2,
                             rng=random.Random(seed + 2), metrics=metrics)
        storm = StormController(cluster, seed, waves, flaky=flaky,
                                monkey=monkey)

        job_watch = backing.tpujobs.watch("default")
        done_names: set = set()

        def done_tracker() -> None:
            for _event_type, obj in job_watch:
                if (obj.get("status") or {}).get("phase") == "Done":
                    name = (obj.get("metadata") or {}).get("name")
                    if name:
                        # Interning frees the decoded copy — see the
                        # pre-baseline cl_names comment.
                        done_names.add(sys.intern(name))

        tracker = threading.Thread(target=done_tracker, daemon=True)
        tracker.start()

        def series_idents() -> set:
            # Series identities (name+labels, no values) — when the
            # flat-series gate trips, the diff NAMES the leak.
            return {line.rsplit(" ", 1)[0]
                    for line in metrics.render_lines()
                    if not line.startswith("#")}

        def wait_until(cond, what: str, budget_s: float) -> None:
            end = time.monotonic() + budget_s
            while time.monotonic() < end:
                if cond():
                    return
                time.sleep(0.05)
            phases: dict = {}
            for j in backing.tpujobs.list("default"):
                ph = (j.get("status") or {}).get("phase") or "None"
                phases[ph] = phases.get(ph, 0) + 1
            raise RuntimeError(
                f"cluster soak stalled waiting for {what} (seed={seed}): "
                f"phases={phases}; queue_len={len(controller.queue)}; "
                f"scheduler={controller.scheduler.summary()}; "
                f"tracked_pods={cluster.tracked_pods()}; "
                f"done={len(done_names)}")

        try:
            # -- warmup: touch every metric family the storm will touch
            # (preemption restarts, chaos kills, injected API errors,
            # node flaps, heartbeats), then delete and baseline — the
            # flat-series gate compares against THIS count.
            #
            # Admission gates on discovered inventory, and an EMPTY
            # inventory admits everything — so wait for node discovery
            # first, or the warmup sails through without ever parking
            # Queued. Then shrink the world to ~2 slices (all but two
            # nodes NotReady) BEFORE creating the warm fleet: parking
            # must be deterministic, and at full capacity the 0.3 s warm
            # jobs drain faster than admissions trickle in, so the queue
            # never backs up and the tpujob_queue_depth{queue} gauge
            # series would first appear mid-soak — as bogus growth.
            wait_until(lambda: controller.scheduler.summary()["inventory"],
                       "slice inventory discovery", 30)
            parked_nodes = cluster.node_names()[2:]
            for node in parked_nodes:
                cluster.set_node_ready(node, False)
            wait_until(lambda: sum(
                e["capacity"] for e in
                controller.scheduler.summary()["inventory"].values()) <= 2,
                "inventory shrink for warm parking", 30)
            warm_names = [f"cw-{i:03d}" for i in range(warm_jobs)]
            for i, name in enumerate(warm_names):
                backing.tpujobs.create("default",
                                       _cluster_job(name,
                                                    ("a", "b")[i % 2]))

            def queue_gauges_exist() -> bool:
                lines = metrics.render_lines()
                return all(any(f'tpujob_queue_depth{{queue="{q}"}}' in line
                               for line in lines) for q in ("a", "b"))

            wait_until(queue_gauges_exist, "warm jobs parked in both queues",
                       60)
            for node in parked_nodes:
                cluster.set_node_ready(node, True)

            def some_running() -> bool:
                return sum(
                    1 for p in backing.pods.list("default")
                    if (p.get("status") or {}).get("phase") == "Running"
                ) >= 2

            wait_until(some_running, "warmup pods Running", 60)
            cluster.preempt_slices(cluster.slice_ids())
            monkey.kill_once()
            flaky.error_rate = 0.3
            time.sleep(0.3)
            flaky.error_rate = 0.0
            first_node = cluster.node_names()[0]
            cluster.set_node_ready(first_node, False)
            time.sleep(0.2)
            cluster.set_node_ready(first_node, True)
            wait_until(lambda: len(done_names) >= warm_jobs,
                       "warmup jobs Done", 90)

            # The Event-AGGREGATION path (get+update on a repeated
            # stable-message event, e.g. a second Queued after a storm
            # preemption re-queues a job) creates two api_requests_total
            # series the first time it runs — touch it now so the
            # flat-series gate's baseline already holds them.
            class _WarmRef:
                namespace, name = "default", warm_names[0]
                metadata = {"name": warm_names[0], "namespace": "default"}

            for _ in range(2):
                controller.recorder.event(_WarmRef(), "Normal",
                                          "BenchWarmup",
                                          "series-baseline warmup")
            for name in warm_names:
                backing.tpujobs.delete("default", name)
            wait_until(lambda: len(controller.jobs) == 0,
                       "warmup deletion reconciles", 60)
            wait_until(lambda: not any(
                metrics.job_series("default", n) for n in warm_names),
                "warmup metric prune", 60)
            controller.run_gc_once()
            gc.collect()
            # Pre-intern every job name BEFORE the RSS baseline: the
            # done-tracker otherwise retains one JSON-decoded copy of
            # each name, allocated mid-churn — and a single small
            # survivor pins its whole pymalloc pool/arena, so 5k of
            # them scattered across the soak's allocation peak read as
            # hundreds of MB of "growth" that is fragmentation, not a
            # leak. Interned here, the survivors all live in
            # baseline-side arenas and the decoded copies get freed.
            cl_names = [sys.intern(f"cl-{i:05d}") for i in range(jobs)]
            series_base = metrics.series_count()
            series_ident_base = series_idents()
            rss_base = rss_mb_trimmed()
            warm_done = len(done_names)

            # -- the soak: a ROLLING fleet. A feeder keeps at most
            # max_inflight jobs live (a real fleet is queue-fed, not a
            # single 5k-job thundering herd) and a reaper deletes jobs
            # as they finish — per-job state, metric series and pods
            # must recycle UNDER load, not only in a quiet teardown.
            # Cumulative scale is the headline (jobs x 2 pods each);
            # bounding the live set also keeps the RSS gate about
            # operator retention instead of the allocator's high-water
            # mark from holding every job object + 10k pods at once.
            max_inflight = 2 * slices
            submitted = 0
            reaped: set = set()
            feed_done = threading.Event()

            def cl_done() -> int:
                return len(done_names) - warm_done

            def feeder() -> None:
                nonlocal submitted
                while submitted < jobs and not stop.is_set():
                    if submitted - cl_done() >= max_inflight:
                        time.sleep(0.02)
                        continue
                    backing.tpujobs.create(
                        "default",
                        _cluster_job(cl_names[submitted],
                                     ("a", "b")[submitted % 2]))
                    submitted += 1
                feed_done.set()

            def ttl_fixture_state() -> None:
                # Real apiservers TTL Events out (default 1 h) and keep
                # no verb audit log; the fake store keeps both forever,
                # which would read as soak RSS growth. Emulate the TTL
                # continuously so the RSS gate measures operator
                # retention, not fixture bookkeeping.
                events = backing.events.list("default")
                if len(events) > 512:
                    for ev in events[:len(events) - 512]:
                        try:
                            backing.events.delete(
                                "default",
                                (ev.get("metadata") or {}).get("name", ""))
                        except Exception:  # noqa: BLE001 - already TTL'd
                            pass
                backing.clear_actions()

            def reaper() -> None:
                try:
                    import ctypes
                    libc = ctypes.CDLL("libc.so.6")
                except Exception:  # noqa: BLE001 — non-glibc platforms
                    libc = None
                passes = 0
                while not stop.is_set():
                    for name in done_names.copy() - reaped:
                        reaped.add(name)
                        if not (name or "").startswith("cl-"):
                            continue
                        try:
                            backing.tpujobs.delete("default", name)
                        except Exception:  # noqa: BLE001 - already gone
                            pass
                    passes += 1
                    if passes % 20 == 0:
                        # ~1 Hz: the TTL deepcopies the event list, and
                        # malloc_trim returns freed glibc arenas while
                        # the soak is still running — both too heavy
                        # for every 50 ms pass.
                        ttl_fixture_state()
                        if libc is not None:
                            libc.malloc_trim(0)
                    if feed_done.is_set() and cl_done() >= jobs:
                        return
                    time.sleep(0.05)

            t0 = time.perf_counter()
            feed_thread = threading.Thread(target=feeder, daemon=True)
            reap_thread = threading.Thread(target=reaper, daemon=True)
            feed_thread.start()
            reap_thread.start()
            wait_until(lambda: cl_done() >= max(1, jobs // 20),
                       "the fleet to be mid-flight", deadline_s)

            preempt_before = metrics.snapshot().get(
                "tpujob_preemptions_total", 0)
            hist_before = metrics.histogram_snapshot(
                "reconcile_duration_seconds")
            storm.run()  # blocking: the realized window is storm.window
            hist_after = metrics.histogram_snapshot(
                "reconcile_duration_seconds")
            storm_s = storm.window[1] - storm.window[0]

            wait_until(lambda: cl_done() >= jobs,
                       "all jobs Done after the storm", deadline_s)
            wall_s = time.perf_counter() - t0
            drain_after_storm_s = time.monotonic() - storm.window[1]
            stuck_queued = controller.scheduler.summary()["pending"]
            evictions = metrics.snapshot().get(
                "tpujob_preemptions_total", 0) - preempt_before

            # -- teardown: delete whatever the rolling reaper has not
            # reached yet; the lifecycle gates below (leaked pods,
            # joblife residue, series flatness, RSS) all measure THIS
            # end state.
            feed_thread.join(timeout=10.0)
            reap_thread.join(timeout=10.0)
            for name in cl_names:
                if name in reaped:
                    continue
                try:
                    backing.tpujobs.delete("default", name)
                except Exception:  # noqa: BLE001 - already gone
                    pass
            wait_until(lambda: len(controller.jobs) == 0,
                       "deletion reconciles", cleanup_deadline_s)
            # Final full pass of the fixture TTL (the in-flight reaper
            # keeps a 512-event tail; the baseline was taken empty).
            for ev in backing.events.list("default"):
                try:
                    backing.events.delete(
                        "default", (ev.get("metadata") or {}).get("name", ""))
                except Exception:  # noqa: BLE001 - already TTL'd
                    pass
            backing.clear_actions()
            end = time.monotonic() + cleanup_deadline_s
            while time.monotonic() < end \
                    and metrics.series_count() > series_base:
                time.sleep(0.1)
            controller.run_gc_once()
            leaked_pods = len(backing.pods.list("default"))
            new_series = sorted(series_idents() - series_ident_base)
        finally:
            stop.set()
            cluster.stop()
            status.stop()
            job_watch.stop()
            runner.join(timeout=10.0)
            tracker.join(timeout=5.0)

    gc.collect()
    violations = joblife.violation_count()
    residual = joblife.total_entries()
    series_growth = (metrics.series_count() - series_base
                     if series_base is not None else None)
    rss_growth = (rss_mb_trimmed() - rss_base
                  if rss_base is not None else None)
    p99_bound, storm_reconciles = _hist_delta_quantile_bound(
        hist_before, hist_after, 0.99)
    return [
        {
            "metric": f"cluster_{jobs}_jobs_to_done_wall_s",
            "value": round(wall_s, 1),
            "unit": "s",
            "jobs": jobs,
            "pods": jobs * 2,
            "nodes": node_count,
            "slices": slices,
            "shards": shards,
            "seed": seed,
            "max_inflight_jobs": 2 * slices,
            "storm_events": len(storm.plan()),
            "storm_window_s": round(storm_s, 1),
            "transport": "in-process apiserver over HTTP "
                         "(FlakyClientset-wrapped REST clientset)",
        },
        {
            "metric": "cluster_drain_after_storm_s",
            "value": round(drain_after_storm_s, 1),
            "unit": "s",
            "note": "last storm event -> every job Done",
        },
        {
            "metric": "cluster_storm_reconcile_p99_ms",
            "value": (round(p99_bound * 1e3, 1)
                      if p99_bound not in (None, float("inf")) else None),
            "unit": "ms",
            "reconciles_in_window": storm_reconciles,
            "budget_ms": 500.0,
            "note": "upper bound from fixed histogram buckets, "
                    "DURING the storm window only",
        },
        {
            "metric": "cluster_storm_preempted_pods",
            "value": int(storm.stats["preempted_pods"]),
            "unit": "pods",
            "minimum": 1,
            "killed_pods": int(storm.stats["killed_pods"]),
            "drained_pods": int(storm.stats["drained_pods"]),
            "scheduler_evictions": int(evictions),
            "note": "the storm must actually disrupt; zero means the "
                    "waves missed the fleet",
        },
        {
            "metric": "cluster_leaked_pods",
            "value": leaked_pods,
            "unit": "pods",
            "budget": 0,
        },
        {
            "metric": "cluster_stuck_queued",
            "value": stuck_queued,
            "unit": "jobs",
            "budget": 0,
        },
        {
            "metric": "cluster_joblife_violations",
            "value": violations,
            "unit": "violations",
            "budget": 0,
            "note": (joblife.report()[:2000] if violations else
                     "every deletion sweep came back clean"),
        },
        {
            "metric": "cluster_joblife_residual_entries",
            "value": residual,
            "unit": "entries",
            "budget": 0,
            "counts": {k: v for k, v in joblife.counts().items() if v},
        },
        {
            "metric": "cluster_metric_series_growth",
            "value": series_growth,
            "unit": "series",
            "budget": 0,
            "baseline_series": series_base,
            "new_series": new_series[:8],
        },
        {
            "metric": "cluster_rss_growth_mb",
            "value": round(rss_growth, 1) if rss_growth is not None else None,
            "unit": "MB",
            "budget_mb": rss_budget_mb,
            "baseline_mb": round(rss_base, 1) if rss_base else None,
        },
    ]


def _cluster_ok(rows: list) -> bool:
    """The CI contract (hack/verify.sh runs --cluster --quick): the storm
    actually disrupted the fleet, reconcile p99 stayed bounded DURING the
    storm, and the fleet fully drained — zero leaked pods, zero stuck
    Queued, zero joblife violations/residue, flat series count, bounded
    RSS. Any miss exits nonzero (bench_cluster raises on a stall)."""
    ok = True
    for row in rows:
        metric, value = row["metric"], row["value"]
        if metric == "cluster_storm_reconcile_p99_ms" \
                and (value is None or value > row["budget_ms"]):
            print(f"FAIL: during-storm reconcile p99 {value} ms over "
                  f"budget {row['budget_ms']} ms", file=sys.stderr)
            ok = False
        if metric == "cluster_storm_preempted_pods" \
                and value < row["minimum"]:
            print("FAIL: the storm preempted zero pods — the soak "
                  "asserted nothing", file=sys.stderr)
            ok = False
        if metric in ("cluster_leaked_pods", "cluster_stuck_queued",
                      "cluster_joblife_violations",
                      "cluster_joblife_residual_entries",
                      "cluster_metric_series_growth") \
                and (value is None or value != 0):
            print(f"FAIL: {metric} = {value} (budget 0): "
                  f"{row.get('note') or row.get('counts') or ''}",
                  file=sys.stderr)
            ok = False
        if metric == "cluster_rss_growth_mb" \
                and (value is None or value > row["budget_mb"]):
            print(f"FAIL: RSS grew {value} MB across the cluster soak "
                  f"(budget {row['budget_mb']} MB)", file=sys.stderr)
            ok = False
    return ok


# --- checkpoint durability micro-rows ------------------------------------------

def _ckpt_state(size_mb: float):
    import jax.numpy as jnp

    n = max(1, int(size_mb * (1 << 20)) // 4)
    return {"step": jnp.int32(0), "w": jnp.arange(n, dtype=jnp.float32)}


def bench_checkpoint_save_restore(size_mb: float, quick: bool) -> list:
    """Verified-save and restore latency at one state size. Save cost is
    save + commit + verification (manifest write with per-file sha256) —
    the full durable path, not just the async submit; restore is the
    fresh-process resume path (manager init amortized out)."""
    import shutil
    import tempfile

    from tpu_operator.payload import checkpoint as ckpt_mod

    windows = 2 if quick else 5
    state = _ckpt_state(size_mb)
    save_times, verify_times, restore_times = [], [], []
    for w in range(windows):
        d = tempfile.mkdtemp(prefix="bench-ckpt-")
        try:
            ck = ckpt_mod.Checkpointer(d, save_every=1)
            t0 = time.perf_counter()
            ck.maybe_save(w + 1, state)
            t_submit = time.perf_counter()
            ck.flush()  # commit + verify + manifest
            save_times.append((time.perf_counter() - t0) * 1e3)
            verify_times.append((time.perf_counter() - t_submit) * 1e3)
            ck.close()

            reader = ckpt_mod.Checkpointer(d, save_every=1)
            t0 = time.perf_counter()
            _restored, start = reader.restore(state)
            restore_times.append((time.perf_counter() - t0) * 1e3)
            reader.close()
            assert start == w + 1
        finally:
            shutil.rmtree(d, ignore_errors=True)
    for t in (save_times, verify_times, restore_times):
        t.sort()
    mid = len(save_times) // 2
    return [
        {
            "metric": "checkpoint_save_verified_ms",
            "value": round(save_times[mid], 1),
            "unit": "ms",
            "state_mb": size_mb,
            "flush_ms": round(verify_times[mid], 1),
            "windows": windows,
        },
        {
            "metric": "checkpoint_restore_ms",
            "value": round(restore_times[mid], 1),
            "unit": "ms",
            "state_mb": size_mb,
            "windows": windows,
        },
    ]


def bench_checkpoint_fallback_scan(quick: bool) -> dict:
    """Cost of the corrupt-latest walk-back: K newest steps are corrupted,
    restore must quarantine each and land on the newest valid one. This is
    the recovery-path tax a restart pays when storage went bad — it bounds
    how much worse a dirty resume is than a clean one."""
    import shutil
    import tempfile

    from tpu_operator.payload import checkpoint as ckpt_mod

    windows = 2 if quick else 5
    corrupt = 3
    size_mb = 0.25 if quick else 1.0
    state = _ckpt_state(size_mb)
    times = []
    for _w in range(windows):
        d = tempfile.mkdtemp(prefix="bench-ckpt-fb-")
        try:
            ck = ckpt_mod.Checkpointer(d, save_every=1, max_to_keep=corrupt + 2)
            for s in range(1, corrupt + 2):
                ck.maybe_save(s, state)
            ck.close()
            for s in range(2, corrupt + 2):  # corrupt the newest `corrupt`
                step_dir = os.path.join(d, str(s))
                victim = sorted(
                    os.path.join(root, fn)
                    for root, _dirs, files in os.walk(step_dir)
                    for fn in files if fn != ckpt_mod.MANIFEST_NAME)[-1]
                with open(victim, "r+b") as f:
                    f.write(b"\xde\xad\xbe\xef")
            reader = ckpt_mod.Checkpointer(d, save_every=1)
            t0 = time.perf_counter()
            _restored, start = reader.restore(state)
            times.append((time.perf_counter() - t0) * 1e3)
            reader.close()
            assert start == 1, start
            assert reader.restore_fallbacks == corrupt
        finally:
            shutil.rmtree(d, ignore_errors=True)
    times.sort()
    return {
        "metric": "checkpoint_fallback_scan_ms",
        "value": round(times[len(times) // 2], 1),
        "unit": "ms",
        "corrupt_steps_walked": corrupt,
        "state_mb": size_mb,
        "windows": windows,
    }


def bench_checkpoint(quick: bool) -> list:
    """The --checkpoint micro-section: save/restore latency vs state size
    plus the fallback-scan cost. CPU-hostable (orbax I/O is host-side)."""
    rows = []
    for size_mb in ((0.25,) if quick else (1.0, 16.0)):
        rows.extend(bench_checkpoint_save_restore(size_mb, quick))
    rows.append(bench_checkpoint_fallback_scan(quick))
    return rows


# --- data-plane flight recorder overhead ----------------------------------------

def bench_steptrace(quick: bool) -> list:
    """The --steptrace guard: the flight recorder's cost on the steady
    step path must be noise. Two arms run the SAME loop body over the
    same pre-staged batches — recorder off (production loop shape: no
    per-step fence, window fenced by a device_get like every other row)
    vs recorder on (per-phase laps + the per-step ``block_until_ready``
    COMPUTE fence) — in INTERLEAVED windows, so clock drift and host
    noise land on both arms equally. Budget: recorder-on median per-step
    time within 1% of recorder-off, with a 50 µs absolute floor (the
    recorder's cost is constant per step — a handful of clock reads —
    while the baseline shrinks with the bench shape; at production step
    times the relative budget is the binding one)."""
    import jax

    from tpu_operator.payload import cifar, data as data_mod
    from tpu_operator.payload import heartbeat as heartbeat_mod
    from tpu_operator.payload import steptrace as steptrace_mod

    # Small batch, many steps per window: the recorder's cost is constant
    # per STEP, so more steps per window averages host noise down while
    # keeping the per-step time in the few-ms regime where the 1% budget
    # and the 50 µs floor agree.
    if quick:
        batch, steps, windows = 32, 60, 5
        cfg = ["--blocks", "1", "--widths", "8", "8", "8"]
    else:
        batch, steps, windows = 64, 120, 7
        cfg = ["--blocks", "1", "--widths", "8", "16", "32"]
    cargs = cifar.parse_args(["--batch", str(batch), *cfg])
    mesh, _model, state, step_fn, batches = cifar.build(cargs)
    pregen = [data_mod.put_global_batch(mesh, *b)
              for b in itertools.islice(batches, 4)]
    cycled = itertools.cycle(pregen)

    def run_window(rec):
        nonlocal state
        t0 = time.perf_counter()
        metrics = fence = None
        for i in range(steps):
            if rec is not None:
                rec.begin(i)
            args = next(cycled)
            if rec is not None:
                rec.lap(steptrace_mod.DATA)
            state, metrics = step_fn(state, *args)
            if rec is not None:
                # One-step-deferred COMPUTE fence, exactly as the
                # production loop runs it (train.train_loop): dispatch
                # pipelining is preserved; a same-step fence measured
                # 1-3% loss right here, which is what this guard exists
                # to catch.
                rec.lap(steptrace_mod.DISPATCH)
                if fence is not None:
                    jax.block_until_ready(fence)
                rec.lap(steptrace_mod.COMPUTE)
                fence = metrics
                rec.lap(steptrace_mod.HOST)
                rec.commit()
                # The idle profile-directive poll, exactly as the
                # production loop runs it after every commit
                # (train.train_loop): the on-demand capture path must
                # cost nothing while no directive is pending, so its
                # idle cost is measured inside the same ≤1% budget as
                # the recorder.
                take = getattr(idle_hb, "take_profile_directive", None)
                if take is not None and take():
                    raise AssertionError("idle reporter yielded a directive")
        jax.device_get(metrics["loss"])
        return (time.perf_counter() - t0) / steps

    # Warmup (compile) outside any timed window.
    for _ in range(3):
        state, metrics = step_fn(state, *next(cycled))
    jax.device_get(metrics["loss"])

    # A REAL reporter (never started — no beats, no sockets) so the
    # idle poll exercises the production take_profile_directive path.
    idle_hb = heartbeat_mod.HeartbeatReporter(
        "http://bench.invalid", "bench", poster=lambda *_a: None)
    recorder = steptrace_mod.StepRecorder(capacity=1024)
    off_times, on_times = [], []
    for _ in range(windows):
        off_times.append(run_window(None))
        on_times.append(run_window(recorder))
    # Min of PAIRWISE deltas, not a median-vs-median comparison: this is
    # an overhead guard on a shared CI host whose contention bursts dwarf
    # the µs being measured. A real recorder regression is present in
    # EVERY adjacent off/on pair; a contention burst is absent from at
    # least one — so the smallest per-pair delta isolates the systematic
    # cost (median gates flaked at several percent right here).
    off = min(off_times)
    deltas = [on_t - off_t for off_t, on_t in zip(off_times, on_times)]
    # A negative min-delta means a burst hit an off-window harder than
    # any on-window — i.e. the overhead is below the noise floor. Clamp
    # the headline at 0 rather than report a nonsense negative cost.
    overhead = max(0.0, min(deltas))
    on = off + overhead
    overhead_pct = 100.0 * overhead / off
    # The recorder's own digest must be coherent: every phase present,
    # whole-step p50 within the timed window's ballpark.
    summary = recorder.summary()
    assert summary is not None and summary["steps"] == windows * steps
    assert {"dataWait", "dispatch", "compute", "host"} \
        <= set(summary["phases"]), summary
    return [{
        "metric": "steptrace_overhead",
        "off_step_ms": round(off * 1e3, 4),
        "on_step_ms": round(on * 1e3, 4),
        "overhead_pct": round(overhead_pct, 2),
        "overhead_us_per_step": round((on - off) * 1e6, 2),
        "digest_step_p50_ms": round(summary["stepP50Seconds"] * 1e3, 4),
        "windows": windows,
        "unit": "pct",
        "value": round(overhead_pct, 2),
    }]


def _steptrace_ok(rows: list) -> bool:
    (row,) = rows
    over_pct = row["overhead_pct"]
    over_abs = (row["on_step_ms"] - row["off_step_ms"]) / 1e3
    if over_pct <= 1.0 or over_abs <= 50e-6:
        return True
    print(f"steptrace budget EXCEEDED: recorder-on step "
          f"{row['on_step_ms']} ms vs off {row['off_step_ms']} ms "
          f"({over_pct:.2f}% > 1% and {over_abs * 1e6:.1f} µs > 50 µs)",
          file=sys.stderr)
    return False


# --- self-tuning data plane rows ------------------------------------------------

def bench_dataplane(quick: bool) -> list:
    """The --dataplane gate, three rows:

    1. ``dataplane_autotune_convergence`` — the REAL controller
       (payload/autotune.py) drives a deterministic plant where the DATA
       wait shrinks as prefetch depth covers the host's generation burst
       while every depth unit costs fixed per-step host work, so the
       plant has an interior optimum. A static sweep over the full depth
       range finds the best static step time; the controller starts at
       minDepth and must settle within 5% of it inside the window
       budget. The plant is modeled (no sleeps): the row asserts the
       CONTROLLER's convergence property, which timing noise on a shared
       CI host would otherwise dominate; the loop rows below measure the
       real step path.

    2. ``dataplane_async_host_shave`` — the same CPU step loop the
       steptrace guard uses, recorder ON in both arms, every step
       posting a heartbeat through a poster that costs ~1 ms (a status
       server one POST-timeout hop away). Sync arm: the POST rides the
       step thread and lands in the recorder's HOST phase. Async arm:
       the AsyncHost worker pays it, the step thread pays an enqueue.
       The measured HOST-phase p50 must shave by at least half.

    3. ``dataplane_overhead`` — the PR 9 budget, extended: baseline arm
       is recorder OFF + inert runtime (the production loop shape);
       loaded arm is recorder ON + the autotune controller attached as
       the commit observer (float adds per step, one window evaluation
       per ``window_steps``). Interleaved windows, min-of-pairwise-delta
       (the steptrace guard's method, same rationale), budget ≤ 1% with
       the 50 µs absolute floor.
    """
    from tpu_operator.payload import autotune as autotune_mod
    from tpu_operator.payload import heartbeat as heartbeat_mod
    from tpu_operator.payload import steptrace as steptrace_mod

    rows = []

    # -- row 1: convergence vs the best static depth --------------------------
    min_depth, max_depth, window = 1, 6, 16
    compute_s, burst_s, cover_s, cost_s = 0.010, 0.006, 0.002, 0.0005

    def plant(depth: int) -> dict:
        # DATA wait: the host generation burst minus what the in-flight
        # window hides; each depth unit costs fixed host work that lands
        # device-side (placement/dispatch), i.e. outside the residue the
        # controller can see — the interior optimum a greedy
        # depth-always-helps heuristic would overshoot.
        data = max(0.0, burst_s - cover_s * (depth - min_depth))
        other = compute_s + cost_s * (depth - min_depth)
        return {"seconds": data + other, steptrace_mod.DATA: data,
                steptrace_mod.COMPUTE: other}

    static_times = {d: plant(d)["seconds"]
                    for d in range(min_depth, max_depth + 1)}
    best_depth = min(static_times, key=static_times.get)
    control = autotune_mod.PrefetchControl(min_depth)
    controller = autotune_mod.DataPlaneController(
        control, min_depth=min_depth, max_depth=max_depth,
        window_steps=window)
    # Budget: one climb needs a change window + a verdict window, so the
    # worst case is 2x the depth range, plus settle margin. The loop
    # deliberately OVERRUNS the budget: a controller still flapping at
    # the boundary shows up as settled_at > budget_windows in the gate,
    # instead of being clamped to the budget by loop construction.
    budget_windows = 2 * (max_depth - min_depth) + 4
    settled_at = 0
    for w in range(budget_windows + 4):
        before = control.depth
        for _ in range(window):
            controller.on_step(plant(control.depth))
        if control.depth != before:
            settled_at = w + 1
    achieved = static_times[control.depth]
    best = static_times[best_depth]
    rows.append({
        "metric": "dataplane_autotune_convergence",
        "converged_depth": control.depth,
        "best_static_depth": best_depth,
        "achieved_step_ms": round(achieved * 1e3, 4),
        "best_static_step_ms": round(best * 1e3, 4),
        "within_pct": round(100.0 * (achieved / best - 1.0), 2),
        "windows_to_settle": settled_at,
        "budget_windows": budget_windows,
        "adjustments": controller.adjustments(),
        "unit": "pct",
        "value": round(100.0 * (achieved / best - 1.0), 2),
    })

    # -- shared CPU step loop for rows 2 + 3 ----------------------------------
    import jax

    from tpu_operator.payload import cifar, data as data_mod

    if quick:
        batch, steps, windows = 32, 60, 5
        cfg = ["--blocks", "1", "--widths", "8", "8", "8"]
    else:
        batch, steps, windows = 64, 120, 7
        cfg = ["--blocks", "1", "--widths", "8", "16", "32"]
    cargs = cifar.parse_args(["--batch", str(batch), *cfg])
    mesh, _model, state, step_fn, batches = cifar.build(cargs)
    pregen = [data_mod.put_global_batch(mesh, *b)
              for b in itertools.islice(batches, 4)]
    cycled = itertools.cycle(pregen)

    def run_window(rec, on_host=None):
        nonlocal state
        t0 = time.perf_counter()
        metrics = fence = None
        for i in range(steps):
            if rec is not None:
                rec.begin(i)
            args = next(cycled)
            if rec is not None:
                rec.lap(steptrace_mod.DATA)
            state, metrics = step_fn(state, *args)
            if rec is not None:
                rec.lap(steptrace_mod.DISPATCH)
                if fence is not None:
                    jax.block_until_ready(fence)
                rec.lap(steptrace_mod.COMPUTE)
                fence = metrics
                if on_host is not None:
                    on_host(i)
                rec.lap(steptrace_mod.HOST)
                rec.commit()
        jax.device_get(metrics["loss"])
        return (time.perf_counter() - t0) / steps

    for _ in range(3):
        state, metrics = step_fn(state, *next(cycled))
    jax.device_get(metrics["loss"])

    # -- row 2: the async host path shaves measured HOST time -----------------
    post_s = 0.001

    def slow_poster(_url, _body):
        time.sleep(post_s)

    def host_arm(use_async: bool) -> float:
        rec = steptrace_mod.StepRecorder(capacity=4096)
        reporter = heartbeat_mod.HeartbeatReporter(
            "http://bench", "dp", poster=slow_poster, interval=0.0)
        host = autotune_mod.AsyncHost(capacity=256)
        if use_async:
            reporter.async_sink = host.submit
        for _ in range(max(2, windows // 2)):
            run_window(rec, on_host=lambda i: reporter.report(
                i, {"loss": 0.0}))
        host.close()
        summary = rec.summary()
        return summary["phases"]["host"]["p50Seconds"]

    sync_host = host_arm(False)
    async_host = host_arm(True)
    rows.append({
        "metric": "dataplane_async_host_shave",
        "sync_host_p50_ms": round(sync_host * 1e3, 4),
        "async_host_p50_ms": round(async_host * 1e3, 4),
        "post_ms": post_s * 1e3,
        "shave_pct": round(100.0 * (1.0 - async_host / max(sync_host, 1e-12)),
                           1),
        "unit": "pct",
        "value": round(100.0 * (1.0 - async_host / max(sync_host, 1e-12)), 1),
    })

    # -- row 3: recorder + autotune stay inside the PR 9 budget ---------------
    recorder = steptrace_mod.StepRecorder(capacity=4096)
    control3 = autotune_mod.PrefetchControl(2)
    controller3 = autotune_mod.DataPlaneController(
        control3, min_depth=1, max_depth=8, window_steps=32)
    recorder.on_commit = controller3.on_step
    off_times, on_times = [], []
    for _ in range(windows):
        off_times.append(run_window(None))
        on_times.append(run_window(recorder))
    off = min(off_times)
    deltas = [on_t - off_t for off_t, on_t in zip(off_times, on_times)]
    overhead = max(0.0, min(deltas))
    on = off + overhead
    rows.append({
        "metric": "dataplane_overhead",
        "off_step_ms": round(off * 1e3, 4),
        "on_step_ms": round(on * 1e3, 4),
        "overhead_pct": round(100.0 * overhead / off, 2),
        "overhead_us_per_step": round(overhead * 1e6, 2),
        "windows_evaluated": controller3.windows_evaluated,
        "windows": windows,
        "unit": "pct",
        "value": round(100.0 * overhead / off, 2),
    })
    return rows


def _dataplane_ok(rows: list) -> bool:
    conv, shave, over = rows
    ok = True
    if conv["within_pct"] > 5.0 or \
            conv["windows_to_settle"] > conv["budget_windows"]:
        print(f"dataplane convergence FAILED: settled depth "
              f"{conv['converged_depth']} is {conv['within_pct']}% off the "
              f"best static depth {conv['best_static_depth']} (budget 5%), "
              f"settled at window {conv['windows_to_settle']} of "
              f"{conv['budget_windows']}", file=sys.stderr)
        ok = False
    if shave["async_host_p50_ms"] > 0.5 * shave["sync_host_p50_ms"]:
        print(f"dataplane async host path FAILED to shave HOST time: "
              f"p50 {shave['async_host_p50_ms']} ms async vs "
              f"{shave['sync_host_p50_ms']} ms sync (must at least halve)",
              file=sys.stderr)
        ok = False
    over_abs = (over["on_step_ms"] - over["off_step_ms"]) / 1e3
    if not (over["overhead_pct"] <= 1.0 or over_abs <= 50e-6):
        print(f"dataplane overhead budget EXCEEDED: recorder+autotune step "
              f"{over['on_step_ms']} ms vs off {over['off_step_ms']} ms "
              f"({over['overhead_pct']:.2f}% > 1% and "
              f"{over_abs * 1e6:.1f} µs > 50 µs)", file=sys.stderr)
        ok = False
    return ok


# --- warm-restart startup rows --------------------------------------------------

def startup_worker_main(cfg_json: str) -> int:
    """Subprocess half of the startup bench: ONE fresh attempt of the
    transformer payload — build, (restore), overlapped AOT compile, first
    step — against the cache/checkpoint dirs the driver passes in. TTFS is
    measured from post-import to first-step completion (what the
    operator's startup breakdown covers; interpreter+import cost is
    identical cold and warm and would only dilute the ratio). Prints one
    JSON line."""
    cfg = json.loads(cfg_json)
    # Must land in the environment BEFORE jax is imported: the persistent
    # cache dir is read at config init, the platform at backend init.
    os.environ["JAX_PLATFORMS"] = cfg.get("platform", "cpu")
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cfg["cache_dir"]
    if cfg.get("store_uri"):
        # Remote warm-start store (the --store rows): the same env the
        # operator injects for spec.store, so the payload-side prefetch +
        # write-behind run exactly the production path.
        os.environ["TPUJOB_STORE_BACKEND"] = cfg.get("store_backend",
                                                     "localfs")
        os.environ["TPUJOB_STORE_URI"] = cfg["store_uri"]
        os.environ["TPUJOB_STORE_PARALLELISM"] = "4"
        os.environ["TPUJOB_STORE_PREFETCH"] = "1"
        os.environ["TPUJOB_NAMESPACE"] = "bench"
        os.environ["TPUJOB_NAME"] = cfg.get("job_name", "store-bench")
        if cfg.get("ckpt_dir"):
            os.environ["TPU_CHECKPOINT_DIR"] = cfg["ckpt_dir"]

    from tpu_operator.payload import bootstrap
    from tpu_operator.payload import checkpoint as ckpt_mod
    from tpu_operator.payload import startup as startup_mod
    from tpu_operator.payload import train, transformer, warmstore

    bootstrap.enable_compilation_cache()
    t0 = time.perf_counter()
    if cfg.get("store_uri") and warmstore.start_prefetch():
        # No rendezvous to overlap in a single-process worker, so the
        # whole download lands inside TTFS — the honest fresh-node cost
        # (production overlaps it with the DNS/rendezvous wait).
        warmstore.finish_prefetch()
    targs = transformer.parse_args(cfg["argv"])
    mesh, _model, state, step, batches = transformer.build(targs)
    ck = (ckpt_mod.from_env_or_args(cfg["ckpt_dir"], save_every=10_000)
          if cfg.get("ckpt_dir") else None)
    tracker = startup_mod.new_tracker()
    spec = transformer.lm_token_spec(mesh)
    try:
        state, _metrics = train.train_loop(
            mesh, step, state, batches, cfg["steps"], spec=spec,
            checkpointer=ck, heartbeat=None, startup=tracker)
    finally:
        if ck is not None:
            ck.close()  # flushes the async save AND the remote upload
    t_end = time.perf_counter()
    ttfs = (tracker.first_step_done_at or t_end) - t0
    # Per-run goodput, payload-side: useful step time = the first step
    # plus everything after its completion (pure stepping + save
    # bookkeeping); wallclock = the whole attempt. The controller computes
    # the production equivalent from heartbeats; this is the bench's
    # self-contained version of the same ratio.
    first_step = tracker.durations.get(startup_mod.FIRST_STEP, 0.0)
    wall = max(t_end - t0, 1e-9)
    useful = max(0.0, (t_end - t0) - ttfs) + first_step
    state, steps_per_sec = train.throughput(
        mesh, step, state, batches, steps=cfg.get("steady_steps", 3),
        warmup=1, spec=spec)
    print(json.dumps({
        "ttfs_s": round(ttfs, 4),
        "steady_step_ms": round(1e3 / steps_per_sec, 2),
        "goodput": round(min(1.0, useful / wall), 4),
        "wall_s": round(wall, 4),
        "breakdown": tracker.breakdown(),
    }), flush=True)
    return 0


def _run_startup_worker(cfg: dict) -> dict:
    import subprocess

    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--startup-worker", json.dumps(cfg)],
        capture_output=True, text=True, timeout=900)
    if out.returncode != 0:
        raise RuntimeError(
            f"startup worker failed (rc {out.returncode}):\n{out.stderr}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def bench_startup(quick: bool) -> list:
    """Cold vs warm restart of the transformer payload, each in a FRESH
    process (in-process jit caches would fake the warm path): the cold
    attempt populates the persistent compilation cache and leaves a final
    checkpoint; the warm attempt restores it and — via the overlapped
    prologue + cache hit — must reach its first step ≥ 2x faster. The
    delta IS the restart tax the operator's preemption budgets pay on
    every one of their maxRestarts*4 restarts."""
    import shutil
    import tempfile

    if quick:
        argv = ["--dim", "128", "--layers", "2", "--heads", "4",
                "--batch", "4", "--seq-len", "128", "--vocab", "1024"]
    else:
        # Deep-and-narrow on purpose: XLA compile time scales with graph
        # size (layers — measured 44 s cold vs 3.8 s cached for this
        # config), step time with FLOPs — this is the CPU-hostable config
        # whose TTFS is compile-dominated the way flagship payloads are on
        # a real TPU, so the warm/cold ratio measures the cache, not the
        # host's matmul throughput.
        argv = ["--dim", "64", "--layers", "16", "--heads", "4",
                "--batch", "2", "--seq-len", "64", "--vocab", "512"]
    cache_dir = tempfile.mkdtemp(prefix="bench-xla-cache-")
    ckpt_dir = tempfile.mkdtemp(prefix="bench-startup-ckpt-")
    base = {"argv": argv, "cache_dir": cache_dir, "ckpt_dir": ckpt_dir,
            "steady_steps": 5 if quick else 10}
    try:
        cold = _run_startup_worker({**base, "steps": 2})
        warm = _run_startup_worker({**base, "steps": 4})
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    speedup = cold["ttfs_s"] / warm["ttfs_s"] if warm["ttfs_s"] else 0.0
    rows = [
        {"metric": "startup_ttfs_cold_s", "value": cold["ttfs_s"],
         "unit": "s", "steady_step_ms": cold["steady_step_ms"],
         **{f"cold_{k}": v for k, v in cold["breakdown"].items()}},
        {"metric": "startup_ttfs_warm_s", "value": warm["ttfs_s"],
         "unit": "s", "speedup_vs_cold": round(speedup, 2),
         "steady_step_ms": warm["steady_step_ms"],
         **{f"warm_{k}": v for k, v in warm["breakdown"].items()}},
    ]
    return rows


# --- remote warm-start store rows ----------------------------------------------

def bench_store_writebehind_guard(quick: bool) -> dict:
    """The non-blocking proof: the same interval-save loop with and
    without a write-behind uploader pointed at a HIGH-LATENCY fake
    backend. If uploads rode the step loop, each save boundary would pay
    ≥ latency × (chunk exists/put + manifest put) ≈ 3×latency; the guard
    asserts the measured per-step overhead stays an order of magnitude
    under ONE latency unit."""
    import shutil
    import tempfile

    from tpu_operator.payload import checkpoint as ckpt_mod
    from tpu_operator.store import (FakeBackend, WarmStartStore,
                                    WriteBehindUploader)

    steps = 6 if quick else 10
    latency = 0.15
    state = _ckpt_state(0.25 if quick else 1.0)

    def run(with_store: bool) -> float:
        d = tempfile.mkdtemp(prefix="bench-store-wb-")
        uploader = None
        try:
            if with_store:
                backend = FakeBackend(latency=latency)
                uploader = WriteBehindUploader(
                    WarmStartStore(backend, prefix="bench"),
                    fail_after=1_000_000)
            ck = ckpt_mod.Checkpointer(d, save_every=1, uploader=uploader)
            t0 = time.perf_counter()
            for s in range(1, steps + 1):
                ck.maybe_save(s, state)
            per_step = (time.perf_counter() - t0) / steps
            ck.close()
            return per_step * 1e3
        finally:
            shutil.rmtree(d, ignore_errors=True)

    base_ms = run(False)
    with_ms = run(True)
    return {
        "metric": "store_writebehind_overhead_ms_per_step",
        "value": round(with_ms - base_ms, 2),
        "unit": "ms",
        "base_ms_per_step": round(base_ms, 2),
        "with_store_ms_per_step": round(with_ms, 2),
        "injected_latency_ms": latency * 1e3,
        "blocking_would_cost_ms": round(3 * latency * 1e3, 1),
        "budget_ms": round(latency * 1e3 / 2, 1),
        "steps": steps,
    }


def bench_store(quick: bool) -> list:
    """Fresh-node warm start through the remote store, measured: run 1
    (fully cold: empty local dirs AND empty store) populates the store —
    write-behind checkpoint upload + compilation-cache sync; run 2
    simulates the fleet scheduler re-placing the gang on a FRESH node
    (brand-new empty local cache + checkpoint dirs, same remote store):
    the rendezvous-overlapped prefetch must pull the executables and the
    latest checkpoint back down and beat the cold TTFS by the budget
    factor. Both runs report payload-side goodput; the write-behind guard
    proves uploads never ride the step loop."""
    import shutil
    import tempfile

    if quick:
        argv = ["--dim", "128", "--layers", "2", "--heads", "4",
                "--batch", "4", "--seq-len", "128", "--vocab", "1024"]
    else:
        # Same deep-narrow compile-dominated shape as bench_startup: the
        # fresh-node ratio must measure the store bringing the compile
        # cache + checkpoint across nodes, not host matmul throughput.
        argv = ["--dim", "64", "--layers", "16", "--heads", "4",
                "--batch", "2", "--seq-len", "64", "--vocab", "512"]
    store_root = tempfile.mkdtemp(prefix="bench-store-remote-")
    # ONE fixed cache/checkpoint path for both runs, WIPED between them —
    # exactly what a fresh node looks like in production: the mount
    # points (spec.compilationCache.path, spec.checkpointDir) are the
    # same configured paths on every node, only the contents are gone.
    # The path must be byte-identical or the persistent cache cannot hit
    # at all: jax derives debug_options.xla_gpu_per_fusion_autotune_
    # cache_dir from the cache dir and (as of jax 0.4.37) fails to scrub
    # it from the compilation-cache key, so entries written under a
    # different cache PATH hash to different keys.
    cache_dir = tempfile.mkdtemp(prefix="bench-store-cache-")
    ckpt_dir = tempfile.mkdtemp(prefix="bench-store-ckpt-")

    def wipe(path: str) -> None:
        shutil.rmtree(path, ignore_errors=True)
        os.makedirs(path, exist_ok=True)

    base = {"argv": argv, "store_uri": store_root, "job_name": "store-bench",
            "cache_dir": cache_dir, "ckpt_dir": ckpt_dir,
            "steady_steps": 5 if quick else 10}
    try:
        cold = _run_startup_worker({**base, "steps": 2})
        # The fleet scheduler re-placed the gang: fresh node, same mount
        # points, empty local state — only the remote store is warm.
        wipe(cache_dir)
        wipe(ckpt_dir)
        fresh = _run_startup_worker({**base, "steps": 4})
    finally:
        for d in (store_root, cache_dir, ckpt_dir):
            shutil.rmtree(d, ignore_errors=True)
    speedup = cold["ttfs_s"] / fresh["ttfs_s"] if fresh["ttfs_s"] else 0.0
    rows = [
        {"metric": "store_ttfs_cold_s", "value": cold["ttfs_s"],
         "unit": "s", "goodput": cold.get("goodput"),
         "steady_step_ms": cold["steady_step_ms"],
         **{f"cold_{k}": v for k, v in cold["breakdown"].items()}},
        {"metric": "store_ttfs_fresh_node_s", "value": fresh["ttfs_s"],
         "unit": "s", "speedup_vs_cold": round(speedup, 2),
         "goodput": fresh.get("goodput"),
         "steady_step_ms": fresh["steady_step_ms"],
         "local_dirs": "empty (fresh node); store warm",
         **{f"fresh_{k}": v for k, v in fresh["breakdown"].items()}},
        bench_store_writebehind_guard(quick),
    ]
    return rows


def _store_ok(rows: list, quick: bool) -> bool:
    """The CI contract (hack/verify.sh runs --store --quick): the
    fresh-node attempt must hit the prefetch (cache + checkpoint pulled
    from the store), beat the fully cold TTFS by the budget factor, carry
    a sane goodput that IMPROVES on cold (less dead startup time), and
    the write-behind must stay off the step loop."""
    ok = True
    cold = next(r for r in rows if r["metric"] == "store_ttfs_cold_s")
    fresh = next(r for r in rows if r["metric"] == "store_ttfs_fresh_node_s")
    guard = next(r for r in rows
                 if r["metric"] == "store_writebehind_overhead_ms_per_step")
    if not fresh.get("fresh_prefetchHit"):
        print(f"FAIL: fresh-node run did not hit the store prefetch "
              f"({fresh})", file=sys.stderr)
        ok = False
    # Same noise policy as the startup gate: tiny --quick shapes on a
    # shared CI box leave less compile time to win back.
    budget = 1.2 if quick else 1.5
    if fresh.get("speedup_vs_cold", 0) < budget:
        print(f"FAIL: fresh-node TTFS only {fresh.get('speedup_vs_cold')}x "
              f"faster than fully cold (budget: {budget}x)", file=sys.stderr)
        ok = False
    for row in (cold, fresh):
        gp = row.get("goodput")
        if gp is None or not 0.0 < gp <= 1.0:
            print(f"FAIL: {row['metric']} goodput {gp!r} out of (0, 1]",
                  file=sys.stderr)
            ok = False
    if ok and fresh["goodput"] <= cold["goodput"]:
        print(f"FAIL: fresh-node goodput {fresh['goodput']} did not improve "
              f"on cold {cold['goodput']} (warm start should cut dead "
              f"startup time)", file=sys.stderr)
        ok = False
    if guard["value"] > guard["budget_ms"]:
        print(f"FAIL: write-behind added {guard['value']} ms/step "
              f"(budget {guard['budget_ms']} ms — uploads must not ride "
              f"the step loop)", file=sys.stderr)
        ok = False
    return ok


def bench_serve(quick: bool) -> list:
    """The --serve rows (CPU-hostable): the batched decode service under
    the synthetic load generator, and the rolling reload under sustained
    load.

    Row 1 (serve-decode): a fixed requests/sec schedule against one
    replica; reports served req/s and p50/p95 request latency.
    Row 2 (serve-rolling-reload): mid-run, the "trainer" commits a newer
    verified snapshot to the (fake) remote store; the replica must
    observe it, drop readiness, reload, and return — with ZERO failed
    decode steps and requests still completing across the window."""
    import tempfile
    import threading as threading_mod
    import time as time_mod

    from tpu_operator.payload import bootstrap as bootstrap_mod
    from tpu_operator.payload import checkpoint as checkpoint_mod
    from tpu_operator.payload import serve as serve_mod
    from tpu_operator.store import WarmStartStore
    from tpu_operator.store.blob import from_uri

    def serve_args(tmp: str, load: str):
        argv = ["--load", load, "--checkpoint-dir", f"{tmp}/serve",
                "--reload-poll", "0.2", "--reload-stagger", "0"]
        if quick:
            argv += ["--batch", "2", "--decode-tokens", "2", "--window",
                     "16", "--vocab", "32", "--dim", "16", "--heads", "2",
                     "--kv-heads", "1", "--layers", "1"]
        else:
            argv += ["--batch", "8", "--decode-tokens", "8", "--window",
                     "64", "--vocab", "128", "--dim", "64", "--heads",
                     "4", "--kv-heads", "2", "--layers", "2"]
        return serve_mod.parse_args(argv)

    info = bootstrap_mod.ProcessInfo(
        coordinator_address="", process_id=0, num_processes=1,
        worker_id=0, worker_hostnames=(), job_name="bench-serve")

    def commit(store, args, step, tmp):
        trainer_dir = f"{tmp}/trainer-{step}"
        _m, _mod, state, _fn, _spec = serve_mod.build_decode(args)
        state = state.replace(step=state.step + step)
        ck = checkpoint_mod.Checkpointer(trainer_dir, save_every=1)
        try:
            ck.save(step, state)
            ck.flush()
        finally:
            ck.close()
        store.upload_checkpoint(f"{trainer_dir}/{step}", step)

    rows = []
    # Row 1: plain decode under load.
    with tempfile.TemporaryDirectory() as tmp:
        load = "30:3" if quick else "60:8"
        loop = serve_mod.ServeLoop(serve_args(tmp, load), info,
                                   heartbeat=None, store=None,
                                   recorder=None)
        t0 = time_mod.perf_counter()
        summary = loop.run()
        elapsed = time_mod.perf_counter() - t0
        stats = loop.window.drain()  # leftovers of the final window
        rows.append({
            "bench": "serve", "metric": "serve_decode_rps",
            "value": round(summary["completed"] / max(1e-9, elapsed), 2),
            "engine": loop.engine.kind,
            "arrivals": summary["arrivals"],
            "completed": summary["completed"],
            "shed": summary["shed"],
            "failed_steps": summary["failedSteps"],
            "tokens_per_s": round(summary["tokensPerSecond"], 1),
            "p50_ms": round(1000 * stats.get("p50", 0.0), 3),
            "p95_ms": round(1000 * stats.get("p95", 0.0), 3),
            "p99_ms": round(
                1000 * summary.get("p99LatencySeconds", 0.0), 3),
            "steps": summary["steps"],
        })
    # Row 2: rolling reload under sustained load.
    with tempfile.TemporaryDirectory() as tmp:
        load = "30:5" if quick else "60:12"
        args = serve_args(tmp, load)
        backend = from_uri(f"fake://bench-serve-{os.getpid()}")
        store = WarmStartStore(backend, prefix="bench/serve")
        commit(store, args, 10, tmp)
        loop = serve_mod.ServeLoop(args, info, heartbeat=None,
                                   store=store, recorder=None)

        def trainer():
            time_mod.sleep(1.5)
            commit(store, args, 20, tmp)

        th = threading_mod.Thread(target=trainer, daemon=True)
        th.start()
        summary = loop.run()
        th.join()
        rows.append({
            "bench": "serve", "metric": "serve_rolling_reload",
            "value": summary["reloads"],
            "loaded_step": summary["loadedStep"],
            "failed_steps": summary["failedSteps"],
            "completed": summary["completed"],
            "arrivals": summary["arrivals"],
        })

    # Row 3: incremental-vs-reforward A/B. Both engines driven directly
    # (no load-generator noise): admit a full-window prompt into every
    # slot, generate the per-request budget, release, repeat. Each round
    # yields batch x decode_tokens tokens on either engine (the paged
    # prefill emits the first token; the re-forward baseline takes one
    # more full-window step for it), so tokens/sec is apples-to-apples.
    def engine_tps(kind: str) -> float:
        import numpy as np

        with tempfile.TemporaryDirectory() as tmp2:
            args = serve_args(tmp2, "0:0")
            args.decode_engine = kind
            args.decode_tokens = 8  # amortize prefill like a real request
            _mesh, _model, state, decode_fn, tok_shard = \
                serve_mod.build_decode(args)
            eng = serve_mod.make_engine(args, decode_fn, tok_shard)
            eng.warmup(state.params)
            rng = np.random.default_rng(0)
            prompts = rng.integers(
                1, args.vocab, (args.batch, args.window)).astype(np.int32)
            active = np.ones(args.batch, bool)
            rounds = 4 if quick else 6
            tokens = 0
            t0 = time_mod.perf_counter()
            for _ in range(rounds):
                for slot in range(args.batch):
                    ok, tok = eng.admit(slot, prompts[slot],
                                        args.decode_tokens, state.params)
                    assert ok
                    tokens += int(tok is not None)
                steps = args.decode_tokens - (1 if kind == "paged" else 0)
                for _ in range(steps):
                    eng.step(state.params, active)
                    tokens += args.batch
                for slot in range(args.batch):
                    eng.release(slot)
            return tokens / max(1e-9, time_mod.perf_counter() - t0)

    tps_paged = engine_tps("paged")
    tps_reforward = engine_tps("reforward")
    rows.append({
        "bench": "serve", "metric": "serve_ab_paged_speedup_x",
        "value": round(tps_paged / max(1e-9, tps_reforward), 2),
        "paged_tokens_per_s": round(tps_paged, 1),
        "reforward_tokens_per_s": round(tps_reforward, 1),
    })

    # Row 4: the O(1)-per-token claim — paged decode step time must not
    # scale with the context already accumulated in the cache. One
    # engine provisioned for 256-token prompts; measure the per-token
    # step cost while serving 64-token contexts vs 256-token contexts.
    # (The re-forward baseline recomputes the whole context per token,
    # so its cost at 256 is ~4x its cost at 64 by construction.)
    import numpy as np

    flat_args = serve_mod.parse_args([
        "--load", "0:0", "--window", "256", "--decode-tokens", "64",
        "--batch", "2", "--vocab", "32", "--dim", "16", "--heads", "2",
        "--kv-heads", "1", "--layers", "1", "--decode-engine", "paged"])
    _mesh, _model, flat_state, _fn, _shard = serve_mod.build_decode(
        flat_args)
    flat_eng = serve_mod.make_engine(flat_args)
    flat_eng.warmup(flat_state.params)

    def step_cost_ms(context: int) -> float:
        prompt = (np.arange(context, dtype=np.int32)
                  % (flat_args.vocab - 1)) + 1
        for slot in range(flat_args.batch):
            flat_eng.admit(slot, prompt, flat_args.decode_tokens,
                           flat_state.params)
        active = np.ones(flat_args.batch, bool)
        for _ in range(4):  # untimed spin-up past compile + caches
            flat_eng.step(flat_state.params, active)
        reps = 24 if quick else 48
        t0 = time_mod.perf_counter()
        for _ in range(reps):
            flat_eng.step(flat_state.params, active)
        dt = time_mod.perf_counter() - t0
        for slot in range(flat_args.batch):
            flat_eng.release(slot)
        return 1000 * dt / (reps * flat_args.batch)

    cost_64 = step_cost_ms(64)
    cost_256 = step_cost_ms(256)
    rows.append({
        "bench": "serve", "metric": "serve_flat_token_cost_x",
        "value": round(cost_256 / max(1e-9, cost_64), 3),
        "w64_token_ms": round(cost_64, 4),
        "w256_token_ms": round(cost_256, 4),
    })
    return rows


def _serve_ok(rows: list, quick: bool) -> bool:
    """The CI contract (hack/verify.sh runs --serve --quick): the decode
    service must actually serve, the rolling reload must complete under
    load with ZERO failed decode steps, incremental decode must beat the
    re-forward baseline (>= 3x tokens/sec at the default shape; the quick
    shape's tiny two-token generations amortize less prefill, so its
    budget is looser), per-token paged decode cost must stay flat in the
    context length (window 256 within 1.3x of window 64 — the O(1)
    claim), and p99 request latency under the load schedule must hold
    the SLO budget."""
    ok = True
    for row in rows:
        if row.get("failed_steps", 0) != 0:
            print(f"FAIL: {row['metric']} had {row['failed_steps']} failed "
                  f"decode steps (budget: 0)", file=sys.stderr)
            ok = False
        if "completed" in row and row["completed"] <= 0:
            print(f"FAIL: {row['metric']} completed no requests ({row})",
                  file=sys.stderr)
            ok = False
    reload_row = next(r for r in rows
                      if r["metric"] == "serve_rolling_reload")
    if reload_row["value"] < 1 or reload_row.get("loaded_step", 0) != 20:
        print(f"FAIL: rolling reload did not complete under load "
              f"({reload_row})", file=sys.stderr)
        ok = False
    ab = next(r for r in rows if r["metric"] == "serve_ab_paged_speedup_x")
    # The quick shape (dim 16, window 16) is jit-dispatch-bound on CPU —
    # both arms pay ~the same per-call overhead, so the quick budget only
    # guards "incremental is not slower"; the >= 3x claim is the default
    # shape's (measured ~5x: re-forward pays O(window) recompute per
    # token, the paged engine one cached-span step).
    ab_budget = 1.2 if quick else 3.0
    if ab["value"] < ab_budget:
        print(f"FAIL: paged decode only {ab['value']}x the re-forward "
              f"baseline (budget: >= {ab_budget}x) ({ab})", file=sys.stderr)
        ok = False
    flat = next(r for r in rows if r["metric"] == "serve_flat_token_cost_x")
    if flat["value"] > 1.3:
        print(f"FAIL: per-token decode cost grew {flat['value']}x from "
              f"window 64 to 256 (budget: <= 1.3x) ({flat})",
              file=sys.stderr)
        ok = False
    decode_row = next(r for r in rows if r["metric"] == "serve_decode_rps")
    p99_budget_ms = 1000.0 if quick else 2000.0
    if not 0 < decode_row["p99_ms"] <= p99_budget_ms:
        print(f"FAIL: p99 request latency {decode_row['p99_ms']}ms under "
              f"load (SLO budget: (0, {p99_budget_ms}]ms) ({decode_row})",
              file=sys.stderr)
        ok = False
    if decode_row["shed"] != 0:
        print(f"FAIL: backpressure shed {decode_row['shed']} requests at "
              f"the bench load (budget: 0) ({decode_row})", file=sys.stderr)
        ok = False
    return ok


def _startup_ok(rows: list, quick: bool) -> bool:
    """The CI contract (hack/verify.sh runs --startup --quick): the warm
    attempt must hit the persistent compilation cache, beat cold TTFS by
    the budget factor, and hold steady-state step time."""
    ok = True
    cold = next(r for r in rows if r["metric"] == "startup_ttfs_cold_s")
    warm = next(r for r in rows if r["metric"] == "startup_ttfs_warm_s")
    if not warm.get("warm_cacheHit"):
        print("FAIL: warm restart did not hit the persistent compilation "
              f"cache ({warm})", file=sys.stderr)
        ok = False
    # Tiny --quick shapes leave less compile time to win back (and share
    # CI CPU with noisy neighbors — observed 1.35-3.9x run to run), so the
    # gate budget is looser than the ≥2x the real config must show.
    budget = 1.2 if quick else 2.0
    if warm.get("speedup_vs_cold", 0) < budget:
        print(f"FAIL: warm TTFS only {warm.get('speedup_vs_cold')}x faster "
              f"than cold (budget: {budget}x)", file=sys.stderr)
        ok = False
    # Coarse guard only: it exists to catch the AOT path poisoning steady
    # state (same executable → same step time), not to benchmark it — the
    # shared CI box jitters single-digit steps by 2-3x.
    if warm["steady_step_ms"] > cold["steady_step_ms"] * 3.0 + 50.0:
        print(f"FAIL: steady-state step regressed warm "
              f"({warm['steady_step_ms']} ms vs cold "
              f"{cold['steady_step_ms']} ms)", file=sys.stderr)
        ok = False
    return ok


def _control_plane_ok(rows: list) -> bool:
    """The CI contract (hack/verify.sh runs --control-plane --quick):
    steady-state reconcile must stay zero-read and the parallel gang must
    actually beat sequential."""
    ok = True
    for row in rows:
        if row["metric"] == "api_reads_per_reconcile" and row["value"] != 0:
            print(f"FAIL: steady-state reconcile issued {row['value']} read "
                  f"RPCs (budget: 0)", file=sys.stderr)
            ok = False
        if (row["metric"].startswith("gang_create_")
                and row.get("speedup_vs_sequential", 0) <= 1.0):
            print(f"FAIL: parallel gang create not faster than sequential "
                  f"({row})", file=sys.stderr)
            ok = False
    return ok


# --- main ----------------------------------------------------------------------

def main(argv=None) -> int:
    args = parse_args(argv)
    if args.startup_worker:
        return startup_worker_main(args.startup_worker)
    if args.startup:
        rows = [_emit(row) for row in bench_startup(args.quick)]
        return 0 if _startup_ok(rows, args.quick) else 1
    if args.store:
        # Workers run on CPU; the in-driver write-behind guard does orbax
        # host I/O — pin CPU like --checkpoint.
        os.environ["JAX_PLATFORMS"] = "cpu"
        rows = [_emit(row) for row in bench_store(args.quick)]
        return 0 if _store_ok(rows, args.quick) else 1
    if args.fleet:
        # Operator-only rows: no JAX import, runs anywhere (the CI gate).
        rows = [_emit(row) for row in bench_fleet(args.quick)]
        return 0 if _fleet_ok(rows) else 1
    if args.drain:
        # Operator-only rows: no JAX import, runs anywhere (the CI gate).
        rows = [_emit(row) for row in bench_drain(args.quick)]
        return 0 if _drain_ok(rows) else 1
    if args.churn:
        # Operator-only rows: no JAX import, runs anywhere (the CI gate).
        rows = [_emit(row) for row in bench_churn(args.quick)]
        return 0 if _churn_ok(rows) else 1
    if args.cluster:
        # Operator-only rows: no JAX import, runs anywhere (the CI gate).
        # The soak gates RSS growth, so pymalloc is swapped out first:
        # pymalloc frees a 256 KiB arena only when every pool in it is
        # empty, and a 10k-pod churn leaves each arena hosting a few
        # long-lived survivors — ~180 MB of arena residue at full scale
        # with <15 MB of live blocks inside, which would swamp the
        # retention signal the gate exists to catch.  glibc malloc
        # (plus the bench's periodic malloc_trim) returns interior free
        # pages, so the row measures the operator, not the allocator.
        if os.environ.get("PYTHONMALLOC") != "malloc":
            os.environ["PYTHONMALLOC"] = "malloc"
            os.execv(sys.executable, [sys.executable] + sys.argv)
        rows = [_emit(row) for row in bench_cluster(args.quick, args.seed)]
        return 0 if _cluster_ok(rows) else 1
    if args.control_plane:
        # Operator-only rows: no JAX import, runs anywhere (the CI gate).
        rows = [_emit(row) for row in bench_control_plane(args.quick)]
        return 0 if _control_plane_ok(rows) else 1
    if args.checkpoint:
        # Orbax I/O is host-side: pin CPU so the rows measure the durable
        # path, not a tunnel's device→host transfer artifacts.
        os.environ["JAX_PLATFORMS"] = "cpu"
        for row in bench_checkpoint(args.quick):
            _emit(row)
        return 0
    if args.steptrace:
        # Recorder cost is host-side clock reads: pin CPU (the tunnel's
        # per-fence RTT would swamp the µs-scale number being guarded).
        os.environ["JAX_PLATFORMS"] = "cpu"
        rows = [_emit(row) for row in bench_steptrace(args.quick)]
        return 0 if _steptrace_ok(rows) else 1
    if args.dataplane:
        # Same rationale as --steptrace: the budgets guard host-side
        # µs-scale costs, which the TPU tunnel's RTT would swamp.
        os.environ["JAX_PLATFORMS"] = "cpu"
        rows = [_emit(row) for row in bench_dataplane(args.quick)]
        return 0 if _dataplane_ok(rows) else 1
    if args.serve:
        # The decode model is tiny and the budgets are correctness-shaped
        # (zero failed steps, reload completes) — CPU-pinned like the
        # other host-side gates; real decode throughput belongs to the
        # TPU suite run.
        os.environ["JAX_PLATFORMS"] = "cpu"
        rows = [_emit(row) for row in bench_serve(args.quick)]
        return 0 if _serve_ok(rows, args.quick) else 1
    if args.flagship:
        # A/B budgets are relative and both arms share every platform
        # artifact, so the rows are CPU-hostable; --quick pins CPU like
        # the headline (non-quick measures whatever platform is up).
        if args.quick:
            os.environ["JAX_PLATFORMS"] = "cpu"
        rows = [_emit(row) for row in bench_flagship(args.quick)]
        return 0 if _flagship_ok(rows) else 1
    if args.quick:
        # Force CPU even when a TPU plugin pinned the platform at boot
        # (backend clients initialize lazily, so this override wins).
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if args.quick:
        jax.config.update("jax_platforms", "cpu")

    if args.suite:
        rows = []
        # Control plane first: CPU-only, fast, and a budget violation should
        # surface before an hour of TPU rows.
        cp_rows = [_emit(row) for row in bench_control_plane(args.quick)]
        rows.extend(cp_rows)
        if not _control_plane_ok(cp_rows):
            return 1
        fleet_rows = [_emit(row) for row in bench_fleet(args.quick)]
        rows.extend(fleet_rows)
        if not _fleet_ok(fleet_rows):
            return 1
        for row in bench_checkpoint(args.quick):
            rows.append(_emit(row))
        if jax.devices()[0].platform == "cpu":
            # The overhead being guarded is µs-scale host cost; through
            # the TPU tunnel every recorder fence pays the ~100 ms RTT
            # and the budget would fail on transport, not on the
            # recorder. The CPU-pinned standalone gate (verify.sh runs
            # `--steptrace --quick`) owns the budget; the suite row only
            # exists where it measures the right thing.
            st_rows = [_emit(row) for row in bench_steptrace(args.quick)]
            rows.extend(st_rows)
            if not _steptrace_ok(st_rows):
                return 1
            # The data-plane budgets guard the same µs-scale host costs
            # — CPU-only for the same reason as the steptrace row; the
            # verify.sh standalone gate (`--dataplane --quick`) owns
            # them either way.
            dp_rows = [_emit(row) for row in bench_dataplane(args.quick)]
            rows.extend(dp_rows)
            if not _dataplane_ok(dp_rows):
                return 1
            # Serving rows: correctness-shaped budgets (zero failed
            # decode steps, reload completes) — CPU-only in the suite
            # for the same tunnel rationale; the verify.sh standalone
            # gate (`--serve --quick`) owns them either way.
            sv_rows = [_emit(row) for row in bench_serve(args.quick)]
            rows.extend(sv_rows)
            if not _serve_ok(sv_rows, args.quick):
                return 1
        for row in bench_startup(args.quick):
            rows.append(_emit(row))
        for row in bench_store(args.quick):
            rows.append(_emit(row))
        rows.append(_emit(bench_matmul(args.quick)))
        for row in bench_attention(args.quick):
            rows.append(_emit(row))
        ladder = LM_LADDER_QUICK if args.quick else LM_LADDER
        for name, cfg, steps in ladder:
            rows.append(_emit(bench_lm(name, cfg, steps,
                                       windows=1 if args.quick else 3)))
        rows.append(_emit(bench_lm_realdata(args.quick)))
        for row in bench_moe(args.quick):
            rows.append(_emit(row))
        for row in bench_pipeline_overhead(args.quick):
            rows.append(_emit(row))
        headline = _emit(bench_cifar(args.quick, args.batch, args.steps))
        rows.append(headline)
        if not args.quick:
            # Only real-TPU runs update the recorded artifact — the CPU
            # smoke invocation must not clobber the measured numbers
            # backing docs/benchmarks.md, and neither may a non-quick run
            # on a host where JAX silently fell back to CPU (tunnel down):
            # gate on the actual backend, and divert anything else to a
            # clearly-labeled side file.
            platform = jax.devices()[0].platform
            out = {"rows": rows, "platform": platform,
                   "peak_tflops": V5E_PEAK_TFLOPS}
            name = ("BENCH_SUITE.json" if platform == "tpu"
                    else f"BENCH_SUITE.{platform}.json")
            with open(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    name), "w") as f:
                json.dump(out, f, indent=1)
        return 0

    _emit(bench_cifar(args.quick, args.batch, args.steps))
    return 0


if __name__ == "__main__":
    sys.exit(main())
