#!/usr/bin/env python3
"""Headline benchmark: CIFAR-10 ResNet training throughput per chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

BASELINE.md: the reference publishes no performance numbers at all (it is a
control-plane operator; its compute lived in user MXNet images). The
BASELINE.json target metric is "CIFAR-10 steps/sec/chip vs GPU spec" — the
GPU spec being the reference's single-GPU CIFAR example
(/root/reference/README.md:126-167, `alpha.kubernetes.io/nvidia-gpu: 1`,
NVIDIA K80-class, 2017-era MXNet). Published MXNet ResNet/CIFAR-10 numbers
for that setup cluster around ~1.2k images/sec, which we pin as the
baseline denominator below (documented assumption, reference ships none).

The benched step is the flagship payload exactly as the operator launches it
(tpu_operator/payload/cifar.py): ResNet-20, bf16 compute on the MXU, f32
master params, one jit with sharding over the (data, model) mesh — on
whatever accelerator is attached (single TPU chip under the driver; falls
back to CPU with --quick for smoke runs).

Measurement hygiene (the driver's TPU is reached through a network tunnel
whose artifacts a real TPU VM does not have — ~100 ms RTT per host sync,
~0.3 GB/s effective host→device bandwidth):
- batches are pre-staged in HBM and cycled, so the timed region measures
  the training step, not the tunnel's transfer bandwidth (a real input
  pipeline overlaps host I/O behind the step via prefetch);
- the timing fence is a ``device_get`` of the final loss — a value fetch
  cannot complete before the dependent step chain does on any backend,
  whereas ``block_until_ready`` was observed returning early through the
  tunnel and would inflate the result ~10x.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys


# The reference's GPU config throughput assumption (see module docstring).
BASELINE_IMAGES_PER_SEC = 1200.0


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true",
                   help="tiny CPU-friendly config (smoke test, not a benchmark)")
    p.add_argument("--batch", type=int, default=0, help="override global batch")
    p.add_argument("--steps", type=int, default=0, help="override timed steps")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.quick:
        # Force CPU even when a TPU plugin pinned the platform at boot
        # (backend clients initialize lazily, so this override wins).
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if args.quick:
        jax.config.update("jax_platforms", "cpu")

    from tpu_operator.payload import cifar, train

    n_devices = len(jax.devices())
    platform = jax.devices()[0].platform

    if args.quick:
        batch = args.batch or 64
        steps = args.steps or 5
        cfg = ["--blocks", "1", "--widths", "8", "16", "32"]
    else:
        batch = args.batch or 2048
        steps = args.steps or 60
        cfg = ["--blocks", "3", "--widths", "16", "32", "64"]  # ResNet-20

    from tpu_operator.payload import data as data_mod

    cargs = cifar.parse_args(["--batch", str(batch), *cfg])
    mesh, _model, state, step, batches = cifar.build(cargs)

    # Pre-stage a handful of batches in HBM and cycle them: host RNG and the
    # tunnel's host→device path stay off the timed region (see module
    # docstring); put_global_batch on an already-sharded array is a no-op.
    pregen = [data_mod.put_global_batch(mesh, *b)
              for b in itertools.islice(batches, 8)]
    cycled = itertools.cycle(pregen)

    # Median of three timed windows (compile cost is paid once, before
    # the first window; each window still runs its own 5 warmup steps):
    # the tunnel adds a few percent of run-to-run jitter a single
    # window would pass straight through to the recorded number.
    rates = []
    for _ in range(1 if args.quick else 3):
        state, steps_per_sec = train.throughput(
            mesh, step, state, cycled, steps=steps, warmup=5
        )
        rates.append(steps_per_sec)
    rates.sort()
    images_per_sec = rates[len(rates) // 2] * batch
    per_chip = images_per_sec / n_devices

    result = {
        "metric": f"cifar10_resnet20_bf16_images_per_sec_per_chip_{platform}",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMAGES_PER_SEC, 3),
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
