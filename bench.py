#!/usr/bin/env python3
"""Benchmarks — headline + the full reproducible suite.

Default invocation (the driver contract) prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
— the CIFAR-10 ResNet training throughput per chip.

``--suite`` re-measures EVERY row of docs/benchmarks.md and prints one JSON
line per row (plus the headline line last, so the driver's single-line
parse still works by reading the final line). No benchmark number in the
docs lives outside this file: each row of the table is a ``--suite`` row.

BASELINE.md: the reference publishes no performance numbers at all (it is a
control-plane operator; its compute lived in user MXNet images). The
BASELINE.json target metric is "CIFAR-10 steps/sec/chip vs GPU spec" — the
GPU spec being the reference's single-GPU CIFAR example
(/root/reference/README.md:126-167, `alpha.kubernetes.io/nvidia-gpu: 1`,
NVIDIA K80-class, 2017-era MXNet). Published MXNet ResNet/CIFAR-10 numbers
for that setup cluster around ~1.2k images/sec, which we pin as the
baseline denominator below (documented assumption, reference ships none).

Measurement hygiene (the driver's TPU is reached through a network tunnel
whose artifacts a real TPU VM does not have — ~100 ms RTT per host sync,
~0.3 GB/s effective host→device bandwidth):
- batches are pre-staged in HBM and cycled, so the timed region measures
  the training step, not the tunnel's transfer bandwidth (a real input
  pipeline overlaps host I/O behind the step via prefetch);
- the timing fence is a ``device_get`` of a final value — a value fetch
  cannot complete before the dependent computation chain does on any
  backend, whereas ``block_until_ready`` was observed returning early
  through the tunnel and would inflate results ~10x.

MFU accounting (the ``lm_*`` rows): model FLOPs per step =
6 * params * tokens (fwd+bwd param matmuls) + 12 * L * B * T^2 * d / 2
(causal attention, fwd+bwd, the /2 because a causal kernel skips the
masked half). Remat recompute is *excluded* — MFU counts useful FLOPs
only, so remat configs pay their recompute as lost utilization, which is
the honest accounting. Peak for the v5e chip: 197 bf16 TFLOPS.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time


# The reference's GPU config throughput assumption (see module docstring).
BASELINE_IMAGES_PER_SEC = 1200.0
V5E_PEAK_TFLOPS = 197.0


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true",
                   help="tiny CPU-friendly config (smoke test, not a "
                        "benchmark); with --suite, runs every suite row at "
                        "smoke shapes")
    p.add_argument("--suite", action="store_true",
                   help="re-measure every docs/benchmarks.md row: CIFAR "
                        "headline, LM ladder + flagship MFU, raw matmul "
                        "ceiling, flash-vs-XLA attention at long T")
    p.add_argument("--batch", type=int, default=0, help="override global batch")
    p.add_argument("--steps", type=int, default=0, help="override timed steps")
    return p.parse_args(argv)


def _device_get_fence(x):
    import jax

    return jax.device_get(x)


def _emit(row: dict) -> dict:
    print(json.dumps(row), flush=True)
    return row


# --- CIFAR headline ------------------------------------------------------------

def bench_cifar(quick: bool, batch_override: int = 0,
                steps_override: int = 0) -> dict:
    """The flagship classifier payload exactly as the operator launches it
    (tpu_operator/payload/cifar.py): ResNet-20, bf16 on the MXU, one jit."""
    import jax

    from tpu_operator.payload import cifar, data as data_mod, train

    n_devices = len(jax.devices())
    platform = jax.devices()[0].platform

    if quick:
        batch = batch_override or 64
        steps = steps_override or 5
        cfg = ["--blocks", "1", "--widths", "8", "16", "32"]
    else:
        batch = batch_override or 2048
        steps = steps_override or 60
        cfg = ["--blocks", "3", "--widths", "16", "32", "64"]  # ResNet-20

    cargs = cifar.parse_args(["--batch", str(batch), *cfg])
    mesh, _model, state, step, batches = cifar.build(cargs)

    # Pre-stage a handful of batches in HBM and cycle them: host RNG and the
    # tunnel's host→device path stay off the timed region (module
    # docstring); put_global_batch on an already-sharded array is a no-op.
    pregen = [data_mod.put_global_batch(mesh, *b)
              for b in itertools.islice(batches, 8)]
    cycled = itertools.cycle(pregen)

    # Median of three timed windows (compile cost is paid once, before
    # the first window; each window still runs its own 5 warmup steps):
    # the tunnel adds a few percent of run-to-run jitter a single
    # window would pass straight through to the recorded number.
    rates = []
    for _ in range(1 if quick else 3):
        state, steps_per_sec = train.throughput(
            mesh, step, state, cycled, steps=steps, warmup=5
        )
        rates.append(steps_per_sec)
    rates.sort()
    images_per_sec = rates[len(rates) // 2] * batch
    per_chip = images_per_sec / n_devices

    return {
        "metric": f"cifar10_resnet20_bf16_images_per_sec_per_chip_{platform}",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMAGES_PER_SEC, 3),
    }


# --- LM ladder / flagship MFU --------------------------------------------------

def lm_model_flops_per_step(n_matmul_params: int, batch: int, seq: int,
                            layers: int, dim: int) -> int:
    """Model FLOPs of one step (module docstring: 6NT + causal attention).
    ``n_matmul_params`` must exclude embedding tables: their forward is a
    gather and their backward a scatter-add, not 6N matmul FLOPs — counting
    them would inflate MFU by ~12% at the flagship config."""
    tokens = batch * seq
    return (6 * n_matmul_params * tokens
            + 12 * layers * batch * seq * seq * dim // 2)


def bench_lm(name: str, argv: list, steps: int, warmup: int = 3) -> dict:
    import jax

    from tpu_operator.payload import data as data_mod, transformer

    targs = transformer.parse_args(argv)
    mesh, _model, state, step, batches = transformer.build(targs)
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    n_params = sum(leaf.size for _path, leaf in flat)
    n_matmul_params = sum(
        leaf.size for path, leaf in flat
        if not any("embed" in str(getattr(k, "key", k)) for k in path))
    spec = transformer.lm_token_spec(mesh)
    pregen = [data_mod.put_global_batch(mesh, *b, spec=spec)
              for b in itertools.islice(batches, 4)]
    cycled = itertools.cycle(pregen)

    for _ in range(warmup):
        state, metrics = step(state, *next(cycled))
    _device_get_fence(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, *next(cycled))
    _device_get_fence(metrics["loss"])
    dt = (time.perf_counter() - t0) / steps

    flops = lm_model_flops_per_step(n_matmul_params, targs.batch,
                                    targs.seq_len, targs.layers, targs.dim)
    tflops = flops / dt / 1e12
    return {
        "metric": name,
        "value": round(targs.batch * targs.seq_len / dt),
        "unit": "tokens/sec",
        "params_M": round(n_params / 1e6, 1),
        "matmul_params_M": round(n_matmul_params / 1e6, 1),
        "step_ms": round(dt * 1e3, 1),
        "model_tflops": round(tflops, 1),
        "mfu_pct": round(100 * tflops / V5E_PEAK_TFLOPS, 1),
        "config": " ".join(argv),
    }


LM_LADDER = [
    ("lm_d512_L4", ["--dim", "512", "--layers", "4", "--heads", "8",
                    "--batch", "32", "--seq-len", "2048",
                    "--vocab", "32768"], 30),
    ("lm_d1024_L8", ["--dim", "1024", "--layers", "8", "--heads", "8",
                     "--batch", "16", "--seq-len", "2048",
                     "--vocab", "32768"], 20),
    # The flagship: largest config sustaining peak MFU on one v5e chip —
    # 541M params, dots-remat (matmul outputs resident, elementwise
    # recomputed), bf16 adam mu, batch 32 via 4 grad-accum microbatches.
    ("lm_flagship_d2048_L8", ["--dim", "2048", "--layers", "8",
                              "--heads", "16", "--batch", "32",
                              "--seq-len", "2048", "--vocab", "32768",
                              "--remat", "--remat-policy", "dots",
                              "--grad-accum", "4",
                              "--adam-mu-dtype", "bf16"], 10),
    # The same flagship with grouped-query attention (4 K/V heads serving
    # 16 query heads): ~50M fewer params, ~14% more tokens/sec.
    ("lm_flagship_gqa_kv4", ["--dim", "2048", "--layers", "8",
                             "--heads", "16", "--kv-heads", "4",
                             "--batch", "32", "--seq-len", "2048",
                             "--vocab", "32768",
                             "--remat", "--remat-policy", "dots",
                             "--grad-accum", "4",
                             "--adam-mu-dtype", "bf16"], 10),
]

LM_LADDER_QUICK = [
    ("lm_quick", ["--dim", "64", "--layers", "2", "--heads", "2",
                  "--batch", "4", "--seq-len", "128", "--vocab", "256"], 3),
]


# --- raw matmul ceiling --------------------------------------------------------

def bench_matmul(quick: bool) -> dict:
    """Ceiling check: chained bf16 matmuls, one dispatch — what the chip
    gives a pure MXU workload through this framework's jit path. Model
    configs below this are bandwidth/overhead-bound, not framework-bound."""
    import jax
    import jax.numpy as jnp

    n = 1024 if quick else 8192
    chain = 2 if quick else 8
    steps = 2 if quick else 10

    @jax.jit
    def chained(x, w):
        for _ in range(chain):
            x = jnp.dot(x, w)
        return x

    key = jax.random.key(0)
    x = jax.random.normal(key, (n, n), jnp.bfloat16)
    w = jax.random.normal(key, (n, n), jnp.bfloat16)
    out = chained(x, w)
    _device_get_fence(out[0, 0])
    t0 = time.perf_counter()
    for _ in range(steps):
        out = chained(out, w)
    _device_get_fence(out[0, 0])
    dt = (time.perf_counter() - t0) / steps
    tflops = 2 * n * n * n * chain / dt / 1e12
    return {
        "metric": f"matmul_bf16_{n}cubed_x{chain}",
        "value": round(tflops, 1),
        "unit": "TFLOPS",
        "pct_of_peak": round(100 * tflops / V5E_PEAK_TFLOPS, 1),
    }


# --- flash attention vs fused-XLA at long T ------------------------------------

def bench_attention(quick: bool) -> list:
    """Train-step (fwd+bwd) attention at growing T: the Pallas flash path
    (O(T) memory both directions) vs XLA differentiating dense attention
    (O(T^2) scores). Rows report speedup; where the dense path cannot even
    fit in HBM the flash row is the only one that runs — that is the
    long-context capability, reported as xla_ms = null."""
    import jax
    import jax.numpy as jnp

    from tpu_operator.payload import flash_attention as fa
    from tpu_operator.payload import ring_attention as ring

    on_tpu = jax.default_backend() == "tpu"
    # Batch shrinks as T grows (tokens roughly constant, like a real
    # long-context config); the dense path runs only while its backward's
    # ~3 f32 [B,H,T,T] tensors fit a 16G chip.
    configs = [(256, 1, 2, 64)] if quick else [
        (2048, 4, 16, 128), (8192, 1, 16, 128), (32768, 1, 16, 128)]
    xla_budget_bytes = 12e9
    rows = []

    def timed_grad(fn, q, k, v, steps):
        loss = jax.jit(jax.grad(
            lambda q: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)))
        g = loss(q)
        _device_get_fence(g[0, 0, 0, 0])
        t0 = time.perf_counter()
        for _ in range(steps):
            g = loss(q)
        _device_get_fence(g[0, 0, 0, 0])
        return (time.perf_counter() - t0) / steps

    for t, b, h, d in configs:
        key = jax.random.key(0)
        shape = (b, t, h, d)
        q = jax.random.normal(key, shape, jnp.bfloat16)
        k = jax.random.normal(key, shape, jnp.bfloat16)
        v = jax.random.normal(key, shape, jnp.bfloat16)
        steps = 3 if quick else max(2, 20 * 2048 // t)
        flash_ms = timed_grad(
            lambda q, k, v: fa.flash_attention(q, k, v, causal=True,
                                               use_pallas=on_tpu or None),
            q, k, v, steps) * 1e3
        xla_ms = None
        if 3 * 4 * b * h * t * t <= xla_budget_bytes:
            xla_ms = timed_grad(
                lambda q, k, v: ring.reference_attention(q, k, v, causal=True),
                q, k, v, steps) * 1e3
        rows.append({
            "metric": f"flash_attention_T{t}_fwd_bwd",
            "value": round(flash_ms, 2),
            "unit": "ms/step",
            "xla_ms": round(xla_ms, 2) if xla_ms is not None else None,
            "speedup_vs_xla": (round(xla_ms / flash_ms, 2)
                               if xla_ms is not None else None),
            "shape": f"B{b} H{h} D{d}",
        })
    return rows


# --- main ----------------------------------------------------------------------

def main(argv=None) -> int:
    args = parse_args(argv)
    if args.quick:
        # Force CPU even when a TPU plugin pinned the platform at boot
        # (backend clients initialize lazily, so this override wins).
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if args.quick:
        jax.config.update("jax_platforms", "cpu")

    if args.suite:
        rows = []
        rows.append(_emit(bench_matmul(args.quick)))
        for row in bench_attention(args.quick):
            rows.append(_emit(row))
        ladder = LM_LADDER_QUICK if args.quick else LM_LADDER
        for name, cfg, steps in ladder:
            rows.append(_emit(bench_lm(name, cfg, steps)))
        headline = _emit(bench_cifar(args.quick, args.batch, args.steps))
        rows.append(headline)
        if not args.quick:
            # Only real-hardware runs update the recorded artifact — the
            # CPU smoke invocation must not clobber the measured numbers
            # backing docs/benchmarks.md.
            out = {"rows": rows, "platform": jax.devices()[0].platform,
                   "peak_tflops": V5E_PEAK_TFLOPS}
            with open(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "BENCH_SUITE.json"), "w") as f:
                json.dump(out, f, indent=1)
        return 0

    _emit(bench_cifar(args.quick, args.batch, args.steps))
    return 0


if __name__ == "__main__":
    sys.exit(main())
