"""The reconcile engine.

Reference parity: pkg/controller/controller.go:66-279 —
informer event handlers → rate-limited workqueue (controller.go:105,114-132,
270-279), worker loop (controller.go:175-203), ``syncMXJob`` mapping a queue
key to a cached per-UID TrainingJob and calling Reconcile
(controller.go:237-249), forgetting jobs that reach a terminal/cleanup phase
(controller.go:261-265).

Deliberate upgrades over the reference (SURVEY.md quirks/notes):

- **Pod and service informers feed the queue too**, keyed back to the owning
  TPUJob through its OwnerReference. The reference only watched MXJobs and
  relied on the 30 s resync to notice pod state changes — worker death was
  invisible for up to 30 s. On TPU slices that window strands expensive
  hardware, so child events enqueue immediately.
- **The jobs map is lock-guarded**, making ``threadiness > 1`` safe. The
  reference's map was safe only because it always ran with threadiness 1
  (server.go:94; SURVEY.md §5 race notes). The workqueue's processing-set
  semantics already guarantee one worker per key.
- **A GC sweep** (``run_gc_once``) deletes orphaned children whose owning
  TPUJob is gone — the reference declared ``--gc-interval`` but wired it to
  nothing (options.go:42), leaving cleanup to a stale shell script
  (hack/scripts/cleanup_clusters.sh:5-7).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from tpu_operator.apis.tpujob.v1alpha1.types import (
    ControllerConfig,
    DEFAULT_STRAGGLER_RATIO,
    DrainReason,
    DrainState,
    LABEL_GROUP_KEY,
    TPUJob,
    TPUJobPhase,
)
from tpu_operator.client import errors
from tpu_operator.client.informer import (
    INDEX_OWNER_UID,
    Listers,
    SharedInformerFactory,
    add_child_indexes,
    object_key,
)
from tpu_operator.client.workqueue import RateLimitingQueue
from tpu_operator.controller.deadlines import DeadlineManager
from tpu_operator.controller.events import EventRecorder
from tpu_operator.obs.timeline import TimelineStore
from tpu_operator.scheduler.fleet import FleetScheduler
from tpu_operator.scheduler.inventory import (
    SliceInventory,
    job_demand,
    scheduling_params,
)
from tpu_operator.scheduler.sharding import ShardedWorkQueue
from tpu_operator.scheduler.writeback import WritebackLimiter
from tpu_operator.trainer import elastic as elastic_mod
from tpu_operator.trainer import serving as serving_mod
from tpu_operator.trainer.training import TrainingJob, live_pod
from tpu_operator.util import tracing
from tpu_operator.util.tracing import traced
from tpu_operator.util import joblife, lockdep

log = logging.getLogger(__name__)

# Gang-cadence hygiene: a member whose last cadence beat is older than
# this is dropped from the straggler evaluation (a dead/replaced process
# must not skew the gang median forever with its frozen last value), and
# the per-job map is bounded (stalest-evicted) against misconfigured
# payloads minting ever-new processIds — the same unbounded-labeled-state
# class HEARTBEAT_CAP and the queue-depth LRU bound elsewhere.
CADENCE_EXPIRY_SECONDS = 300.0
CADENCE_MAX_PROCS = 1024

# Serving-readiness hygiene: a replica whose last serving beat is older
# than this drops from the ready set (its Service is removed) even
# without an explicit ready=false beat — a wedged replica must stop
# receiving traffic. Much tighter than the cadence expiry: readiness is
# a routing decision, not a statistics window.
SERVING_EXPIRY_SECONDS = 60.0
SERVING_MAX_PROCS = 1024


def _expire_serving_procs(procs: Dict[int, Dict[str, Any]],
                          now: float) -> List[int]:
    """Mark serving entries whose last beat is older than the expiry as
    STALE (not-ready, zero traffic) rather than deleting them: a stale
    entry is still KNOWN, so the readiness gate removes its Service —
    deleting it would make the replica *unknown*, and unknown indices
    deliberately keep their Services (the operator-restart case: absence
    of evidence is not evidence of not-ready). Returns newly staled
    pids."""
    staled: List[int] = []
    for p, e in procs.items():
        if not e.get("stale") and now - e["seen"] > SERVING_EXPIRY_SECONDS:
            e["stale"] = True
            e["ready"] = False
            e["rps"] = 0.0
            staled.append(p)
    return staled


class Controller:
    """ref: controller.New (controller.go:90) + Run (controller.go:145)."""

    def __init__(
        self,
        clientset: Any,
        informer_factory: SharedInformerFactory,
        config: Optional[ControllerConfig] = None,
        namespace: str = "",
        queue: Optional[RateLimitingQueue] = None,
        metrics: Optional[Any] = None,
        clock: Callable[[], float] = time.monotonic,
        heartbeat_persist_interval: float = 30.0,
        wall_clock: Callable[[], float] = time.time,
        shards: int = 1,
        writeback_qps: float = 0.0,
    ):
        self.clientset = clientset
        self.factory = informer_factory
        self.config = config or ControllerConfig()
        self.namespace = namespace
        self._clock = clock
        self._wall_clock = wall_clock
        # Minimum seconds between heartbeat-triggered status writes per job
        # (see record_heartbeat); 0 persists every heartbeat immediately.
        self.heartbeat_persist_interval = heartbeat_persist_interval
        # Prometheus-style registry (controller/statusserver.py); a real
        # Metrics by default so call sites never branch. The workqueue and
        # event recorder feed the same registry (client-go-style workqueue
        # metrics, event aggregation counters).
        from tpu_operator.controller.statusserver import Metrics
        self.metrics = metrics if metrics is not None else Metrics()
        # Late-bind the API-request ledger into a fake clientset (the REST
        # transport gets the same binding in the server bootstrap): every
        # clientset call then ticks api_requests_total{verb,resource}.
        if getattr(clientset, "metrics", "absent") is None:
            clientset.metrics = self.metrics
        # shards > 1: per-shard workers with key-hash affinity (one worker
        # owns one shard; a key always reconciles on the same worker), each
        # shard its own rate-limited queue. shards == 1 keeps the single
        # RateLimitingQueue shape every existing consumer/test knows.
        if queue is not None:
            self.queue = queue
        elif shards > 1:
            self.queue = ShardedWorkQueue(shards, clock=clock,
                                          metrics=self.metrics)
        else:
            self.queue = RateLimitingQueue(clock=clock, metrics=self.metrics)
        # Exact-time wakeups for time obligations (backoff release, stall
        # watchdog, active deadline, finished-TTL): the TrainingJob reports
        # its next obligation after every reconcile and the manager parks a
        # delayed enqueue for that moment (controller/deadlines.py).
        self.deadlines = DeadlineManager(self.queue, clock=wall_clock)
        self.recorder = EventRecorder(clientset, metrics=self.metrics)
        # Unified job timelines: every decision event the recorder emits
        # (Queued/Admitted/Preempted/GroupRestart/...) also lands in the
        # per-job timeline store, stamped with the reconcile trace id —
        # the live half of GET /api/jobs/<ns>/<name>/timeline. Pruned on
        # deletion through the listener below, audited by the joblife
        # sweep like every other per-job container.
        self.timeline = TimelineStore()
        self.recorder.add_observer(self.timeline.record_event)
        # Fleet scheduler: the admission queue + slice inventory every
        # TrainingJob consults. An empty inventory (no sliceInventory in
        # config) admits everything — the pre-fleet behavior.
        self.scheduler = FleetScheduler(
            SliceInventory.from_config(self.config),
            enqueue=self.queue.add, metrics=self.metrics, clock=wall_clock)
        # Global non-critical status-PUT budget (0 = unlimited).
        self.writeback = (WritebackLimiter(writeback_qps)
                          if writeback_qps > 0 else None)
        # UID-keyed in-memory jobs (ref: controller.go:71); lock-guarded so
        # threadiness > 1 is safe (the reference's was not).
        self.jobs: Dict[str, TrainingJob] = joblife.track(
            "Controller.jobs")  # per-job: sync_tpujob; guarded-by: _jobs_lock
        self._jobs_lock = lockdep.lock("Controller._jobs_lock")
        # key -> heartbeat "time" of the last persist-enqueued heartbeat
        # (guarded by _jobs_lock; see record_heartbeat's coalescing).
        self._hb_persisted: Dict[str, float] = joblife.track(
            "Controller._hb_persisted")  # per-job: sync_tpujob; guarded-by: _jobs_lock
        # Straggler detection state, key -> {"attempt": n, "procs":
        # {processId -> {"p95", "step", "time"}}, "flagged": set(pid)}.
        # In-memory only (rebuilt from fresh cadence beats after an
        # operator restart — it is telemetry, not state); reset on attempt
        # change, dropped on job deletion.
        self._gang_cadence: Dict[str, Dict[str, Any]] = joblife.track(
            "Controller._gang_cadence")  # per-job: sync_tpujob; guarded-by: _jobs_lock
        # Serving-mode per-replica state, key -> {"attempt": n, "procs":
        # {processId -> {"ready", "rps", "p50", "p95", "loadedStep",
        # "reloads", "seen"}}}. In-memory like the cadence map (readiness
        # re-earns itself from fresh beats after an operator restart; the
        # reload delta baselines persist IN status.serving).
        self._serving: Dict[str, Dict[str, Any]] = joblife.track(
            "Controller._serving")  # per-job: sync_tpujob; guarded-by: _jobs_lock
        # Parties holding per-job state the controller can't reach (the
        # status server's heartbeat stash) register here; every callback
        # runs on the deletion reconcile, BEFORE the joblife sweep that
        # asserts nothing per-job survived.
        self._deletion_listeners: List[Callable[[str, str], None]] = []  # guarded-by: _jobs_lock
        # Epoch pin for the deletion sweep: a worker of THIS controller
        # draining a last deletion after a test harness moved on must
        # not judge the next epoch's containers.
        self._joblife_epoch = joblife.current_epoch()
        # Straggler-remediation pacing (spec.elastic.stragglerPolicy):
        # how long each flagged member has stayed flagged; crossing the
        # patience window hands the member to the TrainingJob's next
        # reconcile for replace/shed. Own lock inside (safe under
        # _jobs_lock); in-memory like the cadence map — a restarted
        # operator re-earns the window from fresh flags.
        self._remediation = elastic_mod.RemediationTracker()

        self.job_informer = self.factory.informer_for("tpujobs")
        self.job_informer.add_event_handler(
            on_add=self.enqueue,
            on_update=lambda _old, new: self.enqueue(new),
            on_delete=self.enqueue,
        )
        # Child informers → owner enqueue (upgrade; see module docstring),
        # indexed by controlling-owner UID + job label so reconciles read
        # children from the cache instead of LISTing the apiserver.
        for resource in ("pods", "services"):
            inf = self.factory.informer_for(resource)
            add_child_indexes(inf.store)
            inf.add_event_handler(
                on_add=self._enqueue_owner,
                on_update=lambda _old, new: self._enqueue_owner(new),
                on_delete=self._enqueue_owner,
            )
        # The read path handed to every TrainingJob: informer stores only.
        self.listers = Listers(
            tpujobs=self.job_informer.store,
            pods=self.factory.informer_for("pods").store,
            services=self.factory.informer_for("services").store,
        )
        # Live slice-inventory discovery (ROADMAP item 1 follow-on): a
        # node informer rebuilds the scheduler's capacity model on every
        # node add/remove/relabel, so capacity changes update admission —
        # and trigger a queue rebalance — without an operator restart.
        # Cluster-scoped: namespace "" = the un-namespaced node path.
        self._node_informer = None
        # Debounce state for discovered-capacity refreshes: the capacity
        # map last handed to the scheduler, and the pending shrink timer
        # (a NotReady→Ready flap inside config.node_debounce_seconds must
        # cancel its own shrink before the scheduler ever sees it).
        self._inv_lock = lockdep.lock("Controller._inv_lock")
        self._inv_applied: Optional[Dict[str, int]] = None  # guarded-by: _inv_lock
        self._inv_timer: Optional[threading.Timer] = None  # guarded-by: _inv_lock
        if getattr(self.config, "discover_slice_inventory", False):
            self._node_informer = self.factory.informer_for("nodes",
                                                            namespace="")
            self._node_informer.add_event_handler(
                on_add=lambda _obj: self._refresh_node_inventory(),
                on_update=self._on_node_update,
                on_delete=lambda _obj: self._refresh_node_inventory(),
            )

    # -- enqueue (ref: controller.go:270-279) ----------------------------------

    def enqueue(self, obj: Dict[str, Any]) -> None:
        self.queue.add(object_key(obj))

    def add_deletion_listener(self,
                              listener: Callable[[str, str], None]) -> None:
        """Register a ``(namespace, name)`` callback run on every job
        deletion reconcile — the hook for per-job state living outside
        the controller's own maps. Idempotent per callable."""
        with self._jobs_lock:
            if listener not in self._deletion_listeners:
                self._deletion_listeners.append(listener)

    def _enqueue_owner(self, obj: Dict[str, Any]) -> None:
        md = obj.get("metadata") or {}
        for ref in md.get("ownerReferences") or []:
            if ref.get("kind") == "TPUJob" and ref.get("controller"):
                ns = md.get("namespace", "default")
                self.queue.add(f"{ns}/{ref.get('name')}")

    # -- run (ref: controller.go:145-203) --------------------------------------

    def run(self, threadiness: int, stop_event: threading.Event) -> None:
        """Start informers, wait for cache sync, run workers until stopped
        (ref: controller.go:145-173; worker cadence via queue blocking rather
        than the reference's 1 s wait.Until polling).

        With a sharded queue the worker count IS the shard count — one
        worker owns one shard, so key-hash affinity (never two workers on
        one job) holds by construction and ``threadiness`` is ignored."""
        self.factory.start(stop_event)
        if not self.factory.wait_for_cache_sync():
            raise RuntimeError("timed out waiting for informer caches to sync")
        # Discovery mode: seed the capacity model from the synced node
        # cache once, unconditionally — a cluster with zero (TPU) nodes
        # must yield an EMPTY discovered inventory, not silently keep a
        # stale static one that per-node events would never fire to
        # replace.
        self._refresh_node_inventory()
        self._rebuild_scheduler_accounting()
        num_shards = getattr(self.queue, "num_shards", None)
        if num_shards is not None:
            log.info("caches synced; starting %d shard workers", num_shards)
            workers = [
                threading.Thread(target=self._worker,
                                 args=(stop_event, i),
                                 daemon=True, name=f"reconcile-shard-{i}")
                for i in range(num_shards)
            ]
        else:
            log.info("caches synced; starting %d workers", threadiness)
            workers = [
                threading.Thread(target=self._worker, args=(stop_event,),
                                 daemon=True, name=f"reconcile-worker-{i}")
                for i in range(threadiness)
            ]
        for w in workers:
            w.start()
        stop_event.wait()
        with self._inv_lock:
            # A debounce timer outliving the controller would apply a
            # stale shrink into a torn-down scheduler mid-test-teardown.
            if self._inv_timer is not None:
                self._inv_timer.cancel()
                self._inv_timer = None
        self.queue.shutdown()
        for w in workers:
            w.join(timeout=5.0)

    def _rebuild_scheduler_accounting(self) -> None:
        """Fleet-scheduler restart rebuild, EAGER: before any worker runs,
        re-reserve the slices of every cached job whose persisted state
        shows held hardware (phase Running/Backoff, or Creating with gang
        pods in the cache). The per-reconcile force-admit path covers the
        same ground lazily, but lazily is not enough: a job created right
        after an operator restart can reconcile BEFORE an old Running
        job's first pass and be admitted into capacity that is physically
        occupied (caught by the kill -9 e2e drive)."""
        for obj in self.job_informer.store.list():
            job = TPUJob.from_dict(obj)
            phase = job.status.phase
            holds = phase in (TPUJobPhase.RUNNING, TPUJobPhase.BACKOFF)
            if not holds and phase == TPUJobPhase.CREATING:
                holds = any(live_pod(p) for p in
                            self.listers.pods.by_index(INDEX_OWNER_UID,
                                                       job.uid))
            if not holds:
                continue
            priority, queue = scheduling_params(job.spec)
            # Elastic jobs re-reserve what their persisted
            # status.elastic says they actually hold (a gang shrunk to
            # 4 of 8 must not re-reserve 8 phantom slices) — the SAME
            # derivation the live admission gate uses. Serve jobs
            # likewise re-reserve their CURRENT traffic-scaled replica
            # count (serving.sched_kwargs), never the spec's original.
            demand, kwargs = elastic_mod.sched_kwargs(
                job.spec, job.status.elastic, job_demand(job.spec))
            demand, serve_kwargs = serving_mod.sched_kwargs(
                job.spec, job.status.serving, demand)
            self.scheduler.ensure_admitted(
                f"{job.namespace}/{job.name}", uid=job.uid,
                demand=demand,
                priority=priority, queue=queue,
                holds_hardware=True, **kwargs, **serve_kwargs)

    def _refresh_node_inventory(self) -> None:
        """Recompute slice capacity from the cached node objects and swap
        it into the fleet scheduler (reservations preserved; newly
        fitting gangs admit and their reconciles are woken). O(nodes) per
        node event — idempotent, so the initial sync's per-node add burst
        just converges on the same model.

        Capacity SHRINKS are debounced (config.node_debounce_seconds): a
        node whose Ready condition flaps NotReady→Ready inside the window
        produces zero scheduler calls — without the window every kubelet
        heartbeat blip would drive a shrink/regrow rebalance pair through
        FleetScheduler.update_inventory, churning the Queued head at
        fleet scale. Growth is never delayed: a new node admitting a
        queued gang applies on this very event."""
        if self._node_informer is None:
            return
        inv = SliceInventory.from_node_objects(
            self._node_informer.store.list())
        new = inv.capacities()
        debounce = float(getattr(self.config, "node_debounce_seconds", 0.0)
                         or 0.0)
        apply_now: Optional[Dict[str, int]] = None
        with self._inv_lock:
            applied = self._inv_applied
            if applied is not None and new == applied:
                # Converged (the flap healed, or a no-op relabel): any
                # pending shrink is now stale — drop it unfired.
                if self._inv_timer is not None:
                    self._inv_timer.cancel()
                    self._inv_timer = None
                return
            if applied is None or debounce <= 0:
                merged = dict(new)
            else:
                # Growth applies immediately (elementwise max); a key
                # shrinking or vanishing keeps its old value until the
                # debounce timer confirms the shrink outlived the window.
                merged = {k: max(v, applied.get(k, 0))
                          for k, v in new.items()}
                for k, v in applied.items():
                    merged.setdefault(k, v)
            if merged != new and self._inv_timer is None:
                timer = threading.Timer(debounce,
                                        self._flush_node_inventory)
                timer.daemon = True
                self._inv_timer = timer
                timer.start()
            if merged != applied:
                self._inv_applied = dict(merged)
                apply_now = merged
        if apply_now is not None:
            # Outside _inv_lock: update_inventory takes the scheduler's
            # lock and wakes reconciles — never nested under ours.
            self.scheduler.update_inventory(apply_now)

    def _on_node_update(self, old: Optional[Dict[str, Any]],
                        new: Dict[str, Any]) -> None:
        self._refresh_node_inventory()
        self._maybe_drain_cordoned(old, new)

    def _maybe_drain_cordoned(self, old: Optional[Dict[str, Any]],
                              new: Dict[str, Any]) -> None:
        """Node-maintenance drain trigger: a node whose spec just flipped
        to unschedulable (kubectl cordon — the first step of every drain)
        is about to lose its pods, so every TPUJob gang with a live pod
        bound to it is asked to cooperatively drain (verified save +
        planned exit) BEFORE the kubelet evictions start. Maintenance
        then costs one checkpoint interval, not an uncheckpointed crash;
        a payload that never reacts hits the drain deadline and is torn
        down exactly as it would have been without this hook. Edge-
        triggered on the False→True flip: a node that STAYS cordoned
        must not re-drain every re-ganged successor forever."""
        if not isinstance(new, dict):
            return
        was = bool(((old or {}).get("spec") or {}).get("unschedulable"))
        cordoned = bool((new.get("spec") or {}).get("unschedulable"))
        if was or not cordoned:
            return
        node = str((new.get("metadata") or {}).get("name") or "")
        if not node:
            return
        targets: Dict[str, Any] = {}
        with self._jobs_lock:
            for pod in self.listers.pods.list():
                if (pod.get("spec") or {}).get("nodeName") != node:
                    continue
                if not live_pod(pod):
                    continue
                md = pod.get("metadata") or {}
                for ref in md.get("ownerReferences") or []:
                    if ref.get("kind") != "TPUJob" \
                            or not ref.get("controller"):
                        continue
                    key = f"{md.get('namespace', 'default')}/{ref.get('name')}"
                    tj = self.jobs.get(key)
                    if tj is not None and key not in targets:
                        targets[key] = (tj, tj.job.status.attempt)
        for key, (tj, attempt) in targets.items():
            tj.request_maintenance_drain(node, attempt)
            self.queue.add(key)
            log.info("drain: node %s cordoned; requesting maintenance "
                     "drain of %s (attempt %d)", node, key, attempt)

    def _flush_node_inventory(self) -> None:
        """Debounce expiry: the shrink survived the window, so apply the
        capacity model exactly as the live node cache states it now (the
        cache may have healed further since the timer was armed)."""
        if self._node_informer is None:
            return
        inv = SliceInventory.from_node_objects(
            self._node_informer.store.list())
        new = inv.capacities()
        with self._inv_lock:
            self._inv_timer = None
            if new == self._inv_applied:
                return
            self._inv_applied = dict(new)
        self.scheduler.update_inventory(new)

    def _worker(self, stop_event: threading.Event,
                shard: Optional[int] = None) -> None:
        while not stop_event.is_set():
            if not self.process_next_work_item(timeout=0.5, shard=shard):
                if self.queue.is_shutdown:  # drained and closed
                    return

    def process_next_work_item(self, timeout: Optional[float] = None,
                               shard: Optional[int] = None) -> bool:
        """One queue pop → sync → ack cycle (ref: controller.go:175-203).
        Returns False if nothing was processed.

        Each cycle runs under a root tracing span, so every log record and
        every nested ``@traced`` call (sync_tpujob → reconcile → ...) shares
        one trace id, visible in ``GET /api/traces``; the reconcile duration
        feeds the ``reconcile_duration_seconds`` histogram."""
        if shard is not None:
            key = self.queue.get(timeout=timeout, shard=shard)
        else:
            key = self.queue.get(timeout=timeout)
        if key is None:
            return False
        start = self._clock()
        with tracing.span("reconcile", key=key):
            try:
                forget = self.sync_tpujob(key)
                self.metrics.inc("reconcile_total")
                if forget:
                    self.queue.forget(key)
            except Exception as e:  # noqa: BLE001 — requeue with backoff
                log.warning("error syncing %s (requeueing): %s", key, e)
                self.metrics.inc("reconcile_total")
                self.metrics.inc("reconcile_errors_total")
                self.queue.add_rate_limited(key)
            finally:
                self.metrics.observe("reconcile_duration_seconds",
                                     self._clock() - start)
                self.queue.done(key)
        return True

    # -- sync (ref: controller.go:207-267) -------------------------------------

    @traced
    def sync_tpujob(self, key: str) -> bool:
        """Reconcile one job key. Returns True when the key can be forgotten
        (terminal phase — ref: controller.go:261-265 forgets on CleanUp)."""
        namespace, _, name = key.partition("/")
        cached = self.job_informer.store.get(namespace, name)
        if cached is None:
            # Deleted: children are garbage-collected by K8s via
            # OwnerReferences (ref: controller.go:227-232 just forgets).
            with self._jobs_lock:
                tj = self.jobs.pop(key, None)
                self._hb_persisted.pop(key, None)
                self._gang_cadence.pop(key, None)
                self._serving.pop(key, None)
            self._remediation.forget(key)
            self.recorder.forget_object(namespace, name)
            self.timeline.forget_job(namespace, name)
            self.deadlines.forget(key)
            # A deleted job's slice reservation (or queue slot) frees for
            # the next pending gang.
            self.scheduler.release(key)
            # Per-job labeled series must not outlive the job (the same
            # slow-leak class the event dedup cache and the queue-depth
            # LRU bound): every registry-resident {namespace,name} series
            # is dropped here. The render-time heartbeat gauges
            # (job_last_step / job_step_time_seconds / job_tokens_per_
            # second / job_loss / job_last_checkpoint_step /
            # job_store_last_uploaded_step) never live in the registry —
            # _live_heartbeats prunes their backing map against the
            # informer cache — so gauges and counters alike go to zero
            # series for a deleted job.
            for series in ("job_goodput_ratio",
                           "job_straggler_ratio",
                           "job_world_size",
                           "job_prefetch_depth",
                           "job_checkpoint_save_failures_total",
                           "job_checkpoint_restore_fallbacks_total",
                           "job_store_upload_failures_total",
                           "compilation_cache_hits_total",
                           "store_prefetch_hits_total",
                           "store_prefetch_misses_total",
                           "job_serving_replicas_ready",
                           "job_serving_requests_per_second",
                           "job_serving_tokens_per_second",
                           "job_serving_queue_depth",
                           "job_serving_kv_cache_utilization",
                           "job_weight_reloads_total",
                           "job_drain_seconds"):
                self.metrics.remove_series(
                    series, labels={"namespace": namespace, "name": name})
            # The planned-restart counter carries the drain reason on top
            # of the job identity: drop every combination.
            for reason in DrainReason.ALL:
                self.metrics.remove_series(
                    "job_planned_restarts_total",
                    labels={"namespace": namespace, "name": name,
                            "reason": reason})
            # The serving latency gauge carries a quantile label on top of
            # the job identity: drop every combination.
            for quantile in ("0.5", "0.95"):
                self.metrics.remove_series(
                    "job_serving_latency_seconds",
                    labels={"namespace": namespace, "name": name,
                            "quantile": quantile})
            # The autotune adjustment counters carry {knob,direction} on
            # top of the job identity: drop every combination.
            from tpu_operator.payload.autotune import KNOB_OF
            for knob, direction in set(KNOB_OF.values()):
                self.metrics.remove_series(
                    "job_autotune_adjustments_total",
                    labels={"namespace": namespace, "name": name,
                            "knob": knob, "direction": direction})
            # Out-of-controller per-job state (the status server's
            # heartbeat stash) cleans up through registered listeners —
            # snapshotted under the lock, called outside it (a listener
            # takes its own lock; nesting it under _jobs_lock would mint
            # a lock-order edge for nothing).
            with self._jobs_lock:
                listeners = list(self._deletion_listeners)
            for listener in listeners:
                try:
                    listener(namespace, name)
                except Exception as e:  # noqa: BLE001 — cleanup best-effort
                    log.warning("deletion listener failed for %s: %s",
                                key, e)
            # ...and then the joblife witness audits the whole process:
            # any `# per-job:` container still holding this job's key, or
            # any registry series still carrying its identity labels, is
            # a lifecycle leak (recorded, failing the owning test / the
            # churn soak — the runtime half of the `lifecycle` analyzer
            # rule).
            if joblife.enabled() \
                    and joblife.current_epoch() == self._joblife_epoch:
                leaked = [f"joblife: metric series outlives deleted job "
                          f"{key}: {series} (add the family to the "
                          f"deletion prune list above)"
                          for series in self.metrics.job_series(namespace,
                                                                name)]
                for message in leaked:
                    joblife.record_violation(message)
                tokens = [key, (namespace, name)]
                if tj is not None:
                    tokens.append(tj.uid)
                leaked += joblife.sweep(tokens,
                                        where=f"deletion of TPUJob {key}",
                                        epoch=self._joblife_epoch)
                for message in leaked:
                    # Violations accumulate for the conftest guard / the
                    # churn soak; the log line is what a production
                    # operator surfaces.
                    log.warning("%s", message)
            return True

        job = TPUJob.from_dict(cached)
        with self._jobs_lock:
            tj = self.jobs.get(key)
            if tj is None or tj.uid != job.uid:
                # New job, or same name re-created with a new UID
                # (ref: controller.go:237-245).
                tj = TrainingJob(self.clientset, self.recorder, job,
                                 self.config, metrics=self.metrics,
                                 listers=self.listers,
                                 scheduler=self.scheduler,
                                 writeback=self.writeback)
                self.jobs[key] = tj
            else:
                tj.refresh(job)

        # Serve mode: re-evaluate beat expiry BEFORE reconciling — the
        # stale-pruning inside the serving fold only runs when another
        # beat arrives, so without this sweep a wedged SOLE replica (or a
        # fully wedged fleet) would hold its ready set — and its Services
        # — forever. The expiry epoch below (next_time_obligation) is
        # what wakes this reconcile on time.
        with self._jobs_lock:
            self._sweep_serving_locked(key, tj)
        tj.reconcile()
        # Arm (or clear) the exact-time wakeup for the job's next time
        # obligation — this is what makes deadline/stall/backoff/TTL
        # enforcement land at the configured second instead of the next
        # resync (and, for serve jobs, the serving-beat expiry).
        self.deadlines.sync(key, tj.next_time_obligation())
        return tj.job.status.phase in (
            TPUJobPhase.CLEANUP, TPUJobPhase.DONE, TPUJobPhase.FAILED
        )

    # -- heartbeats (statusserver POST /api/heartbeat → CRD status) ------------

    def record_heartbeat(self, namespace: str, name: str,
                         heartbeat: Dict[str, Any]) -> Optional[bool]:
        """Attach a payload heartbeat to the in-memory job (the status source
        of truth). Writing through the in-memory job instead of straight to
        the apiserver keeps the single-writer status discipline — a direct
        write would be clobbered by the next ``update_crd_status``.

        Returns True when recorded, False when the job is unknown (the
        TrainingJob may simply not be built yet — transient), and None when
        the heartbeat was dropped as stale (older generation); the status
        server uses the distinction to keep its liveness gauges honest.

        Persistence is *coalesced*: the key is enqueued for an immediate
        status write only for the first heartbeat, an attempt change, or
        when ``heartbeat_persist_interval`` has passed since the last
        persisted one — otherwise the in-memory copy rides along on the
        next natural reconcile (child events, informer resync). Without
        this, every 10 s post per job costs a reconcile + status PUT +
        watch-echo reconcile of pure telemetry churn."""
        from tpu_operator.util.util import parse_rfc3339

        key = f"{namespace}/{name}"
        new_t = parse_rfc3339(str(heartbeat.get("time", ""))) or 0.0
        straggler_events: list = []
        profile_events: list = []
        drain_events: list = []
        with self._jobs_lock:
            tj = self.jobs.get(key)
            if tj is None:
                return False
            # A terminating pod from a previous generation keeps posting
            # during its grace period; accepting its heartbeat would refresh
            # the stall watchdog's baseline for the new, possibly-hung
            # attempt. Drop an *explicitly* older attempt (returning None so
            # the server can tell this from an unknown job). A missing
            # attempt is treated as current — payloads that don't post it
            # must not be stall-looped after the first restart — and newer
            # is accepted: the status cache may lag a just-bumped attempt.
            hb_attempt = heartbeat.get("attempt")
            if hb_attempt is not None:
                try:
                    hb_attempt = int(hb_attempt)
                except (TypeError, ValueError):
                    hb_attempt = None
            if (hb_attempt is not None
                    and hb_attempt < tj.job.status.attempt):
                return None
            try:
                pid = int(heartbeat.get("processId") or 0)
            except (TypeError, ValueError):
                pid = 0
            # Every process's cadence feeds the straggler detector;
            # StragglerDetected events are emitted AFTER the lock drops
            # (recorder RPCs must never run under _jobs_lock).
            straggler_changed = self._apply_cadence_locked(
                key, tj, pid, heartbeat, hb_attempt, straggler_events)
            # Serving beats come from EVERY replica (each is its own
            # server): the fold aggregates readiness/traffic/latency
            # across the fleet regardless of process id.
            serving_changed = self._apply_serving_locked(
                key, tj, namespace, name, pid, heartbeat, hb_attempt)
            if pid != 0:
                # Cadence-only beat from a non-zero gang member: it exists
                # for the detector (and, in serve mode, the serving fold)
                # alone. status.lastHeartbeat and every other fold stay
                # process 0's single stream; persistence is forced only
                # when a roll-up changed.
                persist = straggler_changed or serving_changed
            else:
                self._apply_steptiming_heartbeat(tj, pid, heartbeat,
                                                 hb_attempt)
                profile_changed = self._apply_profile_heartbeat(
                    tj, heartbeat, hb_attempt, profile_events)
                drain_changed = self._apply_drain_heartbeat(
                    tj, heartbeat, hb_attempt, drain_events)
                persist = self._fold_heartbeat_locked(
                    key, tj, namespace, name, heartbeat, hb_attempt, new_t
                ) or straggler_changed or serving_changed \
                    or profile_changed or drain_changed
        for message in straggler_events:
            self.recorder.event(tj, "Warning", "StragglerDetected", message)
        for message in profile_events:
            self.recorder.event(tj, "Normal", "ProfileCaptured", message)
        for message in drain_events:
            self.recorder.event(tj, "Normal", "DrainAcked", message)
        if persist:
            self.queue.add(key)
        return True

    def _fold_heartbeat_locked(self, key: str, tj: TrainingJob,
                               namespace: str, name: str,
                               heartbeat: Dict[str, Any],
                               hb_attempt: Optional[int],
                               new_t: float) -> bool:
        """Process 0's full-stream fold (called under _jobs_lock): the
        lastHeartbeat merge plus the checkpoint/store/startup/goodput/
        stepTiming roll-ups. Returns whether the beat must persist
        immediately (vs riding the coalescing window)."""
        prev = tj.job.status.last_heartbeat
        merged = dict(heartbeat)
        if prev is not None:
            # Same generation (missing attempt = current, as above): a
            # partial post must not erase telemetry it didn't carry —
            # a liveness-only beat would otherwise wipe step/loss from
            # status and drop the per-job gauges until the next full
            # post. Resolve BOTH sides against the current attempt so
            # a stored pre-restart beat never leaks stale step/loss
            # into the new generation's heartbeat.
            now_attempt = tj.job.status.attempt
            prev_attempt = prev.get("attempt")
            hb_gen = now_attempt if hb_attempt is None else hb_attempt
            prev_gen = now_attempt if prev_attempt is None else prev_attempt
            if hb_gen == prev_gen:
                for field in ("step", "processId", "stepTimeSeconds",
                              "tokensPerSec", "loss",
                              "lastCheckpointStep",
                              "checkpointSaveFailures",
                              "checkpointRestoreFallbacks",
                              "storeLastUploadedStep",
                              "storeUploadFailures",
                              "stepTiming", "dataPlane", "serving"):
                    if field not in merged and field in prev:
                        merged[field] = prev[field]
        tj.job.status.last_heartbeat = merged
        self._apply_checkpoint_heartbeat(tj, namespace, name, heartbeat,
                                         hb_attempt)
        self._apply_store_heartbeat(tj, namespace, name, heartbeat,
                                    hb_attempt)
        self._apply_startup_heartbeat(tj, namespace, name, heartbeat,
                                      hb_attempt)
        self._apply_goodput_heartbeat(tj, namespace, name, heartbeat,
                                      hb_attempt)
        self._apply_dataplane_heartbeat(tj, namespace, name, heartbeat,
                                        hb_attempt)
        # Compare against the last *persisted* stamp, not the last
        # received one — a steady sub-interval cadence would otherwise
        # keep resetting the baseline and never persist again. A
        # startup-breakdown beat is always persisted immediately: it is
        # a one-shot per attempt, and coalescing would park it in
        # memory until the next natural reconcile (up to a resync
        # period) — observed as status.startup missing while the
        # payload already trains.
        last = self._hb_persisted.get(key)
        persist = (prev is None
                   or prev.get("attempt") != heartbeat.get("attempt")
                   or "startup" in heartbeat
                   or "drainAck" in heartbeat
                   or last is None
                   or new_t - last >= self.heartbeat_persist_interval)
        if persist:
            self._hb_persisted[key] = new_t
        return persist

    def pending_profile(self, namespace: str, name: str
                        ) -> Optional[Dict[str, Any]]:
        """The on-demand profile directive to ride process 0's next
        heartbeat ACK: ``{"id", "steps"}`` while ``status.profile`` sits
        in state Requested (set by the reconcile from the tpujobctl
        profile annotation), None otherwise. Folding the capture result
        flips the state, which stops the directive — the payload
        additionally dedups by id, so a directive raced by its own
        result is harmless."""
        with self._jobs_lock:
            tj = self.jobs.get(f"{namespace}/{name}")
            if tj is None:
                return None
            pr = tj.job.status.profile
            if not pr or pr.get("state") != "Requested":
                return None
            return {"id": str(pr.get("id", "")),
                    "steps": int(pr.get("steps") or 8)}

    def _apply_profile_heartbeat(self, tj: TrainingJob,
                                 heartbeat: Dict[str, Any],
                                 hb_attempt: Optional[int],
                                 events: list) -> bool:
        """Fold process 0's profile capture result into
        ``status.profile`` (called under _jobs_lock). The result is a
        one-shot the payload resends until ACKed, so an already-folded
        id is a duplicate, not a change; a fresh fold flips the state to
        Captured (stopping the ACK directive) and queues the
        ProfileCaptured event for emission after the lock drops."""
        pr = heartbeat.get("profile")
        if not isinstance(pr, dict) or not pr.get("id"):
            return False
        rid = str(pr["id"])
        cur = tj.job.status.profile or {}
        if cur.get("id") == rid and cur.get("state") == "Captured":
            return False
        gen = hb_attempt if hb_attempt is not None else tj.job.status.attempt
        new: Dict[str, Any] = {
            "id": rid,
            "state": "Captured",
            "capturedSteps": int(pr.get("capturedSteps") or 0),
            "attempt": int(gen),
        }
        if cur.get("steps"):
            new["steps"] = int(cur["steps"])
        if pr.get("artifactKey"):
            new["artifactKey"] = str(pr["artifactKey"])
        if heartbeat.get("time"):
            new["time"] = str(heartbeat["time"])
        tj.job.status.profile = new
        events.append(
            f"profile {rid}: captured {new['capturedSteps']} raw step "
            f"lap(s)" + (f" -> {new['artifactKey']}"
                         if new.get("artifactKey") else ""))
        return True

    def pending_drain(self, namespace: str, name: str
                      ) -> Optional[Dict[str, Any]]:
        """The cooperative-drain directive to ride process 0's next
        heartbeat ACK: ``{"id", "reason"[, "targetSlices"]}`` while
        ``status.drain`` sits in state Requested for the CURRENT attempt,
        None otherwise. Resent on every beat until the payload's drainAck
        folds the state to Acked (the payload dedups by id); a directive
        whose attempt already restarted — a real failure won the race —
        is never handed to the NEW attempt's payload, the reconcile
        resolves the stale record instead."""
        with self._jobs_lock:
            tj = self.jobs.get(f"{namespace}/{name}")
            if tj is None:
                return None
            dr = tj.job.status.drain
            if not dr or dr.get("state") != DrainState.REQUESTED:
                return None
            if int(dr.get("attempt", -1)) != int(tj.job.status.attempt):
                return None
            directive: Dict[str, Any] = {
                "id": str(dr.get("id", "")),
                "reason": str(dr.get("reason", "")),
            }
            if dr.get("targetSlices"):
                directive["targetSlices"] = int(dr["targetSlices"])
            return directive

    def _apply_drain_heartbeat(self, tj: TrainingJob,
                               heartbeat: Dict[str, Any],
                               hb_attempt: Optional[int],
                               events: list) -> bool:
        """Fold process 0's drain adoption ACK into ``status.drain``
        (called under _jobs_lock): Requested -> Acked, stamping the
        boundary step the gang agreed to drain at. The ACK is a one-shot
        the payload resends until 200'd, so a duplicate — or an ACK for
        a directive this status no longer tracks (overwritten, or the
        attempt already restarted: the satellite race) — is a no-op that
        still clears the payload's one-shot via the 200."""
        da = heartbeat.get("drainAck")
        if not isinstance(da, dict) or not da.get("id"):
            return False
        rid = str(da["id"])
        cur = tj.job.status.drain or {}
        if cur.get("id") != rid or cur.get("state") != DrainState.REQUESTED:
            return False
        gen = hb_attempt if hb_attempt is not None else tj.job.status.attempt
        if int(cur.get("attempt", -1)) != int(gen):
            # The gang restarted between the directive and this ACK (the
            # heartbeat attempt-age gate only drops OLDER beats): the new
            # attempt must not adopt a drain aimed at its predecessor.
            return False
        new = dict(cur)
        new["state"] = DrainState.ACKED
        try:
            step = int(da.get("step") or 0)
        except (TypeError, ValueError):
            step = 0
        if step > 0:
            new["drainedStep"] = step
        # ``time`` keeps the REQUEST stamp: job_drain_seconds is measured
        # request -> planned exit, and the ACK is the middle of that span.
        tj.job.status.drain = new
        events.append(
            f"drain {rid} ({cur.get('reason', '')}): payload adopted, "
            f"exiting at step boundary {step}")
        return True

    def _apply_checkpoint_heartbeat(self, tj: TrainingJob, namespace: str,
                                    name: str, heartbeat: Dict[str, Any],
                                    hb_attempt: Optional[int]) -> None:
        """Fold a heartbeat's durability fields into ``status.checkpoint``
        (called under _jobs_lock). The payload's counters are per-attempt
        (they reset on whole-group restart); status keeps lifetime totals
        by accumulating deltas, with the per-attempt baseline persisted IN
        status so an operator restart doesn't re-add the current attempt's
        count. The same deltas tick the labeled
        ``job_checkpoint_{save_failures,restore_fallbacks}_total``
        counters. ``lastCheckpointStep`` is taken as reported — it may
        legitimately move backwards when a restore fell back past a
        quarantined step."""
        relevant = [heartbeat.get(k) for k in
                    ("lastCheckpointStep", "checkpointSaveFailures",
                     "checkpointRestoreFallbacks")]
        if all(v is None for v in relevant):
            return
        gen = hb_attempt if hb_attempt is not None else tj.job.status.attempt
        ck = dict(tj.job.status.checkpoint or {})
        same_attempt = ck.get("attempt") == gen
        if heartbeat.get("lastCheckpointStep") is not None:
            ck["lastCheckpointStep"] = int(heartbeat["lastCheckpointStep"])
        for src, baseline_key, total_key, metric in (
                ("checkpointSaveFailures", "attemptSaveFailures",
                 "saveFailures", "job_checkpoint_save_failures_total"),
                ("checkpointRestoreFallbacks", "attemptRestoreFallbacks",
                 "restoreFallbacks",
                 "job_checkpoint_restore_fallbacks_total")):
            reported = heartbeat.get(src)
            if reported is None:
                continue
            reported = int(reported)
            baseline = int(ck.get(baseline_key, 0)) if same_attempt else 0
            # A reported count below the baseline means the payload's
            # counters reset (unexpected mid-attempt); count it all.
            delta = reported if reported < baseline else reported - baseline
            ck[total_key] = int(ck.get(total_key, 0)) + delta
            if delta > 0:
                self.metrics.inc(metric, delta,
                                 labels={"namespace": namespace,
                                         "name": name})
            ck[baseline_key] = reported
        ck["attempt"] = int(gen)
        if heartbeat.get("time"):
            ck["time"] = str(heartbeat["time"])
        tj.job.status.checkpoint = ck

    def _apply_store_heartbeat(self, tj: TrainingJob, namespace: str,
                               name: str, heartbeat: Dict[str, Any],
                               hb_attempt: Optional[int]) -> None:
        """Fold a heartbeat's remote-store fields into ``status.store``
        (called under _jobs_lock). Same delta discipline as the
        checkpoint fold: the payload's upload-failure counter is
        per-attempt, status keeps the lifetime total with the per-attempt
        baseline persisted IN status so operator restarts never
        double-count; deltas tick ``job_store_upload_failures_total``.
        ``lastUploadedStep`` is taken as reported — it can move backwards
        when a fresh attempt's store sees older steps than a previous
        attempt uploaded (quarantine pruned the newest)."""
        relevant = [heartbeat.get(k) for k in
                    ("storeLastUploadedStep", "storeUploadFailures")]
        if all(v is None for v in relevant):
            return
        gen = hb_attempt if hb_attempt is not None else tj.job.status.attempt
        st = dict(tj.job.status.store or {})
        same_attempt = st.get("attempt") == gen
        if heartbeat.get("storeLastUploadedStep") is not None:
            st["lastUploadedStep"] = int(heartbeat["storeLastUploadedStep"])
        reported = heartbeat.get("storeUploadFailures")
        if reported is not None:
            reported = int(reported)
            baseline = int(st.get("attemptUploadFailures", 0)) \
                if same_attempt else 0
            delta = reported if reported < baseline else reported - baseline
            st["uploadFailures"] = int(st.get("uploadFailures", 0)) + delta
            if delta > 0:
                self.metrics.inc("job_store_upload_failures_total", delta,
                                 labels={"namespace": namespace,
                                         "name": name})
            st["attemptUploadFailures"] = reported
        st["attempt"] = int(gen)
        if heartbeat.get("time"):
            st["time"] = str(heartbeat["time"])
        tj.job.status.store = st

    def _apply_goodput_heartbeat(self, tj: TrainingJob, namespace: str,
                                 name: str, heartbeat: Dict[str, Any],
                                 hb_attempt: Optional[int]) -> None:
        """Accumulate restart goodput into ``status.goodput`` (called
        under _jobs_lock): useful-step-seconds over attempt wallclock.

        Useful time adds up from two complementary sources that never
        overlap: the startup breakdown contributes ``firstStepSeconds``
        once per attempt (folded in _apply_startup_heartbeat, which calls
        here indirectly via the shared dict), and every subsequent
        heartbeat contributes ``(step - lastStep) * stepTimeSeconds`` —
        stepTimeSeconds is the payload's average over exactly that step
        span, so the product IS the wall time spent stepping between
        posts. Wallclock runs from the first entry into Creating (the
        phase timeline) to the heartbeat's receipt stamp — queue wait
        before the first start is excluded by the same re-basing the
        admission path applies to the timeline. The ratio is what fleet
        churn costs: every preemption's rendezvous + restore + recompile
        + lost-step replay shows up as the gap below 1.0."""
        from tpu_operator.util.util import parse_rfc3339

        step = heartbeat.get("step")
        if step is None:
            return
        gen = hb_attempt if hb_attempt is not None else tj.job.status.attempt
        gp = dict(tj.job.status.goodput or {})
        same_attempt = gp.get("attempt") == gen
        useful = float(gp.get("usefulStepSeconds", 0.0))
        last_step = gp.get("lastStep") if same_attempt else None
        step_time = heartbeat.get("stepTimeSeconds")
        if last_step is not None and step_time is not None \
                and int(step) > int(last_step):
            useful += (int(step) - int(last_step)) * float(step_time)
        gp["usefulStepSeconds"] = round(useful, 6)
        gp["lastStep"] = int(step)
        gp["attempt"] = int(gen)
        now = parse_rfc3339(str(heartbeat.get("time", "")))
        started = parse_rfc3339(
            tj.job.status.phase_timeline.get(TPUJobPhase.CREATING, "")) \
            or parse_rfc3339(tj.job.metadata.get("creationTimestamp", ""))
        if now is not None and started is not None and now > started:
            wall = now - started
            gp["wallclockSeconds"] = round(wall, 6)
            # Clamped: step-time averaging noise can nudge useful past
            # wall on short windows; a ratio above 1 would just confuse.
            gp["ratio"] = round(min(1.0, useful / wall), 6)
            self.metrics.set_gauge("job_goodput_ratio", gp["ratio"],
                                   labels={"namespace": namespace,
                                           "name": name})
        if heartbeat.get("time"):
            gp["time"] = str(heartbeat["time"])
        tj.job.status.goodput = gp

    def _apply_startup_heartbeat(self, tj: TrainingJob, namespace: str,
                                 name: str, heartbeat: Dict[str, Any],
                                 hb_attempt: Optional[int]) -> None:
        """Fold a heartbeat's startup breakdown into ``status.startup``
        (called under _jobs_lock). The breakdown is posted once per attempt
        (right after the first step); the per-stage durations feed the
        ``job_startup_seconds{stage}`` histograms and a cache-hit ticks
        ``compilation_cache_hits_total`` — guarded per attempt, so the
        payload retrying a failed post cannot double-observe."""
        from tpu_operator.payload.startup import STAGE_FIELDS

        su = heartbeat.get("startup")
        if not isinstance(su, dict) or not su:
            return
        gen = hb_attempt if hb_attempt is not None else tj.job.status.attempt
        cur = tj.job.status.startup or {}
        already = cur.get("attempt") == gen
        new: Dict[str, Any] = {}
        for field in STAGE_FIELDS.values():
            if su.get(field) is not None:
                new[field] = float(su[field])
        if su.get("cacheHit") is not None:
            new["cacheHit"] = bool(su["cacheHit"])
        if su.get("prefetchHit") is not None:
            new["prefetchHit"] = bool(su["prefetchHit"])
        if not new:
            return
        new["attempt"] = int(gen)
        if heartbeat.get("time"):
            new["time"] = str(heartbeat["time"])
        tj.job.status.startup = new
        if already:
            return
        for stage, field in STAGE_FIELDS.items():
            if field in new:
                self.metrics.observe("job_startup_seconds", new[field],
                                     labels={"stage": stage.lower()})
        if new.get("cacheHit"):
            self.metrics.inc("compilation_cache_hits_total",
                             labels={"namespace": namespace, "name": name})
        if new.get("prefetchHit") is not None:
            # Once per attempt (guarded by ``already``, like the cache-hit
            # tick): did the rendezvous-overlapped store prefetch deliver?
            self.metrics.inc("store_prefetch_hits_total"
                             if new["prefetchHit"]
                             else "store_prefetch_misses_total",
                             labels={"namespace": namespace, "name": name})
        if new.get("firstStepSeconds") is not None:
            # The attempt's first step is useful work the goodput fold
            # can't see (the first heartbeat carries no stepTimeSeconds);
            # credit it here, once per attempt.
            gp = dict(tj.job.status.goodput or {})
            gp["usefulStepSeconds"] = round(
                float(gp.get("usefulStepSeconds", 0.0))
                + float(new["firstStepSeconds"]), 6)
            tj.job.status.goodput = gp

    def _apply_dataplane_heartbeat(self, tj: TrainingJob, namespace: str,
                                   name: str, heartbeat: Dict[str, Any],
                                   hb_attempt: Optional[int]) -> None:
        """Fold a heartbeat's self-tuning data-plane knob report into
        ``status.dataPlane`` (called under _jobs_lock). Live values
        (prefetch depth, host path, effective checkpoint cadence) are
        taken as reported and ``job_prefetch_depth`` tracks the depth;
        the per-knob adjustment counters follow the checkpoint fold's
        delta discipline — the payload's counters are per-attempt (reset
        on whole-group restart), status keeps lifetime totals by
        accumulating deltas against a per-attempt baseline persisted IN
        status, and each delta ticks
        ``job_autotune_adjustments_total{knob,direction}``."""
        from tpu_operator.payload.autotune import ADJUSTMENT_KEYS, KNOB_OF

        dp = heartbeat.get("dataPlane")
        if not isinstance(dp, dict) or not dp:
            return
        gen = hb_attempt if hb_attempt is not None else tj.job.status.attempt
        cur = dict(tj.job.status.data_plane or {})
        same_attempt = cur.get("attempt") == gen
        new: Dict[str, Any] = {}
        for field in ("prefetchDepth", "checkpointIntervalSteps",
                      "hostDropped"):
            if dp.get(field) is not None:
                new[field] = int(dp[field])
        if isinstance(dp.get("hostAsync"), bool):
            # The statusserver door rejects non-bools; direct callers of
            # record_heartbeat get the same strictness, not a coercion
            # that turns "false" into True.
            new["hostAsync"] = dp["hostAsync"]
        totals = dict(cur.get("adjustments") or {})
        baselines = dict(cur.get("attemptAdjustments") or {}) \
            if same_attempt else {}
        reported_adj = dp.get("adjustments") or {}
        for key in ADJUSTMENT_KEYS:
            reported = reported_adj.get(key)
            if reported is None:
                continue
            reported = int(reported)
            baseline = int(baselines.get(key, 0))
            # Below-baseline means the payload's counters reset
            # (unexpected mid-attempt); count it all — the checkpoint
            # fold's convention.
            delta = reported if reported < baseline else reported - baseline
            if delta > 0:
                totals[key] = int(totals.get(key, 0)) + delta
                knob, direction = KNOB_OF[key]
                self.metrics.inc("job_autotune_adjustments_total", delta,
                                 labels={"namespace": namespace,
                                         "name": name, "knob": knob,
                                         "direction": direction})
            baselines[key] = reported
        if totals:
            new["adjustments"] = totals
        if baselines:
            new["attemptAdjustments"] = baselines
        new["attempt"] = int(gen)
        if heartbeat.get("time"):
            new["time"] = str(heartbeat["time"])
        tj.job.status.data_plane = new
        if new.get("prefetchDepth") is not None:
            self.metrics.set_gauge("job_prefetch_depth",
                                   new["prefetchDepth"],
                                   labels={"namespace": namespace,
                                           "name": name})

    def _apply_steptiming_heartbeat(self, tj: TrainingJob, pid: int,
                                    heartbeat: Dict[str, Any],
                                    hb_attempt: Optional[int]) -> None:
        """Fold process 0's ``stepTiming`` phase digest into
        ``status.stepTiming`` (called under _jobs_lock) and observe the
        ``job_step_phase_seconds{phase}`` histograms. Each digest
        summarizes a DISJOINT window of steps (the payload drains its
        window per post), so observing every digest's per-phase p95 once
        builds an unbiased time-local distribution — no double counting,
        no dedup bookkeeping needed."""
        st = heartbeat.get("stepTiming")
        if not isinstance(st, dict) or not st:
            return
        gen = hb_attempt if hb_attempt is not None else tj.job.status.attempt
        folded = dict(st)
        folded["attempt"] = int(gen)
        folded["processId"] = int(pid)
        if heartbeat.get("time"):
            folded["time"] = str(heartbeat["time"])
        tj.job.status.step_timing = folded
        for field, stats in (st.get("phases") or {}).items():
            p95 = (stats or {}).get("p95Seconds")
            if p95 is not None:
                self.metrics.observe("job_step_phase_seconds", float(p95),
                                     labels={"phase": field})

    def _apply_serving_locked(self, key: str, tj: TrainingJob,
                              namespace: str, name: str, pid: int,
                              heartbeat: Dict[str, Any],
                              hb_attempt: Optional[int]) -> bool:
        """Serving-mode fold (called under _jobs_lock): aggregate one
        replica's serving beat into the per-job fleet view and rewrite
        ``status.serving``. Every replica posts (each is an independent
        server); the roll-up is:

        - ``replicasReady``: replicas whose freshest beat says ``ready``
          (stale beats expire after SERVING_EXPIRY_SECONDS — a wedged
          replica must drop out of routing without posting anything);
        - ``requestsPerSecond``: the fleet sum — the signal the scaler
          divides by ``targetRequestsPerSecondPerReplica``;
        - ``p50/p95LatencySeconds``: the WORST ready replica's value
          (routing decisions care about the tail, and an average across
          replicas would hide exactly the replica the straggler guard
          wants visible);
        - ``loadedStep``: the MINIMUM over ready replicas — the snapshot
          step the whole fleet is guaranteed to serve; it advances only
          once the rolling reload completes everywhere;
        - ``reloads``: lifetime weight-reload total, delta-accounted per
          process against baselines persisted IN status (the checkpoint-
          counter convention: operator restarts never double-count) —
          each delta ticks ``job_weight_reloads_total``;
        - ``desiredReplicas``: the traffic-derived target within
          ``spec.serving`` — consumed by the reconcile's scale sync.

        Returns True when a MATERIAL field changed (readiness membership,
        desired count, loadedStep, a reload landed): the caller forces a
        persist + reconcile; rps/latency drift rides the coalescing
        window like any other telemetry."""
        sv_beat = heartbeat.get("serving")
        if not isinstance(sv_beat, dict) or not sv_beat:
            return False
        if not serving_mod.is_serve(tj.job.spec):
            return False
        gen = hb_attempt if hb_attempt is not None else tj.job.status.attempt
        state = self._serving.get(key)
        if state is not None and int(gen) < int(state.get("attempt", 0)):
            return False  # stale beat from a dead generation
        if state is None or state.get("attempt") != int(gen):
            state = {"attempt": int(gen), "procs": {}}
            self._serving[key] = state
        now = self._wall_clock()
        entry: Dict[str, Any] = {"seen": now, "stale": False}
        entry["ready"] = bool(sv_beat.get("ready"))
        for field, key_ in (("requestsPerSecond", "rps"),
                            ("tokensPerSecond", "tps"),
                            ("kvCacheUtilization", "kvutil"),
                            ("p50LatencySeconds", "p50"),
                            ("p95LatencySeconds", "p95")):
            if sv_beat.get(field) is not None:
                entry[key_] = float(sv_beat[field])
        for field in ("queueDepth", "loadedStep", "reloads"):
            if sv_beat.get(field) is not None:
                entry[field] = int(sv_beat[field])
        state["procs"][int(pid)] = entry
        _expire_serving_procs(state["procs"], now)
        while len(state["procs"]) > SERVING_MAX_PROCS:
            del state["procs"][min(state["procs"],
                                   key=lambda p: state["procs"][p]["seen"])]

        procs = state["procs"]
        ready_pids = {p for p, e in procs.items() if e.get("ready")}
        cur = dict(tj.job.status.serving or {})
        same_attempt = cur.get("attempt") == int(gen)
        prev_ready = cur.get("replicasReady")
        prev_desired = cur.get("desiredReplicas")
        prev_loaded = cur.get("loadedStep")
        new: Dict[str, Any] = {}
        if cur.get("replicas"):
            new["replicas"] = int(cur["replicas"])
        new["replicasReady"] = len(ready_pids)
        total_rps = sum(e.get("rps", 0.0) for e in procs.values())
        new["requestsPerSecond"] = round(total_rps, 3)
        # Fleet decode throughput and queued backlog are SUMS (every
        # replica's contribution counts, ready or mid-reload — its queue
        # is real demand either way); cache pressure is the WORST
        # replica's pool utilization (1.0 anywhere means admissions are
        # blocking on pages there, an average would hide it).
        new["tokensPerSecond"] = round(
            sum(e.get("tps", 0.0) for e in procs.values()), 3)
        new["queueDepth"] = sum(int(e.get("queueDepth", 0))
                                for e in procs.values())
        kvutil = [e["kvutil"] for e in procs.values() if "kvutil" in e]
        if kvutil:
            new["kvCacheUtilization"] = round(max(kvutil), 4)
        for key_, field in (("p50", "p50LatencySeconds"),
                            ("p95", "p95LatencySeconds")):
            vals = [e[key_] for p, e in procs.items()
                    if p in ready_pids and key_ in e]
            if vals:
                new[field] = round(max(vals), 6)
        loaded = [e["loadedStep"] for p, e in procs.items()
                  if p in ready_pids and "loadedStep" in e]
        if loaded:
            new["loadedStep"] = min(loaded)
        # Reload delta accounting (per process, baselines in status).
        totals = int(cur.get("reloads", 0))
        baselines = {str(k): int(v)
                     for k, v in (cur.get("attemptReloads") or {}).items()} \
            if same_attempt else {}
        reported = entry.get("reloads")
        if reported is not None:
            baseline = baselines.get(str(int(pid)), 0)
            delta = reported if reported < baseline else reported - baseline
            if delta > 0:
                totals += delta
                self.metrics.inc("job_weight_reloads_total", delta,
                                 labels={"namespace": namespace,
                                         "name": name})
            baselines[str(int(pid))] = reported
        if totals:
            new["reloads"] = totals
        if baselines:
            new["attemptReloads"] = baselines
        fresh = [e["seen"] for e in procs.values() if not e.get("stale")]
        next_expiry = (min(fresh) + SERVING_EXPIRY_SECONDS) if fresh \
            else None
        desired = serving_mod.desired_replicas(total_rps, tj.job.spec)
        current = int(cur.get("replicas") or 0) \
            or serving_mod.base_replicas(tj.job.spec)
        if len(fresh) < current and desired < current:
            # Partial fleet report (startup, a replica mid-restart, its
            # beats expired): the aggregate under-counts the real
            # traffic, and acting on it would scale DOWN on silence —
            # the first replica to post after a deploy shrank the fleet
            # under everyone else (caught by the real-binary drive).
            # Hold the current size; scale-up still acts on partial data
            # (over-provisioning is the safe direction for serving).
            desired = current
        new["desiredReplicas"] = int(desired)
        new["attempt"] = int(gen)
        if heartbeat.get("time"):
            new["time"] = str(heartbeat["time"])
        tj.job.status.serving = new
        tj.update_serving_ready(int(gen), ready_pids,
                                known_pids=set(procs),
                                next_expiry=next_expiry)
        self.metrics.set_gauge("job_serving_replicas_ready",
                               new["replicasReady"],
                               labels={"namespace": namespace,
                                       "name": name})
        self.metrics.set_gauge("job_serving_requests_per_second",
                               new["requestsPerSecond"],
                               labels={"namespace": namespace,
                                       "name": name})
        self.metrics.set_gauge("job_serving_tokens_per_second",
                               new["tokensPerSecond"],
                               labels={"namespace": namespace,
                                       "name": name})
        self.metrics.set_gauge("job_serving_queue_depth",
                               new["queueDepth"],
                               labels={"namespace": namespace,
                                       "name": name})
        if new.get("kvCacheUtilization") is not None:
            self.metrics.set_gauge("job_serving_kv_cache_utilization",
                                   new["kvCacheUtilization"],
                                   labels={"namespace": namespace,
                                           "name": name})
        for q, field in (("0.5", "p50LatencySeconds"),
                         ("0.95", "p95LatencySeconds")):
            if new.get(field) is not None:
                self.metrics.set_gauge(
                    "job_serving_latency_seconds", new[field],
                    labels={"namespace": namespace, "name": name,
                            "quantile": q})
        return (new["replicasReady"] != prev_ready
                or new["desiredReplicas"] != prev_desired
                or new.get("loadedStep") != prev_loaded
                or int(cur.get("reloads", 0)) != totals
                or not same_attempt)

    def _sweep_serving_locked(self, key: str, tj: TrainingJob) -> None:
        """Reconcile-time serving-expiry sweep (called under _jobs_lock):
        prune beats older than SERVING_EXPIRY_SECONDS and refresh the
        readiness roll-up + handoff from what remains — the path that
        drops a wedged replica (one that stopped posting ANYTHING) out of
        routing. The serving fold does the same pruning per incoming
        beat; this covers the no-beats-at-all case, woken exactly on time
        by the expiry obligation."""
        if not serving_mod.is_serve(tj.job.spec):
            return
        state = self._serving.get(key)
        if state is None or state.get("attempt") != tj.job.status.attempt:
            return
        now = self._wall_clock()
        procs = state["procs"]
        staled = _expire_serving_procs(procs, now)
        if not staled:
            return
        ready_pids = {p for p, e in procs.items() if e.get("ready")}
        fresh = [e["seen"] for e in procs.values() if not e.get("stale")]
        next_expiry = (min(fresh) + SERVING_EXPIRY_SECONDS) if fresh \
            else None
        cur = dict(tj.job.status.serving or {})
        cur["replicasReady"] = len(ready_pids)
        cur["requestsPerSecond"] = round(
            sum(e.get("rps", 0.0) for e in procs.values()), 3)
        tj.job.status.serving = cur
        tj.update_serving_ready(tj.job.status.attempt, ready_pids,
                                known_pids=set(procs),
                                next_expiry=next_expiry)
        self.metrics.set_gauge("job_serving_replicas_ready",
                               len(ready_pids),
                               labels={"namespace": tj.job.namespace,
                                       "name": tj.job.name})
        log.info("serving: %s expired %d stale replica beat(s); "
                 "%d ready", key, len(staled), len(ready_pids))

    def _apply_cadence_locked(self, key: str, tj: TrainingJob, pid: int,
                              heartbeat: Dict[str, Any],
                              hb_attempt: Optional[int],
                              events: list) -> bool:
        """Gang straggler detector (called under _jobs_lock): fold one
        process's step cadence into the per-job map and re-evaluate. A
        member whose p95 LOCAL step time exceeds the gang median by
        ``spec.stepTrace.stragglerRatio`` (default 2.0) is flagged into
        ``status.stragglers``; the worst member's ratio is the
        ``job_straggler_ratio`` gauge, and a NEWLY flagged process
        appends a StragglerDetected message to ``events`` (the caller
        emits after releasing the lock — recorder RPCs never run under
        _jobs_lock). Returns True when the flagged roll-up changed (the
        caller forces a status persist: a straggler flag is an eviction
        signal, not coalescable telemetry).

        The signal is ``stepLocalP95Seconds`` — per-step time MINUS the
        compute wait — because a synchronous gang's collectives equalize
        everything else: one slow member paces every step, so whole-step
        cadence (and the compute wait, which IS the collective wait)
        converges to the same number on every process and can never
        single anyone out. The local share — input wait, dispatch,
        checkpoint, host work — stays genuinely per-process, so a slow
        input pipeline, GC-bound host, or sick NIC stands out at its
        source. (A slow *device* is host-invisible by the same argument
        and needs device-level telemetry — out of scope here.) Fallback
        for digest-less payloads: whole-step p95 / stepTimeSeconds,
        meaningful only when the payload is not gang-synchronized
        (PER_POD compat mode). A materiality floor skips flags whose
        local time is under 2% of the gang's median step — µs-level
        ratio noise between healthy hosts is not a straggler."""
        trace_spec = tj.job.spec.step_trace
        if trace_spec is not None and not trace_spec.enabled:
            return False
        st = heartbeat.get("stepTiming")
        local_p95 = step_p95 = None
        if isinstance(st, dict):
            local_p95 = st.get("stepLocalP95Seconds")
            step_p95 = st.get("stepP95Seconds")
        value = local_p95
        if value is None:
            value = step_p95 if step_p95 is not None \
                else heartbeat.get("stepTimeSeconds")
        gen = hb_attempt if hb_attempt is not None else tj.job.status.attempt
        cleared = False
        state = self._gang_cadence.get(key)
        if state is not None and int(gen) < int(state.get("attempt", 0)):
            # Stale beat from a generation OLDER than the one the
            # detector already tracks: the record_heartbeat age gate only
            # fires once the reconcile bumps status.attempt, so in the
            # window between the new gang's first beat and that bump, a
            # terminating pod's last beats used to RESET the detector
            # back to the dead generation — wiping the live gang's
            # accumulated cadence and force-persisting a spurious
            # stragglers clear on every flip (found by the seeded
            # interleaving schedule over fold-vs-attempt-reset). The
            # detector only moves forward.
            return False
        if state is None or state.get("attempt") != int(gen):
            # New attempt (or first beat): stale cadence from the previous
            # generation must not flag the new gang — and a flag the OLD
            # generation earned must not outlive it in status (the
            # restart likely replaced that very replica). The clear is a
            # roll-up change, so it persist-forces like any other.
            state = {"attempt": int(gen), "procs": {}, "flagged": set()}
            self._gang_cadence[key] = state
            if tj.job.status.stragglers:
                tj.job.status.stragglers = []
                cleared = True
        if value is None:
            return cleared
        step = heartbeat.get("step")
        now = self._wall_clock()
        state["procs"][int(pid)] = {
            "p95": float(value),
            "step_p95": (float(step_p95) if step_p95 is not None else None),
            "step": int(step) if step is not None else 0,
            "time": str(heartbeat.get("time", "")),
            "seen": now,
        }
        # Hygiene before evaluating: expire members that stopped posting
        # (dead pod, replaced replica) and bound the map against bogus
        # ever-new processIds.
        stale = [p for p, entry in state["procs"].items()
                 if now - entry["seen"] > CADENCE_EXPIRY_SECONDS]
        for p in stale:
            del state["procs"][p]
        while len(state["procs"]) > CADENCE_MAX_PROCS:
            del state["procs"][min(state["procs"],
                                   key=lambda p: state["procs"][p]["seen"])]

        def rollup_changed(flagged: Dict[int, Dict[str, Any]]) -> bool:
            # Compare against what STATUS currently says, not the
            # in-memory detector state: a rebuilt detector (operator
            # restart, attempt reset) starts empty while status may
            # still carry flags — the empty evaluation must clear them
            # and persist the clear. The roll-up is rewritten ONLY on a
            # membership change: entries are a snapshot of the flagging
            # evaluation (the gauge tracks live ratio drift) — per-beat
            # value refreshes would make every reconcile see a
            # "critical" stragglers delta and bypass the writeback
            # limiter for the whole flagged duration.
            prev = {int(s.get("processId", -1))
                    for s in (tj.job.status.stragglers or [])}
            if set(flagged) == prev:
                return False
            tj.job.status.stragglers = [flagged[p] for p in sorted(flagged)]
            return True

        procs = state["procs"]
        if len(procs) < 2:
            # A gang of one has no peers to straggle behind; also covers
            # single-process jobs, which never see a second cadence
            # stream. The empty evaluation still feeds the remediation
            # tracker: a flag cleared THIS way (the flagged member's
            # cadence entry expired, the gang shrank) must reset its
            # patience window, or a stale window would fire an instant
            # remediation on a later one-beat re-flag.
            return (rollup_changed({}) or cleared
                    or self._remediation_due_locked(key, tj, gen, set(),
                                                    now))
        values = sorted(p["p95"] for p in procs.values())
        mid = len(values) // 2
        median = (values[mid] if len(values) % 2
                  else (values[mid - 1] + values[mid]) / 2.0)
        if median <= 0:
            return (rollup_changed({}) or cleared
                    or self._remediation_due_locked(key, tj, gen, set(),
                                                    now))
        step_p95s = sorted(p["step_p95"] for p in procs.values()
                           if p.get("step_p95") is not None)
        median_step = step_p95s[len(step_p95s) // 2] if step_p95s else None
        threshold = (trace_spec.straggler_ratio if trace_spec is not None
                     else DEFAULT_STRAGGLER_RATIO)
        worst = 1.0
        flagged: Dict[int, Dict[str, Any]] = {}
        for proc_id, p in procs.items():
            ratio = p["p95"] / median
            if median_step is not None and p["p95"] < 0.02 * median_step:
                # Materiality floor: µs-level local time is ratio noise
                # between healthy hosts, not a straggler — suppressed
                # from the flag AND from the gauge (the gauge's help
                # text promises "above threshold = flagged", so it must
                # never advertise a ratio the detector itself discarded).
                continue
            worst = max(worst, ratio)
            if ratio < threshold:
                continue
            flagged[proc_id] = {
                "processId": proc_id,
                "p95Seconds": round(p["p95"], 6),
                "gangMedianSeconds": round(median, 6),
                "ratio": round(ratio, 3),
                "step": p["step"],
                "time": p["time"],
            }
        self.metrics.set_gauge(
            "job_straggler_ratio", round(worst, 3),
            labels={"namespace": tj.job.namespace, "name": tj.job.name})
        for proc_id in sorted(set(flagged) - state["flagged"]):
            entry = flagged[proc_id]
            events.append(
                f"process {proc_id} is pacing the gang: p95 local step "
                f"time {entry['p95Seconds']:.3f}s vs gang median "
                f"{entry['gangMedianSeconds']:.3f}s "
                f"({entry['ratio']:.1f}x >= {threshold:.1f}x threshold)")
        # Event dedup keys on the detector's own memory (once per
        # attempt+process); the persist decision keys on the STATUS delta.
        state["flagged"] = set(flagged)
        due = self._remediation_due_locked(key, tj, gen, set(flagged), now)
        return rollup_changed(flagged) or cleared or due

    def _remediation_due_locked(self, key: str, tj: TrainingJob, gen: Any,
                                flagged: set, now: float) -> bool:
        """Remediation pacing (spec.elastic.stragglerPolicy), called
        under _jobs_lock with EVERY straggler evaluation's flag set —
        including the empty ones, so a cleared flag resets its patience
        window. A member staying flagged past the window is handed to
        the TrainingJob's next reconcile for replace/shed — exactly
        once per (attempt, process). The handoff is a field set, not an
        RPC, so it is safe under the lock; returning True forces the
        enqueue that runs the reconcile."""
        policy, patience = elastic_mod.straggler_policy(tj.job.spec)
        if policy == elastic_mod.StragglerPolicy.NONE:
            return False
        due = False
        for proc_id in self._remediation.observe(key, int(gen), flagged,
                                                 now, patience):
            tj.request_remediation(
                proc_id, policy, int(gen),
                retry=lambda p=proc_id, g=int(gen):
                    self._remediation.retry(key, g, p))
            due = True
            log.info("straggler remediation due: %s process %d "
                     "(%s after %.0fs flagged)", key, proc_id,
                     policy, patience)
        return due

    # -- GC (wires the reference's dead --gc-interval flag) --------------------

    @traced
    def run_gc_once(self) -> int:
        """Delete children labeled with our group key whose owning TPUJob no
        longer exists. Returns number of objects deleted. (Replaces the
        reference's stale cleanup script, hack/scripts/cleanup_clusters.sh.)"""
        deleted = 0
        live_jobs = {
            object_key(o) for o in self.clientset.tpujobs.list(self.namespace)
        }
        for resource in ("pods", "services"):
            client = getattr(self.clientset, resource)
            for obj in client.list(self.namespace, label_selector=LABEL_GROUP_KEY):
                md = obj.get("metadata") or {}
                owners = [
                    r for r in md.get("ownerReferences") or []
                    if r.get("kind") == "TPUJob"
                ]
                if not owners:
                    continue
                ns = md.get("namespace", "default")
                if any(f"{ns}/{r.get('name')}" in live_jobs for r in owners):
                    continue
                try:
                    client.delete(ns, md.get("name", ""))
                    deleted += 1
                    self.metrics.inc("gc_deleted_total")
                except errors.ApiError as e:
                    if not errors.is_not_found(e):
                        log.warning("gc delete failed: %s", e)
        return deleted

    def run_gc_loop(self, interval: float, stop_event: threading.Event) -> None:
        while not stop_event.wait(interval):
            try:
                n = self.run_gc_once()
                if n:
                    log.info("gc removed %d orphaned objects", n)
            except Exception as e:  # noqa: BLE001
                log.warning("gc sweep failed: %s", e)
