"""Kubernetes Event recording.

Reference parity: the event broadcaster wired in controller.New
(controller.go:96-100) and the SuccessfulCreate/FailedCreate events recorded
on the MXJob from the replica sync paths (replicas.go:520-524,553-557).
client-go's broadcaster machinery (watch fan-out, aggregation, rate limits)
exists because many controllers share one stream; this operator needs the
recorder surface only, so events are written directly through the clientset
with per-(object,reason) aggregation counts — same API-visible result
(``kubectl describe tpujob`` shows the event trail), far less machinery.
"""

from __future__ import annotations

import collections
import datetime
import logging
from typing import Any, Callable, List, Optional, Tuple

from tpu_operator.client import errors
from tpu_operator.util.util import rand_string
from tpu_operator.util import joblife, lockdep

log = logging.getLogger(__name__)

# Dedup-cache bound: entries beyond this are evicted least-recently-used.
# Unbounded, the cache grew one entry per distinct (object, reason, message)
# forever across job churn — a slow leak in a long-lived operator.
DEFAULT_SEEN_CAP = 1024


def _now() -> str:
    return (
        datetime.datetime.now(datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%SZ")
    )


class EventRecorder:
    """Records events against involved objects (ref: record.EventRecorder as
    used at controller.go:97-100; component name "tpu-operator")."""

    def __init__(self, clientset: Any, component: str = "tpu-operator",
                 seen_cap: int = DEFAULT_SEEN_CAP,
                 metrics: Optional[Any] = None):
        self.clientset = clientset
        self.component = component
        self.metrics = metrics
        self._seen_cap = max(1, seen_cap)
        self._lock = lockdep.lock("EventRecorder._lock")
        # LRU: (ns, name, reason, message) -> (event_name, count)
        self._seen: "collections.OrderedDict[Tuple[str, str, str, str], Tuple[str, int]]" = (
            joblife.track("EventRecorder._seen",
                          kind="ordered"))  # per-job: forget_object; guarded-by: _lock
        # Side observers of the event stream (the timeline store): called
        # with (namespace, name, type, reason, message) for EVERY event()
        # call — including aggregated repeats — before the apiserver RPC,
        # so observers see events even when recording fails. Registered
        # once at wiring time, before any event flows; reads are
        # therefore lock-free by the same single-writer argument as
        # tracing._enabled.
        self._observers: List[Callable[[str, str, str, str, str], None]] = []

    def add_observer(self,
                     observer: Callable[[str, str, str, str, str], None]
                     ) -> None:
        """Register an event-stream observer (idempotent per callable)."""
        if observer not in self._observers:
            self._observers.append(observer)

    def forget_object(self, namespace: str, name: str) -> int:
        """Drop dedup entries for a deleted object (the controller calls this
        when a TPUJob disappears), so churn never pins cache slots. Returns
        the number of entries pruned."""
        with self._lock:
            stale = [k for k in self._seen if k[0] == namespace and k[1] == name]
            for k in stale:
                del self._seen[k]
        if stale and self.metrics is not None:
            self.metrics.inc("events_pruned_total", len(stale))
        return len(stale)

    def event(self, obj: Any, event_type: str, reason: str, message: str) -> None:
        """``obj`` is anything with .metadata/.name/.namespace (TrainingJob or
        TPUJob). Failures to record never break reconcile (events are
        best-effort, as in client-go)."""
        for observer in self._observers:
            try:
                observer(obj.namespace, obj.name, event_type, reason,
                         message)
            except Exception as e:  # noqa: BLE001 — observers best-effort too
                log.debug("event observer failed for %s: %s", reason, e)
        try:
            self._record(obj, event_type, reason, message)
        except Exception as e:  # noqa: BLE001 — best-effort by design
            log.debug("dropping event %s/%s: %s", reason, message, e)

    def _record(self, obj: Any, event_type: str, reason: str, message: str) -> None:
        namespace = obj.namespace
        involved = {
            "apiVersion": obj.metadata.get("apiVersion", "tpuoperator.dev/v1alpha1"),
            "kind": "TPUJob",
            "name": obj.name,
            "namespace": namespace,
            "uid": obj.metadata.get("uid", ""),
        }
        key = (namespace, obj.name, reason, message)
        # The apiserver round trips run OUTSIDE the dedup lock: with it
        # held, a slow apiserver serialized every reconcile worker that
        # wanted to record ANY event behind one thread's RPC. The race this
        # opens (two threads recording the same key concurrently) costs at
        # worst one extra Event object, which aggregation folds from then
        # on — strictly better than a control-plane-wide convoy.
        with self._lock:
            prior = self._seen.get(key)
            if prior:
                self._seen.move_to_end(key)
        if prior:
            name, count = prior
            try:
                ev = self.clientset.events.get(namespace, name)
                ev["count"] = count + 1
                ev["lastTimestamp"] = _now()
                self.clientset.events.update(namespace, ev)
                with self._lock:
                    self._seen[key] = (name, count + 1)
                if self.metrics is not None:
                    self.metrics.inc("events_emitted_total")
                    self.metrics.inc("events_aggregated_total")
                return
            except errors.ApiError as e:
                # Fall through to create fresh — but say so: a silently
                # swallowed aggregation failure looked exactly like
                # first-time recording, hiding e.g. a permissions change
                # that 403s every update.
                log.debug("event aggregation of %s failed (%s); "
                          "creating fresh", name, e)
        name = f"{obj.name}.{rand_string(10)}"
        event = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": name, "namespace": namespace},
            "involvedObject": involved,
            "reason": reason,
            "message": message,
            "type": event_type,
            "count": 1,
            "firstTimestamp": _now(),
            "lastTimestamp": _now(),
            "source": {"component": self.component},
        }
        self.clientset.events.create(namespace, event)
        evicted = 0
        with self._lock:
            self._seen[key] = (name, 1)
            self._seen.move_to_end(key)
            while len(self._seen) > self._seen_cap:
                self._seen.popitem(last=False)
                evicted += 1
        if self.metrics is not None:
            self.metrics.inc("events_emitted_total")
            if evicted:
                self.metrics.inc("events_pruned_total", evicted)
