"""Leader election on a coordination.k8s.io Lease.

Reference parity: cmd/mx-operator/app/server.go:106-129 — the reference
runs ``election.RunOrDie`` over an **Endpoints** lock named ``tf-operator``
with lease 15 s / renew 5 s / retry 3 s (server.go:48-52); exactly one
operator replica reconciles at a time, and losing the lease kills the
process (OnStoppedLeading → fatal, server.go:98-102).

Endpoints locks are deprecated upstream; this implementation uses the
modern Lease resource with the same cadence and the same semantics:
``run`` blocks, invoking ``on_started_leading(stop_event)`` once acquired,
and sets the stop event + calls ``on_stopped_leading`` if the lease is lost.

Clock skew note: like client-go, expiry is judged on the *local* clock by
re-reading ``renewTime``; the margin built into lease_duration−renew_deadline
absorbs reasonable skew.
"""

from __future__ import annotations

import datetime
import logging
import socket
import threading
from typing import Any, Callable, Optional

from tpu_operator.client import errors
from tpu_operator.util.util import rand_string

log = logging.getLogger(__name__)

LEASE_DURATION = 15.0   # ref: server.go:49
RENEW_DEADLINE = 5.0    # ref: server.go:50 (renew every 5s while leading)
RETRY_PERIOD = 3.0      # ref: server.go:51

LOCK_NAME = "tpu-operator"  # ref: the "tf-operator" Endpoints lock, server.go:108


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def _fmt(ts: datetime.datetime) -> str:
    return ts.strftime("%Y-%m-%dT%H:%M:%S.%fZ")


def _parse(ts: str) -> Optional[datetime.datetime]:
    for fmt in ("%Y-%m-%dT%H:%M:%S.%fZ", "%Y-%m-%dT%H:%M:%SZ"):
        try:
            return datetime.datetime.strptime(ts, fmt).replace(
                tzinfo=datetime.timezone.utc
            )
        except ValueError:
            continue
    return None


def default_identity() -> str:
    """hostname + random suffix (ref: server.go:105 id = hostname)."""
    return f"{socket.gethostname()}-{rand_string(6)}"


class LeaderElector:
    def __init__(
        self,
        clientset: Any,
        namespace: str,
        identity: str = "",
        name: str = LOCK_NAME,
        lease_duration: float = LEASE_DURATION,
        renew_deadline: float = RENEW_DEADLINE,
        retry_period: float = RETRY_PERIOD,
    ):
        self.clientset = clientset
        self.namespace = namespace
        self.identity = identity or default_identity()
        self.name = name
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.is_leader = threading.Event()

    # -- lease record I/O -----------------------------------------------------

    def _lease_spec(self, transitions: int) -> dict:
        return {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(self.lease_duration),
            "acquireTime": _fmt(_now()),
            "renewTime": _fmt(_now()),
            "leaseTransitions": transitions,
        }

    def try_acquire_or_renew(self) -> bool:
        """One CAS round against the Lease object. Returns True if we hold
        the lease after this round (ref: the acquire/renew loop inside
        election.RunOrDie)."""
        try:
            lease = self.clientset.leases.get(self.namespace, self.name)
        except errors.ApiError as e:
            if not errors.is_not_found(e):
                raise
            lease = {
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "metadata": {"name": self.name, "namespace": self.namespace},
                "spec": self._lease_spec(0),
            }
            try:
                self.clientset.leases.create(self.namespace, lease)
                return True
            except errors.ApiError as e2:
                if errors.is_already_exists(e2):
                    return False  # raced another candidate; retry next round
                raise

        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity", "")
        renew = _parse(spec.get("renewTime", "")) or _now()
        duration = float(spec.get("leaseDurationSeconds", self.lease_duration))
        expired = (_now() - renew).total_seconds() > duration

        if holder == self.identity:
            spec["renewTime"] = _fmt(_now())
            spec["holderIdentity"] = self.identity
        elif expired:
            transitions = int(spec.get("leaseTransitions", 0)) + 1
            lease["spec"] = self._lease_spec(transitions)
        else:
            return False  # someone else holds a live lease

        try:
            self.clientset.leases.update(self.namespace, lease)
            return True
        except errors.ApiError as e:
            if errors.is_conflict(e):
                return False  # lost the CAS; retry
            raise

    # -- run loop -------------------------------------------------------------

    def run(
        self,
        on_started_leading: Callable[[threading.Event], None],
        on_stopped_leading: Optional[Callable[[], None]] = None,
        stop_event: Optional[threading.Event] = None,
    ) -> None:
        """Block: campaign once, then lead until the lease is lost or
        stop_event fires. ``on_started_leading`` runs in a worker thread and
        receives a leading-scoped stop event chained to the outer one
        (ref: OnStartedLeading → controller.Run, server.go:93-95).

        On lost leadership this RETURNS (after ``on_stopped_leading``): the
        process must exit and be restarted by its Deployment, exactly like
        the reference's OnStoppedLeading → fatal (server.go:98-102). The
        controller's workqueue is shut down by then, so re-campaigning in
        the same process would hold the lease while reconciling nothing.
        """
        stop_event = stop_event or threading.Event()

        # Campaign (ref: retry every 3s)
        while not stop_event.is_set() and not self._try():
            stop_event.wait(self.retry_period)
        if stop_event.is_set():
            return

        log.info("leader election: %s acquired %s/%s",
                 self.identity, self.namespace, self.name)
        self.is_leader.set()
        leading_stop = threading.Event()
        threading.Thread(
            target=lambda: (stop_event.wait(), leading_stop.set()), daemon=True,
            name="leader-stop-forwarder",
        ).start()
        worker = threading.Thread(
            target=on_started_leading, args=(leading_stop,), daemon=True,
            name="leading",
        )
        worker.start()

        # Renew loop: a transient API failure retries every retry_period for
        # as long as the last successful renewal keeps the lease alive —
        # leadership drops only when the lease actually expires under us
        # (client-go semantics; one apiserver blip must not tear down the
        # controller).
        import time as _time

        last_renewed = _time.monotonic()
        lost = False
        while not stop_event.is_set() and not lost:
            if stop_event.wait(self.renew_deadline):
                break
            while not stop_event.is_set():
                if self._try():
                    last_renewed = _time.monotonic()
                    break
                if _time.monotonic() - last_renewed > self.lease_duration:
                    log.warning("leader election: lost lease %s/%s",
                                self.namespace, self.name)
                    lost = True
                    break
                stop_event.wait(self.retry_period)

        self.is_leader.clear()
        leading_stop.set()
        if on_stopped_leading:
            on_stopped_leading()

    def _try(self) -> bool:
        try:
            return self.try_acquire_or_renew()
        except Exception as e:  # noqa: BLE001 — transient API errors
            log.warning("leader election round failed: %s", e)
            return False
