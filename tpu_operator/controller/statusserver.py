"""Operator observability: /healthz, /readyz, /metrics, and a job dashboard.

The reference had **no** metrics endpoint, no probes, and its Helm chart's
dashboard referenced a binary that was not even in the repo (SURVEY.md §5
"No Prometheus /metrics endpoint"; §2 #18 dashboard.yaml:25-35). This module
closes all three gaps with one stdlib HTTP server (no new dependencies,
matching the operator's pure-control-plane footprint):

- ``GET /healthz``  — process liveness (always 200 while the thread serves).
- ``GET /readyz``   — 200 once the informer caches of the *leading* instance
  have synced; a non-leading standby also reports 200 (it is a healthy hot
  spare) with ``standby`` in the body so probes don't flap during elections.
- ``GET /metrics``  — Prometheus text format: reconcile totals/errors, queue
  depth, jobs by phase, leadership, GC deletions.
- ``GET /api/jobs`` — JSON roll-up of every TPUJob (phase, state, replicas)
  straight from the informer cache: the dashboard the reference's chart
  promised but never shipped.
- ``GET /``         — minimal HTML rendering of the same roll-up.
"""

from __future__ import annotations

import html
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

log = logging.getLogger(__name__)


class Metrics:
    """Thread-safe monotonic counters (gauges are sampled at scrape time)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {
            "reconcile_total": 0,
            "reconcile_errors_total": 0,
            "gc_deleted_total": 0,
            "leader_elections_won_total": 0,
        }

    def inc(self, name: str, amount: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._counters)


class StatusServer:
    """Serves observability endpoints over the controller's live state.

    ``controller`` may be None before leadership is won — endpoints then
    report standby state. The server binds immediately at process start so
    kubelet probes work for standbys too.
    """

    def __init__(self, port: int, controller: Optional[Any] = None,
                 metrics: Optional[Metrics] = None, host: str = "") -> None:
        self.metrics = metrics if metrics is not None else Metrics()
        self._controller_lock = threading.Lock()
        self._controller = controller
        self._leading = threading.Event()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt: str, *args: Any) -> None:
                log.debug("status: " + fmt, *args)

            def _send(self, code: int, body: str,
                      content_type: str = "text/plain; charset=utf-8") -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/healthz":
                        self._send(200, "ok")
                    elif path == "/readyz":
                        code, body = outer.readyz()
                        self._send(code, body)
                    elif path == "/metrics":
                        self._send(200, outer.render_metrics(),
                                   "text/plain; version=0.0.4; charset=utf-8")
                    elif path == "/api/jobs":
                        self._send(200, json.dumps(outer.jobs_rollup()),
                                   "application/json")
                    elif path == "/":
                        self._send(200, outer.render_dashboard(),
                                   "text/html; charset=utf-8")
                    else:
                        self._send(404, "not found")
                except Exception as e:  # noqa: BLE001 — never kill the probe thread
                    log.warning("status endpoint %s failed: %s", path, e)
                    try:
                        self._send(500, f"error: {e}")
                    except Exception:  # noqa: BLE001
                        pass

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.server.daemon_threads = True
        self.port = self.server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True, name="status-http")
        self._thread.start()
        log.info("status server listening on :%d", self.port)

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()

    def set_controller(self, controller: Any) -> None:
        """Called when this instance wins leadership and builds a controller."""
        with self._controller_lock:
            self._controller = controller
        self._leading.set()
        self.metrics.inc("leader_elections_won_total")

    @property
    def controller(self) -> Optional[Any]:
        with self._controller_lock:
            return self._controller

    # -- endpoint bodies -------------------------------------------------------

    def readyz(self) -> tuple:
        c = self.controller
        if not self._leading.is_set() or c is None:
            return 200, "ok: standby"
        synced = all(inf.has_synced() for inf in c.factory.informers.values())
        return (200, "ok: leading, caches synced") if synced else (
            503, "not ready: caches syncing")

    def jobs_rollup(self) -> list:
        c = self.controller
        if c is None:
            return []
        out = []
        for obj in c.job_informer.store.list():
            md = obj.get("metadata") or {}
            status = obj.get("status") or {}
            spec = obj.get("spec") or {}
            out.append({
                "namespace": md.get("namespace", ""),
                "name": md.get("name", ""),
                "phase": status.get("phase", ""),
                "state": status.get("state", ""),
                "attempt": status.get("attempt", 0),
                "replicas": {
                    str(rs.get("tpuReplicaType", "WORKER")): rs.get("replicas", 0)
                    for rs in spec.get("replicaSpecs", [])
                },
                "replicaStatuses": status.get("replicaStatuses", []),
            })
        return out

    def render_metrics(self) -> str:
        lines = []

        def emit(name: str, value: float, help_text: str,
                 mtype: str = "counter", labels: str = "") -> None:
            full = f"tpu_operator_{name}"
            lines.append(f"# HELP {full} {help_text}")
            lines.append(f"# TYPE {full} {mtype}")
            lines.append(f"{full}{labels} {value}")

        for name, value in sorted(self.metrics.snapshot().items()):
            emit(name, value, f"Total {name.replace('_', ' ')}.")

        emit("leading", 1 if self._leading.is_set() else 0,
             "1 if this instance holds the leader lease.", "gauge")

        c = self.controller
        if c is not None:
            emit("workqueue_depth", len(c.queue),
                 "Pending keys in the reconcile workqueue.", "gauge")
            phases: Dict[str, int] = {}
            for obj in c.job_informer.store.list():
                phase = (obj.get("status") or {}).get("phase") or "None"
                phases[phase] = phases.get(phase, 0) + 1
            full = "tpu_operator_jobs"
            lines.append(f"# HELP {full} TPUJobs known to the informer cache, by phase.")
            lines.append(f"# TYPE {full} gauge")
            for phase, n in sorted(phases.items()):
                lines.append(f'{full}{{phase="{phase}"}} {n}')
        return "\n".join(lines) + "\n"

    def render_dashboard(self) -> str:
        rows = []
        for j in self.jobs_rollup():
            replicas = ", ".join(f"{k}×{v}" for k, v in j["replicas"].items())
            rows.append(
                "<tr>" + "".join(
                    f"<td>{html.escape(str(v))}</td>"
                    for v in (j["namespace"], j["name"], j["phase"],
                              j["state"], j["attempt"], replicas)
                ) + "</tr>"
            )
        body = "".join(rows) or '<tr><td colspan="6"><i>no jobs</i></td></tr>'
        leading = "leading" if self._leading.is_set() else "standby"
        return (
            "<!doctype html><html><head><title>tpu-operator</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "table{border-collapse:collapse}td,th{border:1px solid #ccc;"
            "padding:.4em .8em;text-align:left}</style></head><body>"
            f"<h1>tpu-operator <small>({leading})</small></h1>"
            "<table><tr><th>Namespace</th><th>Name</th><th>Phase</th>"
            "<th>State</th><th>Attempt</th><th>Replicas</th></tr>"
            f"{body}</table>"
            '<p><a href="/metrics">metrics</a> · <a href="/api/jobs">json</a></p>'
            "</body></html>"
        )
