"""Operator observability: probes, Prometheus metrics, traces, heartbeats,
and a job dashboard.

The reference had **no** metrics endpoint, no probes, and its Helm chart's
dashboard referenced a binary that was not even in the repo (SURVEY.md §5
"No Prometheus /metrics endpoint"; §2 #18 dashboard.yaml:25-35). This module
closes all of it with one stdlib HTTP server (no new dependencies, matching
the operator's pure-control-plane footprint):

- ``GET /healthz``  — process liveness (always 200 while the thread serves).
- ``GET /readyz``   — 200 once the informer caches of the *leading* instance
  have synced; a non-leading standby also reports 200 (it is a healthy hot
  spare) with ``standby`` in the body so probes don't flap during elections.
- ``GET /metrics``  — Prometheus text format: counters, gauges, and
  fixed-bucket histograms (reconcile duration, workqueue queue-latency and
  work-duration, job phase durations), jobs by phase, per-job training
  heartbeat gauges, leadership, GC deletions.
- ``GET /api/traces`` — recent reconcile spans (util/tracing ring buffer),
  each carrying the trace id that also tags the log stream.
- ``POST /api/heartbeat`` — step telemetry from training payloads (process 0
  posts step/step-time/tokens-per-sec/loss); flows into per-job gauges here
  and into ``status.lastHeartbeat`` through the controller, so a hung TPU
  slice is visible from ``kubectl get`` and ``/metrics`` instead of from
  silence.
- ``GET /api/jobs`` — JSON roll-up of every TPUJob (phase, state, replicas,
  phase timeline, derived durations, last heartbeat) straight from the
  informer cache: the dashboard the reference's chart promised but never
  shipped.
- ``GET /``         — minimal HTML rendering of the same roll-up.

The :class:`Metrics` registry is deterministic by construction — callers
pass durations they computed from their own (injectable) clocks, so tests
drive every histogram with a fake clock and assert exact bucket contents.
"""

from __future__ import annotations

import html
import json
import logging
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from tpu_operator.payload.autotune import ADJUSTMENT_KEYS
from tpu_operator.payload.startup import STAGE_FIELDS, STAGES as STARTUP_STAGES
from tpu_operator.payload.steptrace import (
    DIGEST_KEYS as STEP_DIGEST_KEYS,
    PHASE_FIELDS as STEP_PHASE_FIELDS,
)
from tpu_operator.obs import timeline as timeline_mod
from tpu_operator.util import tracing
from tpu_operator.util.util import now_rfc3339, parse_rfc3339
from tpu_operator.util import joblife, lockdep

log = logging.getLogger(__name__)

METRIC_PREFIX = "tpu_operator_"

# Fixed histogram buckets (upper bounds, seconds). Queue latency includes
# rate-limit backoff (base 10 s, cap 360 s — workqueue.py), so its buckets
# reach past the cap; work/reconcile durations are control-plane-fast.
RECONCILE_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0)
WORK_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0)
QUEUE_BUCKETS = (0.001, 0.01, 0.1, 1.0, 5.0, 10.0, 30.0, 60.0, 120.0,
                 360.0, 600.0)
# Job lifecycle durations: scheduling is seconds, runtimes are hours.
PHASE_BUCKETS = (1.0, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0)
RUNTIME_BUCKETS = (10.0, 60.0, 300.0, 600.0, 1800.0, 3600.0, 10800.0,
                   43200.0, 86400.0)
# Restart-backoff delays: exponential from the 10 s default base up to the
# 360 s default cap (plus headroom for custom maxSeconds).
BACKOFF_BUCKETS = (1.0, 5.0, 10.0, 30.0, 60.0, 120.0, 360.0, 600.0)
# Startup stages span ms (warm rendezvous) to minutes (cold XLA compile of
# a flagship payload) — log-spaced across both regimes.
STARTUP_BUCKETS = (0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
                   600.0)
# Admission latency spans a sub-second rebalance (capacity free on arrival)
# to hours parked behind a full cluster.
ADMISSION_BUCKETS = (0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0,
                     3600.0, 14400.0)
# Step-phase durations span µs (an idle dataWait/dispatch boundary on a
# healthy pipeline) to tens of seconds (a checkpoint stall, a straggling
# collective) — log-spaced across five decades.
STEP_PHASE_BUCKETS = (0.0001, 0.001, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                      10.0, 30.0)
# Cooperative-drain latency: directive stamped → planned exit classified.
# Sub-second when the gang is at a step boundary with a fresh save, up to
# the drain deadline (default 120 s) plus teardown when the save is slow;
# the tail past 300 s is the hard-kill fallback territory.
DRAIN_BUCKETS = (1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0)

LabelsT = Optional[Dict[str, str]]

# Upper bound on retained per-job heartbeats (evicted stalest-first); far
# above any real job count, purely an unbounded-growth backstop.
HEARTBEAT_CAP = 4096
# Reject heartbeat POSTs larger than this (real bodies are ~200 bytes).
MAX_HEARTBEAT_BODY = 64 * 1024


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(value: float) -> str:
    f = float(value)
    return str(int(f)) if f.is_integer() else repr(f)


def _fmt_le(bound: float) -> str:
    return "+Inf" if bound == float("inf") else _fmt(bound)


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _Histogram:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, nbuckets: int):
        self.counts = [0] * nbuckets  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0


class _Family:
    __slots__ = ("name", "mtype", "help", "buckets", "series")

    def __init__(self, name: str, mtype: str, help_text: str,
                 buckets: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.mtype = mtype
        self.help = help_text
        self.buckets = tuple(buckets or ()) if mtype == "histogram" else ()
        # label tuple (sorted (k, v) pairs) -> float | _Histogram
        self.series: Dict[Tuple[Tuple[str, str], ...], Any] = {}


def _series_key(labels: LabelsT) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


class Metrics:
    """Thread-safe Prometheus metric registry: labeled counters, gauges, and
    fixed-bucket histograms, rendered in valid text exposition format.

    Values are pure accumulators — no internal clock. Duration observations
    come from callers with injectable clocks (workqueue, controller,
    trainer), which is what keeps histogram tests deterministic.
    """

    def __init__(self) -> None:
        self._lock = lockdep.lock("Metrics._lock")
        self._families: Dict[str, _Family] = {}  # guarded-by: _lock
        for name in ("reconcile_total", "reconcile_errors_total",
                     "gc_deleted_total", "leader_elections_won_total"):
            self.register(name, "counter",
                          f"Total {name.replace('_', ' ')}.")
        self.register("workqueue_adds_total", "counter",
                      "Total adds handled by the reconcile workqueue.")
        self.register("workqueue_retries_total", "counter",
                      "Total delayed re-queues (rate-limited backoff and "
                      "non-timer add_after; deadline-manager wakeups are "
                      "excluded).")
        self.register("heartbeats_total", "counter",
                      "Training-step heartbeats received from payloads.")
        self.register("chaos_kills_total", "counter",
                      "Pods deleted by the chaos monkey.")
        self.register("chaos_api_errors_total", "counter",
                      "API errors injected by the flaky-clientset chaos "
                      "wrapper (--chaos-api-error-rate).")
        self.register("api_request_retries_total", "counter",
                      "Transient-failure retries of idempotent apiserver "
                      "requests (client/rest.py backoff).")
        self.register("api_requests_total", "counter",
                      "Apiserver requests issued by the operator, by "
                      "{verb,resource} — the read/write budget ledger "
                      "(fake and REST clientsets both tick it).")
        self.register("job_stalls_total", "counter",
                      "Whole-group restarts triggered by the stall watchdog "
                      "(no heartbeat within stallTimeoutSeconds).")
        self.register("job_deadline_exceeded_total", "counter",
                      "Jobs failed for exceeding activeDeadlineSeconds.")
        self.register("events_emitted_total", "counter",
                      "Kubernetes Events written (created or aggregated).")
        self.register("events_aggregated_total", "counter",
                      "Events folded into an existing Event's count.")
        self.register("events_pruned_total", "counter",
                      "Event-dedup cache entries evicted (LRU bound or "
                      "object deletion).")
        self.register("job_checkpoint_save_failures_total", "counter",
                      "Checkpoint interval-save failures reported by "
                      "payload heartbeats (delta-accumulated per job).")
        self.register("job_checkpoint_restore_fallbacks_total", "counter",
                      "Corrupt/torn checkpoints quarantined while a payload "
                      "walked back to an older valid step on restore.")
        self.register("compilation_cache_hits_total", "counter",
                      "Attempts whose XLA compile was served from the "
                      "persistent compilation cache (warm restart), per "
                      "startup breakdown reports.")
        self.register("job_store_upload_failures_total", "counter",
                      "Remote warm-start-store checkpoint-upload failures "
                      "reported by payload heartbeats (delta-accumulated "
                      "per job; the write-behind uploader retries on the "
                      "next verified save).")
        self.register("store_prefetch_hits_total", "counter",
                      "Attempts whose rendezvous-overlapped store prefetch "
                      "delivered a checkpoint and/or compilation-cache "
                      "entries (fresh-node warm start), once per attempt.")
        self.register("store_prefetch_misses_total", "counter",
                      "Attempts whose store prefetch found nothing to "
                      "fetch (cold store or first attempt), once per "
                      "attempt.")
        self.register("job_goodput_ratio", "gauge",
                      "Per-job restart goodput: useful-step-seconds over "
                      "attempt wallclock since the job first started "
                      "running — what fleet churn (preemptions, cold "
                      "restarts) costs the job, computed from heartbeat "
                      "step cadence + the startup breakdown.")
        self.register("tpujob_preemptions_total", "counter",
                      "Admitted jobs evicted by the fleet scheduler so a "
                      "higher-priority job could fit the slice inventory "
                      "(the victim re-queues on the preemption budget).")
        self.register("tpujob_queue_depth", "gauge",
                      "TPUJobs parked in the admission queue (phase "
                      "Queued), by fair-share queue.")
        self.register("tpujob_admission_latency_seconds", "histogram",
                      "Time from entering the admission queue to slice "
                      "reservation (zero-wait admissions observe ~0; "
                      "rebuild force-admissions are not observed).",
                      ADMISSION_BUCKETS)
        self.register("reconcile_duration_seconds", "histogram",
                      "Wall time of one reconcile pass.", RECONCILE_BUCKETS)
        self.register("workqueue_queue_duration_seconds", "histogram",
                      "Time keys wait in the workqueue before processing "
                      "(includes rate-limit backoff).", QUEUE_BUCKETS)
        self.register("workqueue_work_duration_seconds", "histogram",
                      "Time spent processing a popped key.", WORK_BUCKETS)
        self.register("job_time_to_scheduled_seconds", "histogram",
                      "Creation to first reconcile (phase Creating).",
                      PHASE_BUCKETS)
        self.register("job_time_to_running_seconds", "histogram",
                      "Phase Creating to phase Running.", PHASE_BUCKETS)
        self.register("job_runtime_seconds", "histogram",
                      "Phase Creating to a terminal phase (Done/Failed).",
                      RUNTIME_BUCKETS)
        self.register("group_restart_backoff_seconds", "histogram",
                      "Backoff delay applied between whole-group restarts.",
                      BACKOFF_BUCKETS)
        self.register("job_startup_seconds", "histogram",
                      "Per-attempt startup stage durations "
                      "(label stage: rendezvous/restore/compile/"
                      "first_step), from payload startup breakdowns.",
                      STARTUP_BUCKETS)
        self.register("job_step_phase_seconds", "histogram",
                      "Per-phase step-time split (label phase: dataWait/"
                      "dispatch/compute/checkpoint/host) from the payload "
                      "flight recorder's windowed digests — each digest's "
                      "p95 observed once per disjoint step window.",
                      STEP_PHASE_BUCKETS)
        self.register("job_prefetch_depth", "gauge",
                      "Live device-prefetch depth of the job's data "
                      "plane (in-flight batch window), from process 0's "
                      "dataPlane knob reports — static spec value or "
                      "the autotuner's current choice.")
        self.register("job_autotune_adjustments_total", "counter",
                      "Data-plane autotune knob adjustments, by "
                      "{knob,direction}: prefetch (depth step), host "
                      "(async host path toggle), checkpoint (cadence "
                      "stretch); direction down = a regression-triggered "
                      "revert. Delta-accumulated per job from heartbeat "
                      "counter reports.")
        self.register("job_straggler_ratio", "gauge",
                      "Worst p95-step-time-to-gang-median ratio across the "
                      "job's gang (1.0 = perfectly even; above "
                      "spec.stepTrace.stragglerRatio flags the member into "
                      "status.stragglers). Only set while ≥2 processes "
                      "report cadence.")
        self.register("job_world_size", "gauge",
                      "Worker-process count of the job's current attempt — "
                      "for elastic jobs (spec.elastic) the size the fleet "
                      "scheduler actually granted from the live slice "
                      "inventory, which may be smaller than the spec'd "
                      "world after a shrink.")
        self.register("job_elastic_resizes_total", "counter",
                      "Elastic gang resizes between attempts, by direction "
                      "(down: the inventory could not host the previous "
                      "size or a straggler was shed; up: capacity returned "
                      "and the gang re-expanded toward maxSlices).")
        self.register("job_serving_replicas_ready", "gauge",
                      "Serve-mode replicas whose payload currently posts "
                      "ready serving beats (their per-replica Services "
                      "route; a reloading or wedged replica drops out).")
        self.register("job_serving_requests_per_second", "gauge",
                      "Aggregate requests/sec across the job's serve "
                      "replicas, from serving heartbeats — the traffic "
                      "signal the replica scaler divides by "
                      "targetRequestsPerSecondPerReplica.")
        self.register("job_serving_tokens_per_second", "gauge",
                      "Aggregate decode tokens/sec across the job's "
                      "ready serve replicas, from serving heartbeats — "
                      "the paged-KV incremental-decode throughput the "
                      "bench's A/B gate measures.")
        self.register("job_serving_queue_depth", "gauge",
                      "Requests queued for a decode slot across the "
                      "job's serve replicas (depth-bounded admission "
                      "sheds past --max-queue; a persistently deep "
                      "queue is the scale-up signal).")
        self.register("job_serving_kv_cache_utilization", "gauge",
                      "KV page-pool utilization of the WORST serve "
                      "replica (fraction of pages held by live "
                      "requests; 1.0 = admission blocked on pages).")
        self.register("job_serving_latency_seconds", "gauge",
                      "Per-request decode latency of the WORST ready "
                      "replica, by quantile label (0.5 / 0.95) — the "
                      "tail the serve-mode straggler guard watches.")
        self.register("job_weight_reloads_total", "counter",
                      "Hot weight reloads completed by serve replicas "
                      "(a newer verified snapshot observed in the remote "
                      "store and rolled in with no attempt bump), "
                      "delta-accumulated from serving heartbeats.")
        self.register("job_straggler_remediations_total", "counter",
                      "Straggler remediations executed per "
                      "spec.elastic.stragglerPolicy, by policy (replace: "
                      "the flagged member's pod was deleted and re-created "
                      "into the same rendezvous avoiding its node; shed: "
                      "whole-group restart at one slice fewer, billed to "
                      "the preemption budget).")
        self.register("job_planned_restarts_total", "counter",
                      "Operator-initiated cooperative-drain restarts "
                      "completed, by reason (resize: in-attempt grow "
                      "toward maxSlices; preemption: drain-first fleet "
                      "eviction; maintenance: node cordon/drain). Billed "
                      "to the preemption-factor budget, never the "
                      "crash-loop backoff streak.")
        self.register("job_drain_seconds", "histogram",
                      "Cooperative-drain latency: drain directive stamped "
                      "into status.drain to the gang's planned exit being "
                      "classified (or to deadline expiry on the hard-kill "
                      "fallback).", DRAIN_BUCKETS)

    # -- registry --------------------------------------------------------------

    def register(self, name: str, mtype: str, help_text: str,
                 buckets: Optional[Tuple[float, ...]] = None) -> None:
        """Idempotently declare a metric family. Unlabeled families
        materialize a zero series so they render even before first use."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, mtype, help_text, buckets)
                self._families[name] = fam
                self._series_locked(fam, ())

    def _series_locked(self, fam: _Family, key: Tuple) -> Any:
        s = fam.series.get(key)
        if s is None:
            s = _Histogram(len(fam.buckets)) if fam.mtype == "histogram" else 0.0
            fam.series[key] = s
        return s

    def _family_locked(self, name: str, mtype: str,
                buckets: Optional[Tuple[float, ...]] = None) -> _Family:
        fam = self._families.get(name)
        if fam is None:
            fam = _Family(name, mtype,
                          f"Total {name.replace('_', ' ')}." if
                          mtype == "counter" else f"{name}.", buckets)
            self._families[name] = fam
        return fam

    # -- write paths -----------------------------------------------------------

    def inc(self, name: str, amount: float = 1, labels: LabelsT = None) -> None:
        with self._lock:
            fam = self._family_locked(name, "counter")
            key = _series_key(labels)
            fam.series[key] = self._series_locked(fam, key) + amount

    def set_gauge(self, name: str, value: float, labels: LabelsT = None) -> None:
        with self._lock:
            fam = self._family_locked(name, "gauge")
            fam.series[_series_key(labels)] = float(value)

    def observe(self, name: str, value: float, labels: LabelsT = None) -> None:
        with self._lock:
            fam = self._families.get(name)
            if fam is None or fam.mtype != "histogram":
                # Unlike counters/gauges, histograms need meaningful buckets:
                # auto-registering would hand a typo'd call site a valid-
                # looking family with wrong buckets while the intended one
                # stays empty — fail at first observation instead.
                raise KeyError(f"unregistered histogram {name!r}; "
                               f"register() it with explicit buckets")
            hist: _Histogram = self._series_locked(fam, _series_key(labels))
            for i, bound in enumerate(fam.buckets):
                if value <= bound:
                    hist.counts[i] += 1
                    break
            hist.sum += value
            hist.count += 1

    # -- read paths ------------------------------------------------------------

    def snapshot(self) -> Dict[str, float]:
        """Unlabeled-counter view (back-compat; labeled series are summed)."""
        out: Dict[str, float] = {}
        with self._lock:
            for fam in self._families.values():
                if fam.mtype == "counter":
                    out[fam.name] = sum(fam.series.values())
        return out

    def remove_series(self, name: str, labels: LabelsT = None) -> None:
        """Drop one labeled series (gauge pruning: user-keyed label values
        — e.g. fair-share queue names — must not accumulate forever; the
        same slow-leak class the event dedup cache bounds)."""
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                fam.series.pop(_series_key(labels), None)

    def job_series(self, namespace: str, name: str) -> List[str]:
        """Registry series whose labels carry this job's identity —
        the joblife deletion sweep's metrics probe: right after a job's
        deletion reconcile this must be empty, or a family is missing
        from the controller's prune list."""
        out: List[str] = []
        with self._lock:
            for fam in self._families.values():
                for key in fam.series:
                    labels = dict(key)
                    if labels.get("namespace") == namespace \
                            and labels.get("name") == name:
                        out.append(f"{fam.name}{_label_str(labels)}")
        return sorted(out)

    def series_count(self) -> int:
        """Total labeled series resident in the registry (the churn
        soak's flatness probe — job churn must not grow it)."""
        with self._lock:
            return sum(len(fam.series) for fam in self._families.values())

    def counter_value(self, name: str, labels: LabelsT = None) -> float:
        """One labeled counter/gauge series' value (0.0 when absent) —
        the label-exact read the budget benches assert against, where
        snapshot() would sum away the {verb,resource} split."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None or fam.mtype == "histogram":
                return 0.0
            value = fam.series.get(_series_key(labels))
            return float(value) if value is not None else 0.0

    def histogram_snapshot(self, name: str, labels: LabelsT = None
                           ) -> Optional[Dict[str, Any]]:
        """Test/introspection view of one histogram series:
        {"buckets": {le: cumulative_count}, "sum": s, "count": n}."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None or fam.mtype != "histogram":
                return None
            hist = fam.series.get(_series_key(labels))
            if hist is None:
                return None
            cum, buckets = 0, {}
            for bound, n in zip(fam.buckets, hist.counts):
                cum += n
                buckets[_fmt_le(bound)] = cum
            buckets["+Inf"] = hist.count
            return {"buckets": buckets, "sum": hist.sum, "count": hist.count}

    def render_lines(self, prefix: str = METRIC_PREFIX) -> List[str]:
        lines: List[str] = []
        with self._lock:
            for name in sorted(self._families):
                fam = self._families[name]
                full = prefix + name
                lines.append(f"# HELP {full} {_escape_help(fam.help)}")
                lines.append(f"# TYPE {full} {fam.mtype}")
                for key in sorted(fam.series):
                    labels = dict(key)
                    if fam.mtype == "histogram":
                        hist: _Histogram = fam.series[key]
                        cum = 0
                        for bound, n in zip(fam.buckets, hist.counts):
                            cum += n
                            lines.append(
                                f"{full}_bucket"
                                f"{_label_str({**labels, 'le': _fmt_le(bound)})}"
                                f" {cum}")
                        lines.append(
                            f"{full}_bucket"
                            f"{_label_str({**labels, 'le': '+Inf'})}"
                            f" {hist.count}")
                        lines.append(
                            f"{full}_sum{_label_str(labels)} {_fmt(hist.sum)}")
                        lines.append(
                            f"{full}_count{_label_str(labels)} {hist.count}")
                    else:
                        lines.append(f"{full}{_label_str(labels)} "
                                     f"{_fmt(fam.series[key])}")
        return lines


def _int_field(value: Any, minimum: int, label: str
               ) -> Tuple[Optional[int], str]:
    """Shared strict integer door for heartbeat count/knob fields:
    bool is an int subclass but a True depth/count is a payload bug,
    not 1; float NaN/Inf fail the cast; below-minimum rejects (persisted,
    it would wedge every later status write against a real apiserver's
    schema minimums). One definition so the stepTiming and dataPlane
    doors cannot drift into different policies for the same defect."""
    if isinstance(value, bool):
        return None, f"bad heartbeat: non-numeric {label}"
    try:
        value = int(value)
    except (TypeError, ValueError, OverflowError):
        return None, f"bad heartbeat: non-numeric {label}"
    if value < minimum:
        detail = "negative" if minimum == 0 else f"below {minimum}"
        return None, f"bad heartbeat: {label} {detail}"
    return value, ""


def _sanitize_steptiming(st: Any) -> Tuple[Optional[Dict[str, Any]], str]:
    """Sanitize a heartbeat's ``stepTiming`` phase digest down to exactly
    the CRD schema's shape: (clean-or-None, error). Same door discipline
    as the startup breakdown — a non-finite or negative duration rejects
    the beat (persisted, it would wedge every later status write against
    a real apiserver's schema), while *unknown phase names* are dropped
    silently (a newer payload posting a phase this operator doesn't know
    must not lose the whole beat — forward compatibility, like startup's
    unknown-stage-field skip)."""
    if not isinstance(st, dict):
        return None, "bad heartbeat: stepTiming must be an object"
    clean: Dict[str, Any] = {}
    for field in ("steps",):
        if st.get(field) is not None:
            value, err = _int_field(st[field], 0, f"stepTiming.{field}")
            if err:
                return None, err
            clean[field] = value
    for field in ("stepP50Seconds", "stepP95Seconds", "stepMaxSeconds",
                  "stepLocalP95Seconds"):
        if st.get(field) is not None:
            try:
                value = float(st[field])
            except (TypeError, ValueError):
                return None, f"bad heartbeat: non-numeric stepTiming.{field}"
            if not math.isfinite(value) or value < 0:
                return None, f"bad heartbeat: bad stepTiming.{field}"
            clean[field] = value
    phases = st.get("phases")
    if phases is not None:
        if not isinstance(phases, dict):
            return None, "bad heartbeat: stepTiming.phases must be an object"
        known = set(STEP_PHASE_FIELDS.values())
        clean_phases: Dict[str, Any] = {}
        for name, stats in phases.items():
            if name not in known:
                continue  # unknown phase: dropped, never persisted
            if not isinstance(stats, dict):
                return None, (f"bad heartbeat: stepTiming.phases.{name} "
                              f"must be an object")
            clean_stats: Dict[str, float] = {}
            for key in STEP_DIGEST_KEYS:
                if stats.get(key) is None:
                    continue
                try:
                    value = float(stats[key])
                except (TypeError, ValueError):
                    return None, (f"bad heartbeat: non-numeric "
                                  f"stepTiming.phases.{name}.{key}")
                if not math.isfinite(value) or value < 0:
                    return None, (f"bad heartbeat: bad "
                                  f"stepTiming.phases.{name}.{key}")
                clean_stats[key] = value
            if clean_stats:
                clean_phases[name] = clean_stats
        if clean_phases:
            clean["phases"] = clean_phases
    return (clean or None), ""


def _sanitize_dataplane(dp: Any) -> Tuple[Optional[Dict[str, Any]], str]:
    """Sanitize a heartbeat's ``dataPlane`` knob report down to exactly
    the CRD schema's shape: (clean-or-None, error). Door discipline per
    the stepTiming sanitizer — a non-finite/negative knob value rejects
    the beat (persisted, it would wedge every later status write against
    a real apiserver's schema minimums), while UNKNOWN adjustment keys
    are dropped silently (a newer payload tuning a knob this operator
    doesn't know must not lose the whole beat — forward compat, like the
    unknown-phase drop)."""
    if not isinstance(dp, dict):
        return None, "bad heartbeat: dataPlane must be an object"
    clean: Dict[str, Any] = {}
    for field, minimum in (("prefetchDepth", 0),
                           ("checkpointIntervalSteps", 1),
                           ("hostDropped", 0)):
        if dp.get(field) is not None:
            value, err = _int_field(dp[field], minimum,
                                    f"dataPlane.{field}")
            if err:
                return None, err
            clean[field] = value
    if dp.get("hostAsync") is not None:
        if not isinstance(dp["hostAsync"], bool):
            # Same strict door as the numeric knobs: bool("false") is
            # True, so coercing would persist the opposite of what a
            # stringly-typed payload meant.
            return None, "bad heartbeat: non-boolean dataPlane.hostAsync"
        clean["hostAsync"] = dp["hostAsync"]
    adj = dp.get("adjustments")
    if adj is not None:
        if not isinstance(adj, dict):
            return None, "bad heartbeat: dataPlane.adjustments must be an object"
        clean_adj: Dict[str, int] = {}
        for key in ADJUSTMENT_KEYS:
            if adj.get(key) is None:
                continue
            value, err = _int_field(adj[key], 0,
                                    f"dataPlane.adjustments.{key}")
            if err:
                return None, err
            clean_adj[key] = value
        if clean_adj:
            clean["adjustments"] = clean_adj
    return (clean or None), ""


def _sanitize_serving(sv: Any) -> Tuple[Optional[Dict[str, Any]], str]:
    """Sanitize a heartbeat's ``serving`` beat down to exactly the CRD
    schema's shape: (clean-or-None, error). Door discipline per the
    stepTiming/dataPlane sanitizers — a non-finite or negative value
    rejects the beat (persisted, it would wedge every later status write
    against a real apiserver's schema minimums), ``ready`` must be a real
    boolean (bool("false") is True — a coercion would route traffic to a
    replica that said it was NOT ready), and unknown keys are dropped
    silently for forward compatibility."""
    if not isinstance(sv, dict):
        return None, "bad heartbeat: serving must be an object"
    clean: Dict[str, Any] = {}
    if sv.get("ready") is not None:
        if not isinstance(sv["ready"], bool):
            return None, "bad heartbeat: non-boolean serving.ready"
        clean["ready"] = sv["ready"]
    for field in ("requestsPerSecond", "tokensPerSecond",
                  "kvCacheUtilization", "p50LatencySeconds",
                  "p95LatencySeconds"):
        if sv.get(field) is not None:
            try:
                value = float(sv[field])
            except (TypeError, ValueError):
                return None, f"bad heartbeat: non-numeric serving.{field}"
            if not math.isfinite(value) or value < 0:
                return None, f"bad heartbeat: bad serving.{field}"
            clean[field] = value
    for field in ("queueDepth", "loadedStep", "reloads"):
        if sv.get(field) is not None:
            value, err = _int_field(sv[field], 0, f"serving.{field}")
            if err:
                return None, err
            clean[field] = value
    return (clean or None), ""


def _sanitize_profile(pr: Any) -> Tuple[Optional[Dict[str, Any]], str]:
    """Sanitize a heartbeat's ``profile`` capture result down to exactly
    the CRD schema's shape: (clean-or-None, error). Same door discipline
    as the startup breakdown — it is a one-shot the payload resends until
    ACKed, and a bad value persisted into ``status.profile`` would wedge
    every later status write against a real apiserver's schema."""
    if not isinstance(pr, dict):
        return None, "bad heartbeat: profile must be an object"
    rid = pr.get("id")
    if not isinstance(rid, str) or not rid:
        return None, "bad heartbeat: profile.id must be a non-empty string"
    clean: Dict[str, Any] = {"id": rid}
    steps, err = _int_field(pr.get("capturedSteps", 0), 0,
                            "profile.capturedSteps")
    if err:
        return None, err
    clean["capturedSteps"] = steps
    key = pr.get("artifactKey")
    if key is not None:
        if not isinstance(key, str):
            return None, "bad heartbeat: profile.artifactKey must be a string"
        if key:
            clean["artifactKey"] = key
    return clean, ""


def _sanitize_drain_ack(da: Any) -> Tuple[Optional[Dict[str, Any]], str]:
    """Sanitize a heartbeat's ``drainAck`` (the payload adopted a drain
    directive and will exit at the named step boundary) down to exactly
    the CRD schema's shape: (clean-or-None, error). Same door discipline
    as the profile result — it is a one-shot the payload resends until
    ACKed, and a bad value folded into ``status.drain`` would wedge every
    later status write against a real apiserver's schema."""
    if not isinstance(da, dict):
        return None, "bad heartbeat: drainAck must be an object"
    rid = da.get("id")
    if not isinstance(rid, str) or not rid:
        return None, "bad heartbeat: drainAck.id must be a non-empty string"
    clean: Dict[str, Any] = {"id": rid}
    step, err = _int_field(da.get("step", 0), 0, "drainAck.step")
    if err:
        return None, err
    clean["step"] = step
    return clean, ""


def _public_heartbeat(hb: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    if not hb:
        return None
    return {k: v for k, v in hb.items() if k != "receivedAt"}


def derived_durations(md: Dict[str, Any], timeline: Dict[str, str]
                      ) -> Dict[str, float]:
    """Seconds between lifecycle marks, from status.phaseTimeline (+ the
    object's creationTimestamp when the apiserver stamped one)."""
    out: Dict[str, float] = {}
    created = parse_rfc3339(md.get("creationTimestamp", ""))
    creating = parse_rfc3339(timeline.get("Creating", ""))
    running = parse_rfc3339(timeline.get("Running", ""))
    terminal = (parse_rfc3339(timeline.get("Done", ""))
                or parse_rfc3339(timeline.get("Failed", "")))
    # Clamped like the histogram path (TrainingJob._transition): apiserver
    # vs operator clock skew must not surface negative durations.
    if created and creating:
        out["timeToScheduledSeconds"] = round(max(0.0, creating - created), 6)
    if creating and running:
        out["timeToRunningSeconds"] = round(max(0.0, running - creating), 6)
    if creating and terminal:
        out["runtimeSeconds"] = round(max(0.0, terminal - creating), 6)
    return out


class StatusServer:
    """Serves observability endpoints over the controller's live state.

    ``controller`` may be None before leadership is won — endpoints then
    report standby state. The server binds immediately at process start so
    kubelet probes work for standbys too.
    """

    def __init__(self, port: int, controller: Optional[Any] = None,
                 metrics: Optional[Metrics] = None, host: str = "") -> None:
        self.metrics = metrics if metrics is not None else Metrics()
        self._controller_lock = lockdep.lock("StatusServer._controller_lock")
        self._controller = controller  # guarded-by: _controller_lock
        self._leading = threading.Event()
        self._heartbeats_lock = lockdep.lock("StatusServer._heartbeats_lock")
        # (namespace, name) -> last heartbeat dict (+ receivedAt epoch)
        self._heartbeats: Dict[Tuple[str, str], Dict[str, Any]] = joblife.track(
            "StatusServer._heartbeats")  # per-job: forget_job; guarded-by: _heartbeats_lock
        # Eager deletion prune: before this listener existed, a deleted
        # job's heartbeat survived here until the next scrape/roll-up ran
        # _live_heartbeats — the first leak the joblife deletion sweep
        # caught. The lazy informer diff stays as the backstop for beats
        # that race the deletion reconcile.
        if controller is not None \
                and hasattr(controller, "add_deletion_listener"):
            controller.add_deletion_listener(self.forget_job)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # Socket read timeout: a client that declares a Content-Length
            # and never sends the body must not park a handler thread
            # forever (the unauthenticated POST endpoint makes this an
            # in-cluster DoS vector otherwise).
            timeout = 10

            def log_message(self, fmt: str, *args: Any) -> None:
                log.debug("status: " + fmt, *args)

            def _send(self, code: int, body: str,
                      content_type: str = "text/plain; charset=utf-8") -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                try:
                    if path == "/healthz":
                        self._send(200, "ok")
                    elif path == "/readyz":
                        code, body = outer.readyz()
                        self._send(code, body)
                    elif path == "/metrics":
                        self._send(200, outer.render_metrics(),
                                   "text/plain; version=0.0.4; charset=utf-8")
                    elif path == "/api/jobs":
                        self._send(200, json.dumps(outer.jobs_rollup()),
                                   "application/json")
                    elif path == "/api/fleet":
                        self._send(200, json.dumps(outer.fleet_rollup()),
                                   "application/json")
                    elif path.startswith("/api/jobs/") \
                            and path.endswith("/timeline"):
                        parts = path.split("/")
                        # ['', 'api', 'jobs', ns, name, 'timeline']
                        if len(parts) != 6 or not parts[3] or not parts[4]:
                            self._send(404, "not found")
                            return
                        import urllib.parse
                        params = dict(urllib.parse.parse_qsl(query))
                        code, body = outer.timeline_body(
                            parts[3], parts[4],
                            fmt=params.get("format", ""))
                        self._send(code, body, "application/json")
                    elif path == "/api/traces":
                        import urllib.parse
                        params = dict(urllib.parse.parse_qsl(query))
                        try:
                            limit = int(params.get("limit") or 256)
                        except ValueError:
                            self._send(400, "bad limit: not an integer")
                            return
                        if limit <= 0:
                            limit = 256  # documented default, never "all"
                        self._send(200, json.dumps(
                            outer.traces_body(params.get("job", ""), limit)),
                            "application/json")
                    elif path == "/":
                        self._send(200, outer.render_dashboard(),
                                   "text/html; charset=utf-8")
                    else:
                        self._send(404, "not found")
                except Exception as e:  # noqa: BLE001 — never kill the probe thread
                    log.warning("status endpoint %s failed: %s", path, e)
                    try:
                        self._send(500, f"error: {e}")
                    except Exception:  # noqa: BLE001
                        pass

            def do_POST(self) -> None:  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                try:
                    if path != "/api/heartbeat":
                        self._send(404, "not found")
                        return
                    try:
                        length = int(self.headers.get("Content-Length") or 0)
                    except ValueError:
                        self._send(400, "bad Content-Length")
                        return
                    # Heartbeat bodies are ~200 bytes; an unauthenticated
                    # endpoint must not buffer an attacker-sized body, and a
                    # negative length would turn read() into read-to-EOF,
                    # parking the handler thread until the client hangs up.
                    if length < 0:
                        self._send(400, "bad Content-Length")
                        return
                    if length > MAX_HEARTBEAT_BODY:
                        self._send(413, "heartbeat body too large")
                        return
                    try:
                        body = json.loads(self.rfile.read(length) or b"{}")
                        if not isinstance(body, dict):
                            raise ValueError("body must be a JSON object")
                    except (ValueError, json.JSONDecodeError) as e:
                        self._send(400, f"bad heartbeat: {e}")
                        return
                    ok, message = outer.record_heartbeat(body)
                    if ok:
                        # The 200 ACK is the only control channel back into
                        # the payload: a pending on-demand profile directive
                        # for process 0 rides here (tpujobctl profile), as
                        # does a pending cooperative-drain directive.
                        resp: Dict[str, Any] = {"ok": True}
                        directive = outer.profile_directive_for(body)
                        if directive:
                            resp["profile"] = directive
                        drain = outer.drain_directive_for(body)
                        if drain:
                            resp["drain"] = drain
                        self._send(200, json.dumps(resp),
                                   "application/json")
                    else:
                        # "; retry"-suffixed rejections are transient
                        # (standby instance, job not yet reconciled) →
                        # 503; everything else is a bad body → 400.
                        self._send(
                            503 if message.endswith("retry") else 400,
                            message)
                except Exception as e:  # noqa: BLE001 — never kill the thread
                    log.warning("status endpoint %s failed: %s", path, e)
                    try:
                        self._send(500, f"error: {e}")
                    except Exception:  # noqa: BLE001
                        pass

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.server.daemon_threads = True
        self.port = self.server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True, name="status-http")
        self._thread.start()
        log.info("status server listening on :%d", self.port)

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()

    def set_controller(self, controller: Any) -> None:
        """Called when this instance wins leadership and builds a controller."""
        with self._controller_lock:
            self._controller = controller
        if hasattr(controller, "add_deletion_listener"):
            controller.add_deletion_listener(self.forget_job)
        self._leading.set()
        self.metrics.inc("leader_elections_won_total")

    @property
    def controller(self) -> Optional[Any]:
        with self._controller_lock:
            return self._controller

    # -- heartbeats ------------------------------------------------------------

    def forget_job(self, namespace: str, name: str) -> None:
        """Deletion-listener hook (registered with the controller): drop
        a deleted job's stashed heartbeat eagerly, so its gauge source
        dies with the job instead of lingering until the next scrape's
        ``_live_heartbeats`` informer diff."""
        with self._heartbeats_lock:
            self._heartbeats.pop((namespace, name), None)

    def record_heartbeat(self, body: Dict[str, Any]) -> Tuple[bool, str]:
        """Ingest one payload heartbeat: stash for per-job gauges and pass it
        to the controller so ``status.lastHeartbeat`` persists to the CRD."""
        name = str(body.get("name") or "")
        if not name:
            return False, "bad heartbeat: missing job name"
        namespace = str(body.get("namespace") or "default")
        hb: Dict[str, Any] = {"time": now_rfc3339()}
        for field, cast in (("step", int), ("attempt", int),
                            ("processId", int), ("stepTimeSeconds", float),
                            ("tokensPerSec", float), ("loss", float),
                            ("lastCheckpointStep", int),
                            ("checkpointSaveFailures", int),
                            ("checkpointRestoreFallbacks", int),
                            ("storeLastUploadedStep", int),
                            ("storeUploadFailures", int)):
            if body.get(field) is not None:
                try:
                    value = cast(body[field])
                except (TypeError, ValueError):
                    return False, f"bad heartbeat: non-numeric {field}"
                # Values that can't round-trip the CRD schema must be
                # rejected at the door: persisted into status, a NaN breaks
                # JSON serialization and a negative violates the schema's
                # minimum: 0 — either way every subsequent status write for
                # the job is rejected by a real apiserver, wedging
                # reconcile. (loss is legitimately negative for some
                # objectives; the schema leaves it unbounded.)
                if not math.isfinite(value):
                    return False, f"bad heartbeat: non-finite {field}"
                if field != "loss" and value < 0:
                    return False, f"bad heartbeat: negative {field}"
                hb[field] = value
        # Warm-restart startup telemetry. Both fields are sanitized down to
        # exactly the CRD schema's shape before they can reach status — an
        # unknown key or bad value persisted there would fail strict
        # admission and wedge every later status write for the job.
        stage = body.get("startupStage")
        if stage is not None:
            if stage not in STARTUP_STAGES:
                return False, f"bad heartbeat: unknown startupStage {stage!r}"
            hb["startupStage"] = str(stage)
        st = body.get("stepTiming")
        if st is not None:
            clean_st, err = _sanitize_steptiming(st)
            if err:
                return False, err
            if clean_st:
                hb["stepTiming"] = clean_st
        dp = body.get("dataPlane")
        if dp is not None:
            clean_dp, err = _sanitize_dataplane(dp)
            if err:
                return False, err
            if clean_dp:
                hb["dataPlane"] = clean_dp
        sv = body.get("serving")
        if sv is not None:
            clean_sv, err = _sanitize_serving(sv)
            if err:
                return False, err
            if clean_sv:
                hb["serving"] = clean_sv
        su = body.get("startup")
        if su is not None:
            if not isinstance(su, dict):
                return False, "bad heartbeat: startup must be an object"
            clean: Dict[str, Any] = {}
            for field in STAGE_FIELDS.values():
                if su.get(field) is None:
                    continue
                try:
                    value = float(su[field])
                except (TypeError, ValueError):
                    return False, f"bad heartbeat: non-numeric startup.{field}"
                if not math.isfinite(value) or value < 0:
                    return False, f"bad heartbeat: bad startup.{field}"
                clean[field] = value
            if su.get("cacheHit") is not None:
                clean["cacheHit"] = bool(su["cacheHit"])
            if su.get("prefetchHit") is not None:
                clean["prefetchHit"] = bool(su["prefetchHit"])
            # An empty breakdown carries nothing: storing it would defeat
            # heartbeat coalescing (the controller force-persists any beat
            # with a "startup" key) and 503 no-op beats on a fresh leader.
            if clean:
                hb["startup"] = clean
        pr = body.get("profile")
        if pr is not None:
            clean_pr, err = _sanitize_profile(pr)
            if err:
                return False, err
            if clean_pr:
                hb["profile"] = clean_pr
        da = body.get("drainAck")
        if da is not None:
            clean_da, err = _sanitize_drain_ack(da)
            if err:
                return False, err
            if clean_da:
                hb["drainAck"] = clean_da
        c = self.controller
        if c is None:
            # A standby cannot persist the heartbeat (no in-memory job) nor
            # render its gauges (no informer cache) — a 200 here would
            # blackhole the posts a Service round-robins to standbys and
            # false-trip the staleness alarm on the leader. 503 tells the
            # payload to just retry next interval (it lands on the leader
            # eventually).
            return False, "standby: not leading; retry"
        if c.job_informer.store.get(namespace, name) is None:
            # A 200 here would silently unarm the hung-slice alarm: the
            # gauges would prune at the next scrape and status.lastHeartbeat
            # would never appear. Failing loudly surfaces the misconfig
            # (wrong namespace/name) in the payload's log instead.
            return False, f"unknown job {namespace}/{name}"
        if hasattr(c, "record_heartbeat"):
            # May return False before the first reconcile builds the
            # TrainingJob — transient; the job is in the informer cache, so
            # the gauges hold and status catches up on the next heartbeat.
            # None means the controller dropped the heartbeat as stale (a
            # terminating pod from a previous generation): the gauges must
            # not advertise liveness the stall watchdog ignores, so skip
            # the stash — but still 200 the dying pod.
            recorded = c.record_heartbeat(namespace, name, hb)
            if recorded is None:
                return True, ""
            if recorded is False and ("startup" in hb or "profile" in hb
                                      or "drainAck" in hb):
                # The startup breakdown, the profile capture result, and
                # the drain adoption ACK are ONE-SHOTs: the payload stops
                # resending them after the first 200 (unlike the
                # checkpoint fields, which ride on every beat). ACKing one
                # before the TrainingJob exists — a fresh leader whose
                # first reconcile hasn't run — would silently lose the
                # attempt's status.startup / status.profile /
                # status.drain fold. Fail retryably instead; the payload
                # re-attaches it to the next due beat.
                return False, "not ready: job not yet reconciled; retry"
        if hb.get("processId") not in (None, 0):
            # Cadence-only beats from non-zero gang members feed the
            # controller's straggler detector above; stashing them here
            # would flip the per-job gauges (job_last_step, step time,
            # loss) between whichever process posted last — the gauges
            # stay process 0's stream.
            self.metrics.inc("heartbeats_total")
            return True, ""
        with self._heartbeats_lock:
            self._heartbeats[(namespace, name)] = {
                **hb, "receivedAt": time.time()}
            # Bound the map even on instances that never scrape or hold no
            # controller (standby behind a Service): evict the stalest
            # entries — same slow-leak class the event dedup cache fixes.
            while len(self._heartbeats) > HEARTBEAT_CAP:
                oldest = min(self._heartbeats,
                             key=lambda k: self._heartbeats[k]["receivedAt"])
                del self._heartbeats[oldest]
        if c.job_informer.store.get(namespace, name) is None:
            # The job was deleted between the entry check at the top and
            # the stash: without this repair the deletion reconcile's
            # forget_job has already run and the entry would linger until
            # the lazy scrape diff (or forever on an unscraped instance).
            # Re-validating AFTER inserting closes the window — whichever
            # of stash/deletion ran second cleans up.
            with self._heartbeats_lock:
                self._heartbeats.pop((namespace, name), None)
            return False, f"unknown job {namespace}/{name}"
        self.metrics.inc("heartbeats_total")
        return True, ""

    def _live_heartbeats(self, c: Optional[Any]) -> Dict[Tuple[str, str], Dict[str, Any]]:
        """Current heartbeats, pruned of jobs the informer no longer knows —
        a deleted job must not leave immortal gauge series behind — and
        seeded from persisted ``status.lastHeartbeat`` for jobs this process
        hasn't heard from. The seeding is what keeps the staleness alarm
        armed across restart/failover: a hung slice stops posting, the new
        leader's in-memory map is empty, and without the persisted stamp the
        gauge would be *absent* (alert never fires) instead of *stale*."""
        with self._heartbeats_lock:
            beats = dict(self._heartbeats)
        if c is not None:
            # "default" fallback matches informer.object_key and the payload
            # env contract — an empty-string default would prune heartbeats
            # of namespace-less objects as stale.
            live = {}
            for obj in c.job_informer.store.list():
                md = obj.get("metadata") or {}
                live[(md.get("namespace", "default"),
                      md.get("name", ""))] = obj
            stale = [k for k in beats if k not in live]
            if stale:
                with self._heartbeats_lock:
                    for k in stale:
                        self._heartbeats.pop(k, None)
                for k in stale:
                    beats.pop(k, None)
            for key, obj in live.items():
                if key in beats:
                    continue
                persisted = (obj.get("status") or {}).get("lastHeartbeat")
                if persisted:
                    received = parse_rfc3339(str(persisted.get("time", "")))
                    beats[key] = {**persisted,
                                  "receivedAt": received or 0.0}
        return beats

    # -- endpoint bodies -------------------------------------------------------

    def readyz(self) -> tuple:
        c = self.controller
        if not self._leading.is_set() or c is None:
            return 200, "ok: standby"
        synced = all(inf.has_synced() for inf in c.factory.informers.values())
        return (200, "ok: leading, caches synced") if synced else (
            503, "not ready: caches syncing")

    def jobs_rollup(self) -> list:
        c = self.controller
        if c is None:
            return []
        beats = self._live_heartbeats(c)
        out = []
        for obj in c.job_informer.store.list():
            md = obj.get("metadata") or {}
            status = obj.get("status") or {}
            spec = obj.get("spec") or {}
            timeline = status.get("phaseTimeline") or {}
            ns, name = md.get("namespace", "default"), md.get("name", "")
            out.append({
                "namespace": ns,
                "name": name,
                "phase": status.get("phase", ""),
                "state": status.get("state", ""),
                "attempt": status.get("attempt", 0),
                "replicas": {
                    str(rs.get("tpuReplicaType", "WORKER")): rs.get("replicas", 0)
                    for rs in spec.get("replicaSpecs", [])
                },
                "replicaStatuses": status.get("replicaStatuses", []),
                "phaseTimeline": timeline,
                "durations": derived_durations(md, timeline),
                # Time-aware recovery state: the classified failure ledger
                # and, while parked in Backoff, the re-gang release time.
                "failures": status.get("failures", []),
                "backoffUntil": status.get("backoffUntil", ""),
                # Durability state: which step is actually safe to restart
                # from, and how the payload's checkpoint storage is faring.
                "checkpoint": status.get("checkpoint"),
                # Remote warm-start store roll-up + restart goodput.
                "store": status.get("store"),
                "goodput": status.get("goodput"),
                # Elastic-gang state: the attempt's granted world size,
                # resize accounting, and the remediation audit trail.
                "elastic": status.get("elastic"),
                # The in-memory heartbeat is fresher than the informer-cached
                # status copy (which lags by a reconcile + watch round-trip);
                # the internal receivedAt bookkeeping stays out of the API.
                "lastHeartbeat": _public_heartbeat(
                    beats.get((ns, name)) or status.get("lastHeartbeat")),
            })
        return out

    def traces_body(self, job: str, limit: int) -> Dict[str, Any]:
        """Recent spans, optionally filtered to the traces that touched
        one job (``?job=<ns>/<name>``): a trace qualifies when any of its
        spans carries the job's reconcile key attribute — the controller
        stamps it on every reconcile root span, which is what lets a
        timeline entry link back to the reconcile that caused it."""
        spans = tracing.recent_spans(0)
        if job:
            trace_ids = {s["traceId"] for s in spans
                         if (s.get("attrs") or {}).get("key") == job}
            spans = [s for s in spans if s["traceId"] in trace_ids]
        return {"spans": spans[:limit]}

    def timeline_body(self, namespace: str, name: str,
                      fmt: str = "") -> Tuple[int, str]:
        """The ``GET /api/jobs/<ns>/<name>/timeline`` body: the unified
        span tree (``?format=chrome`` → Chrome trace-event JSON)."""
        c = self.controller
        if c is None:
            return 503, json.dumps({"error": "standby: not leading"})
        obj = c.job_informer.store.get(namespace, name)
        if obj is None:
            return 404, json.dumps(
                {"error": f"unknown job {namespace}/{name}"})
        status = obj.get("status") or {}
        store = getattr(c, "timeline", None)
        events = store.events(namespace, name) if store is not None else []
        timeline = timeline_mod.assemble_timeline(
            namespace, name, status, events)
        if fmt == "chrome":
            return 200, json.dumps(timeline_mod.to_chrome_trace(timeline))
        return 200, json.dumps(timeline)

    def fleet_rollup(self) -> Dict[str, Any]:
        """The ``GET /api/fleet`` body: cluster goodput (the fold of the
        per-job ``status.goodput`` folds), per-queue admission-wait
        quantiles, preemption cost in lost step-seconds, and
        straggler/remediation counts."""
        c = self.controller
        jobs: List[Dict[str, Any]] = []
        queue_waits: Dict[str, Dict[str, float]] = {}
        if c is not None:
            for obj in c.job_informer.store.list():
                md = obj.get("metadata") or {}
                jobs.append({
                    "namespace": md.get("namespace", "default"),
                    "name": md.get("name", ""),
                    "status": obj.get("status") or {},
                })
            sched = getattr(c, "scheduler", None)
            if sched is not None and hasattr(sched, "queue_wait_quantiles"):
                queue_waits = sched.queue_wait_quantiles()
        return timeline_mod.fleet_rollup(jobs, queue_waits)

    def profile_directive_for(self, body: Dict[str, Any]
                              ) -> Optional[Dict[str, Any]]:
        """Pending profile directive to ride this heartbeat's 200 ACK —
        only process 0 captures (it owns the recorder + artifact path),
        and only while ``status.profile.state`` is Requested."""
        if body.get("processId") not in (None, 0):
            return None
        c = self.controller
        if c is None or not hasattr(c, "pending_profile"):
            return None
        name = str(body.get("name") or "")
        namespace = str(body.get("namespace") or "default")
        if not name:
            return None
        return c.pending_profile(namespace, name)

    def drain_directive_for(self, body: Dict[str, Any]
                            ) -> Optional[Dict[str, Any]]:
        """Pending cooperative-drain directive to ride this heartbeat's
        200 ACK — only process 0 adopts it (the consensus allgather
        spreads the latch to the gang), and only while
        ``status.drain.state`` is Requested. Resent on every beat until
        the payload's drainAck folds the state to Acked (the payload
        dedups by id)."""
        if body.get("processId") not in (None, 0):
            return None
        c = self.controller
        if c is None or not hasattr(c, "pending_drain"):
            return None
        name = str(body.get("name") or "")
        namespace = str(body.get("namespace") or "default")
        if not name:
            return None
        return c.pending_drain(namespace, name)

    def render_metrics(self) -> str:
        lines = self.metrics.render_lines()

        def emit(name: str, value: float, help_text: str,
                 mtype: str = "gauge", labels: Optional[Dict[str, str]] = None
                 ) -> None:
            full = METRIC_PREFIX + name
            lines.append(f"# HELP {full} {_escape_help(help_text)}")
            lines.append(f"# TYPE {full} {mtype}")
            lines.append(f"{full}{_label_str(labels or {})} {_fmt(value)}")

        emit("leading", 1 if self._leading.is_set() else 0,
             "1 if this instance holds the leader lease.")

        c = self.controller
        if c is not None:
            q = c.queue
            emit("workqueue_depth", len(q),
                 "Pending keys in the reconcile workqueue.")
            if hasattr(q, "unfinished_work_seconds"):
                emit("workqueue_unfinished_work_seconds",
                     q.unfinished_work_seconds(),
                     "Seconds of work in progress that has not been marked "
                     "done yet, summed over workers.")
            if hasattr(q, "longest_running_processor_seconds"):
                emit("workqueue_longest_running_processor_seconds",
                     q.longest_running_processor_seconds(),
                     "Seconds the longest-running worker has been processing "
                     "its current key.")

            phases: Dict[str, int] = {}
            for obj in c.job_informer.store.list():
                phase = (obj.get("status") or {}).get("phase") or "None"
                phases[phase] = phases.get(phase, 0) + 1
            full = METRIC_PREFIX + "jobs"
            lines.append(f"# HELP {full} TPUJobs known to the informer cache, by phase.")
            lines.append(f"# TYPE {full} gauge")
            for phase, n in sorted(phases.items()):
                lines.append(f'{full}{{phase="{_escape_label(phase)}"}} {n}')

            # Fleet rollup gauges — derived per scrape from the same
            # aggregation /api/fleet serves, so the two can never drift.
            fleet = self.fleet_rollup()
            emit("fleet_goodput_ratio", fleet["goodput"]["ratio"],
                 "Cluster goodput: sum of per-job useful step-seconds "
                 "over sum of per-job wallclock — the fold of the "
                 "status.goodput folds.")
            emit("fleet_preemption_lost_step_seconds",
                 fleet["preemption"]["lostStepSeconds"],
                 "Step-seconds re-run because restarts resumed behind "
                 "the step reached at failure (ledger lostSteps x "
                 "current step time), summed over live jobs.")
            emit("fleet_straggler_count", fleet["stragglers"]["flagged"],
                 "Gang members currently flagged in status.stragglers, "
                 "summed over live jobs.")
            emit("fleet_remediation_count",
                 fleet["stragglers"]["remediations"],
                 "Straggler remediations recorded in the elastic audit "
                 "trails of live jobs.")
            if fleet["queues"]:
                full = METRIC_PREFIX + "fleet_queue_wait_seconds"
                lines.append(f"# HELP {full} Admission-queue wait "
                             f"quantiles per fair-share queue, over the "
                             f"scheduler's recent-admission window.")
                lines.append(f"# TYPE {full} gauge")
                for queue, stats in sorted(fleet["queues"].items()):
                    for quantile in ("p50", "p95"):
                        labels = _label_str({
                            "queue": queue,
                            "quantile": "0.5" if quantile == "p50"
                            else "0.95"})
                        lines.append(
                            f"{full}{labels} {_fmt(stats[quantile])}")

            beats = self._live_heartbeats(c)
            if beats:
                gauges = (
                    ("job_last_step", "step",
                     "Last training step reported by the payload."),
                    ("job_step_time_seconds", "stepTimeSeconds",
                     "Last reported seconds per training step."),
                    ("job_tokens_per_second", "tokensPerSec",
                     "Last reported training throughput in tokens/sec."),
                    ("job_loss", "loss", "Last reported training loss."),
                    ("job_last_checkpoint_step", "lastCheckpointStep",
                     "Last verified (durable) checkpoint step reported by "
                     "the payload."),
                    ("job_store_last_uploaded_step", "storeLastUploadedStep",
                     "Newest checkpoint step durable in the remote "
                     "warm-start store (what a fresh-node restart "
                     "warm-starts from)."),
                )
                for metric, field, help_text in gauges:
                    rows = [((ns, name), hb[field])
                            for (ns, name), hb in sorted(beats.items())
                            if field in hb]
                    if not rows:
                        continue
                    full = METRIC_PREFIX + metric
                    lines.append(f"# HELP {full} {_escape_help(help_text)}")
                    lines.append(f"# TYPE {full} gauge")
                    for (ns, name), value in rows:
                        labels = _label_str({"namespace": ns, "name": name})
                        lines.append(f"{full}{labels} {_fmt(value)}")
                full = METRIC_PREFIX + "job_last_heartbeat_timestamp_seconds"
                lines.append(f"# HELP {full} Unix time the operator last "
                             f"received a heartbeat for the job.")
                lines.append(f"# TYPE {full} gauge")
                for (ns, name), hb in sorted(beats.items()):
                    labels = _label_str({"namespace": ns, "name": name})
                    lines.append(f"{full}{labels} {_fmt(hb['receivedAt'])}")
        return "\n".join(lines) + "\n"

    def render_dashboard(self) -> str:
        rows = []
        for j in self.jobs_rollup():
            replicas = ", ".join(f"{k}×{v}" for k, v in j["replicas"].items())
            hb = j.get("lastHeartbeat") or {}
            heartbeat = (f"step {hb.get('step', '?')} @ {hb.get('time', '')}"
                         if hb else "—")
            runtime = (j.get("durations") or {}).get("runtimeSeconds")
            ttr = (j.get("durations") or {}).get("timeToRunningSeconds")
            timing = " / ".join(
                f"{label} {value:.1f}s"
                for label, value in (("to-running", ttr), ("runtime", runtime))
                if value is not None) or "—"
            rows.append(
                "<tr>" + "".join(
                    f"<td>{html.escape(str(v))}</td>"
                    for v in (j["namespace"], j["name"], j["phase"],
                              j["state"], j["attempt"], replicas,
                              timing, heartbeat)
                ) + "</tr>"
            )
        body = "".join(rows) or '<tr><td colspan="8"><i>no jobs</i></td></tr>'
        leading = "leading" if self._leading.is_set() else "standby"
        return (
            "<!doctype html><html><head><title>tpu-operator</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "table{border-collapse:collapse}td,th{border:1px solid #ccc;"
            "padding:.4em .8em;text-align:left}</style></head><body>"
            f"<h1>tpu-operator <small>({leading})</small></h1>"
            "<table><tr><th>Namespace</th><th>Name</th><th>Phase</th>"
            "<th>State</th><th>Attempt</th><th>Replicas</th>"
            "<th>Timing</th><th>Heartbeat</th></tr>"
            f"{body}</table>"
            '<p><a href="/metrics">metrics</a> · <a href="/api/jobs">json</a>'
            ' · <a href="/api/traces">traces</a></p>'
            "</body></html>"
        )
