"""Deadline manager: exact-time reconcile wakeups for time obligations.

The operator's recovery machinery is event-driven — informer events enqueue
keys, reconciles react. Time-based obligations (backoff release, stall
watchdog, active deadline, finished-TTL) have no triggering event: without
help, they would only be noticed at resync granularity (30 s by default),
which on a TPU slice is 30 s of stranded hardware per incident.

This manager closes the gap using the workqueue's existing ``add_after``:
after every reconcile the controller asks the TrainingJob for its next time
obligation (``TrainingJob.next_time_obligation`` — an epoch timestamp) and
``sync``s it here; the manager schedules a delayed enqueue for that exact
moment. When the wakeup fires, the normal reconcile path runs and the
TrainingJob enforces whatever came due. Scheduling is idempotent: a wakeup
already pending at or before the requested time is not duplicated (each
reconcile re-syncs, so naive scheduling would arm one timer per pass).

The wall clock is injectable (tests drive exact release-time assertions);
it must be the same timebase the TrainingJob stamps status with (epoch
seconds via RFC3339), *not* the queue's monotonic clock — only the final
relative delay crosses into queue time.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional
from tpu_operator.util import joblife, lockdep

# Scheduling slack added to every wakeup so the reconcile runs just *after*
# the obligation (a wakeup landing a hair early would see nothing due,
# reschedule, and hop once more for no reason).
GRACE_SECONDS = 0.05


class DeadlineManager:
    """Schedules per-key reconcile wakeups at absolute wall-clock times."""

    def __init__(self, queue: Any,
                 clock: Callable[[], float] = time.time) -> None:
        self._queue = queue
        self._clock = clock
        self._lock = lockdep.lock("DeadlineManager._lock")
        # key -> pending wakeup epoch (best-effort view; the queue owns the
        # actual timers, which are never cancelled — a stale wakeup just
        # causes one cheap no-op reconcile).
        self._scheduled: Dict[str, float] = joblife.track(
            "DeadlineManager._scheduled")  # per-job: forget; guarded-by: _lock

    def sync(self, key: str, due: Optional[float]) -> None:
        """Ensure a reconcile of ``key`` runs at epoch ``due``.

        ``None`` clears the tracked obligation (already-armed queue timers
        still fire once; the reconcile they trigger is a no-op)."""
        if due is None:
            self.forget(key)
            return
        with self._lock:
            now = self._clock()
            pending = self._scheduled.get(key)
            if pending is not None and now < pending <= due + GRACE_SECONDS:
                # An earlier-or-equal wakeup is already in flight; it will
                # re-sync when it fires.
                return
            self._scheduled[key] = due
            delay = max(0.0, due - now) + GRACE_SECONDS
        # timer=True: a scheduled wakeup is not an error requeue — it stays
        # out of workqueue_retries_total, and queue latency counts from the
        # due time, not from (possibly hours-earlier) scheduling.
        self._queue.add_after(key, delay, timer=True)

    def forget(self, key: str) -> None:
        with self._lock:
            self._scheduled.pop(key, None)

    def pending(self, key: str) -> Optional[float]:
        """Tracked wakeup epoch for ``key`` (introspection/tests)."""
        with self._lock:
            return self._scheduled.get(key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._scheduled)
