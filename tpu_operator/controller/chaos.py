"""Chaos fault injection.

The reference declared ``--chaos-level`` and never used it (options.go:40 —
SURVEY.md quirks). Here it works: at level >= 0 the monkey periodically
deletes one random **running, operator-managed** pod, exercising exactly the
failure path TPU jobs live with in production (slice preemption → whole-group
restart). Level scales aggression: level N kills up to N+1 pods per tick.

Beyond pod kills, :class:`FlakyClientset` (opt-in ``--chaos-api-error-rate``)
attacks the operator's *own* control-plane calls: it wraps a clientset and
injects ApiError 429/500s and latency into CRUD verbs, so the retry/requeue
machinery (client/rest.py backoff, workqueue rate limiting, gang-create
rollback) is exercised continuously instead of only when production
misbehaves.

Never touches pods without the operator's group label, and never runs unless
explicitly enabled — same blast-radius discipline kube-monkey uses.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Any, Callable, Dict, Optional

from tpu_operator.apis.tpujob.v1alpha1.types import LABEL_GROUP_KEY
from tpu_operator.client import errors
from tpu_operator.util import lockdep

log = logging.getLogger(__name__)


class _OwnerRef:
    """Minimal EventRecorder target for the TPUJob that owns a killed pod —
    enough identity (.name/.namespace/.metadata) to anchor the Event without
    fetching the full object."""

    def __init__(self, namespace: str, name: str, uid: str):
        self.namespace = namespace
        self.name = name
        self.metadata = {"name": name, "namespace": namespace, "uid": uid}


class ChaosMonkey:
    def __init__(self, clientset: Any, namespace: str = "", level: int = 0,
                 interval: float = 30.0, rng: random.Random | None = None,
                 recorder: Optional[Any] = None,
                 metrics: Optional[Any] = None):
        self.clientset = clientset
        self.namespace = namespace
        self.level = level
        self.interval = interval
        self.rng = rng or random.Random()
        self.recorder = recorder
        self.metrics = metrics

    def _record_kill(self, pod: Dict[str, Any]) -> None:
        """A chaos kill must be attributable after the fact: a ChaosPodKill
        event on the owning TPUJob (so ``kubectl describe`` explains the
        restart) and a chaos_kills_total tick (so dashboards separate
        injected faults from organic ones)."""
        if self.metrics is not None:
            self.metrics.inc("chaos_kills_total")
        if self.recorder is None:
            return
        md = pod.get("metadata") or {}
        for ref in md.get("ownerReferences") or []:
            if ref.get("kind") == "TPUJob":
                owner = _OwnerRef(md.get("namespace", "default"),
                                  ref.get("name", ""), ref.get("uid", ""))
                self.recorder.event(
                    owner, "Warning", "ChaosPodKill",
                    f"chaos monkey deleted pod {md.get('name', '')}")
                break

    def kill_once(self) -> int:
        """Delete up to level+1 random managed running pods; returns count."""
        pods = [
            p for p in self.clientset.pods.list(
                self.namespace, label_selector=LABEL_GROUP_KEY
            )
            if (p.get("status") or {}).get("phase") in ("Running", "Pending")
        ]
        if not pods:
            return 0
        victims = self.rng.sample(pods, k=min(self.level + 1, len(pods)))
        killed = 0
        for pod in victims:
            md = pod["metadata"]
            try:
                self.clientset.pods.delete(md.get("namespace", "default"), md["name"])
                killed += 1
                log.warning("chaos: killed pod %s", md["name"])
                self._record_kill(pod)
            except errors.ApiError as e:
                if not errors.is_not_found(e):
                    log.warning("chaos: failed to kill %s: %s", md["name"], e)
        return killed

    def run(self, stop_event: threading.Event) -> None:
        if self.level < 0:
            return
        log.warning("chaos monkey enabled: level=%d interval=%.0fs",
                    self.level, self.interval)
        while not stop_event.wait(self.interval):
            try:
                self.kill_once()
            except Exception as e:  # noqa: BLE001
                log.warning("chaos tick failed: %s", e)


# --- API-level fault injection ----------------------------------------------

# Verbs the flaky wrapper intercepts — every CRUD surface the operator uses.
# ``watch`` deliberately passes through: a failed watch *open* already goes
# through the REST retry path, and mid-stream faults are the apiserver
# harness's kill() domain.
FLAKY_VERBS = frozenset({
    "create", "get", "list", "list_with_version", "update", "update_status",
    "delete", "delete_collection",
})


class _FlakyResourceClient:
    """One resource client with fault injection in front of every verb."""

    def __init__(self, inner: Any, chaos: "FlakyClientset"):
        self._inner = inner
        self._chaos = chaos

    def __getattr__(self, name: str) -> Any:
        attr = getattr(self._inner, name)
        if name not in FLAKY_VERBS or not callable(attr):
            return attr

        def flaky(*args: Any, **kwargs: Any) -> Any:
            self._chaos.maybe_fail(name, getattr(self._inner, "kind", ""))
            self._chaos.maybe_lag()
            return attr(*args, **kwargs)

        return flaky


class FlakyClientset:
    """Wraps a clientset (fake or REST) so each CRUD call fails with an
    injected ApiError 429/500 at ``error_rate`` probability, optionally
    adding uniform latency up to ``max_latency`` seconds — the operator's
    own API weather, made reproducible (seeded ``rng``) for the chaos soak
    test and opt-in in production via ``--chaos-api-error-rate``."""

    RESOURCES = ("pods", "services", "events", "endpoints", "configmaps",
                 "leases", "tpujobs", "nodes")

    def __init__(self, inner: Any, error_rate: float = 0.1,
                 max_latency: float = 0.0,
                 rng: Optional[random.Random] = None,
                 metrics: Optional[Any] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self._inner = inner
        self.error_rate = max(0.0, min(1.0, error_rate))
        self.max_latency = max(0.0, max_latency)
        # One lock around the RNG: verbs fire from every controller thread,
        # and an unguarded Random would shear its state (and determinism).
        self._rng_lock = lockdep.lock("FlakyClientset._rng_lock")
        self._rng = rng or random.Random()  # guarded-by: _rng_lock
        self.metrics = metrics
        self._sleep = sleep
        for resource in self.RESOURCES:
            if hasattr(inner, resource):
                setattr(self, resource,
                        _FlakyResourceClient(getattr(inner, resource), self))

    def __getattr__(self, name: str) -> Any:
        # Non-resource attributes (e.g. ``rest``) pass straight through.
        return getattr(self._inner, name)

    def maybe_fail(self, verb: str, kind: str) -> None:
        with self._rng_lock:
            roll = self._rng.random()
            flavor = self._rng.random()
        if roll >= self.error_rate:
            return
        if self.metrics is not None:
            self.metrics.inc("chaos_api_errors_total")
        code = 429 if flavor < 0.5 else 500
        raise errors.ApiError(
            code, message=f"chaos: injected {code} on {verb} {kind}")

    def maybe_lag(self) -> None:
        if self.max_latency <= 0:
            return
        with self._rng_lock:
            lag = self._rng.random() * self.max_latency
        self._sleep(lag)
