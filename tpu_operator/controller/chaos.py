"""Chaos fault injection.

The reference declared ``--chaos-level`` and never used it (options.go:40 —
SURVEY.md quirks). Here it works: at level >= 0 the monkey periodically
deletes one random **running, operator-managed** pod, exercising exactly the
failure path TPU jobs live with in production (slice preemption → whole-group
restart). Level scales aggression: level N kills up to N+1 pods per tick.

Never touches pods without the operator's group label, and never runs unless
explicitly enabled — same blast-radius discipline kube-monkey uses.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Any, Dict, Optional

from tpu_operator.apis.tpujob.v1alpha1.types import LABEL_GROUP_KEY
from tpu_operator.client import errors

log = logging.getLogger(__name__)


class _OwnerRef:
    """Minimal EventRecorder target for the TPUJob that owns a killed pod —
    enough identity (.name/.namespace/.metadata) to anchor the Event without
    fetching the full object."""

    def __init__(self, namespace: str, name: str, uid: str):
        self.namespace = namespace
        self.name = name
        self.metadata = {"name": name, "namespace": namespace, "uid": uid}


class ChaosMonkey:
    def __init__(self, clientset: Any, namespace: str = "", level: int = 0,
                 interval: float = 30.0, rng: random.Random | None = None,
                 recorder: Optional[Any] = None,
                 metrics: Optional[Any] = None):
        self.clientset = clientset
        self.namespace = namespace
        self.level = level
        self.interval = interval
        self.rng = rng or random.Random()
        self.recorder = recorder
        self.metrics = metrics

    def _record_kill(self, pod: Dict[str, Any]) -> None:
        """A chaos kill must be attributable after the fact: a ChaosPodKill
        event on the owning TPUJob (so ``kubectl describe`` explains the
        restart) and a chaos_kills_total tick (so dashboards separate
        injected faults from organic ones)."""
        if self.metrics is not None:
            self.metrics.inc("chaos_kills_total")
        if self.recorder is None:
            return
        md = pod.get("metadata") or {}
        for ref in md.get("ownerReferences") or []:
            if ref.get("kind") == "TPUJob":
                owner = _OwnerRef(md.get("namespace", "default"),
                                  ref.get("name", ""), ref.get("uid", ""))
                self.recorder.event(
                    owner, "Warning", "ChaosPodKill",
                    f"chaos monkey deleted pod {md.get('name', '')}")
                break

    def kill_once(self) -> int:
        """Delete up to level+1 random managed running pods; returns count."""
        pods = [
            p for p in self.clientset.pods.list(
                self.namespace, label_selector=LABEL_GROUP_KEY
            )
            if (p.get("status") or {}).get("phase") in ("Running", "Pending")
        ]
        if not pods:
            return 0
        victims = self.rng.sample(pods, k=min(self.level + 1, len(pods)))
        killed = 0
        for pod in victims:
            md = pod["metadata"]
            try:
                self.clientset.pods.delete(md.get("namespace", "default"), md["name"])
                killed += 1
                log.warning("chaos: killed pod %s", md["name"])
                self._record_kill(pod)
            except errors.ApiError as e:
                if not errors.is_not_found(e):
                    log.warning("chaos: failed to kill %s: %s", md["name"], e)
        return killed

    def run(self, stop_event: threading.Event) -> None:
        if self.level < 0:
            return
        log.warning("chaos monkey enabled: level=%d interval=%.0fs",
                    self.level, self.interval)
        while not stop_event.wait(self.interval):
            try:
                self.kill_once()
            except Exception as e:  # noqa: BLE001
                log.warning("chaos tick failed: %s", e)
