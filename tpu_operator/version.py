"""Version metadata for the operator binary.

Reference parity: version/version.go:22-40 (Version/GitSHA + runtime info,
``--version`` prints and exits).
"""

import platform
import sys

VERSION = "0.1.0"
GIT_SHA = "dev"


def info() -> str:
    """Human-readable version block, printed by ``--version``."""
    return "\n".join(
        [
            f"tpu-operator Version: {VERSION}",
            f"Git SHA: {GIT_SHA}",
            f"Python Version: {platform.python_version()}",
            f"Python Compiler: {platform.python_compiler()}",
            f"Platform: {sys.platform}/{platform.machine()}",
        ]
    )
