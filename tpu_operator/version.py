"""Version metadata for the operator binary.

Reference parity: version/version.go:22-40 (Version/GitSHA + runtime info,
``--version`` prints and exits). The reference's GitSHA was injected by
-ldflags at build; here the image build writes ``tpu_operator/_build_info.py``
(Dockerfile ``ARG GIT_SHA`` → one-line module), with an env override for
ad-hoc runs. Unstamped dev checkouts report "dev" — the same behavior as
the reference's "Not provided." fallback, but the shipped images are
stamped.
"""

import os
import platform
import sys

VERSION = "0.1.0"


def _resolve_git_sha() -> str:
    env = os.environ.get("TPU_OPERATOR_GIT_SHA", "")
    if env:
        return env
    try:
        from tpu_operator._build_info import GIT_SHA as baked
        return baked
    except ImportError:
        return "dev"


GIT_SHA = _resolve_git_sha()


def info() -> str:
    """Human-readable version block, printed by ``--version``."""
    return "\n".join(
        [
            f"tpu-operator Version: {VERSION}",
            f"Git SHA: {GIT_SHA}",
            f"Python Version: {platform.python_version()}",
            f"Python Compiler: {platform.python_compiler()}",
            f"Platform: {sys.platform}/{platform.machine()}",
        ]
    )
