"""tpu_operator — a TPU-native Kubernetes job operator.

A brand-new implementation of the capability set of the reference
mx-operator (StefanoFioravanzo/tf-operator): a ``TPUJob`` custom resource
plus a reconciling controller that turns a declarative replica spec into
pods and discovery services, forms a single JAX multi-controller process
group over a TPU pod slice, and manages the full job lifecycle.

Where the reference (pure Go, ``pkg/...``) bootstraps MXNet parameter-server
topologies through ``DMLC_*`` environment variables, this operator bootstraps
JAX/XLA process groups over TPU ICI/DCN: replica pods request
``cloud-tpus.google.com/v*`` chips and receive ``jax.distributed`` coordinator
env (``JAX_COORDINATOR_ADDRESS``, ``TPU_WORKER_ID``, ``TPU_WORKER_HOSTNAMES``,
megascale DCN discovery vars). Collective bytes ride the TPU fabric itself,
so — exactly like the reference — the operator's communication surface is
bootstrap-only.

Layer map (mirrors SURVEY.md §1 for the reference):

- ``apis/``       CRD schema, defaults, validation, helpers
                  (ref: pkg/apis/mxnet/{v1alpha1,validation,helper})
- ``client/``     REST client, typed clientset, informers, workqueue, fakes
                  (ref: pkg/client/** generated stack — hand-built here)
- ``controller/`` reconcile engine, leader election, event recording
                  (ref: pkg/controller/controller.go, cmd/.../server.go)
- ``trainer/``    job domain logic: TrainingJob lifecycle + TPUReplicaSet
                  (ref: pkg/trainer/{training,replicas,labels}.go)
- ``util/``       tracing, naming, kubeconfig resolution
                  (ref: pkg/util/**, go-tracey)
- ``payload/``    the data plane the reference keeps in user images:
                  JAX bootstrap + reference workloads (linear regression,
                  data-parallel CIFAR-10 ResNet on a device mesh)
- ``cmd/``        process entry: flags, server bootstrap, leader election
                  (ref: cmd/mx-operator/**)
- ``testing/``    in-process fake apiserver (envtest-style tier)
"""

from tpu_operator.version import VERSION

__version__ = VERSION
