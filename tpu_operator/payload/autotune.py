"""Self-tuning data plane: closed-loop knob control for the step loop.

PR 9's flight recorder made steady-state step time *measurable* — each
step splits into DATA / DISPATCH / COMPUTE / CHECKPOINT / HOST phases —
but every job still shipped ONE static data-plane config: prefetch depth
pinned at construction, heartbeat serialization and log formatting on the
step thread, checkpoint cadence fixed. This module closes the loop, the
same declarative-spec → runtime-managed philosophy the operator applies
to pods applied to the data plane itself:

- :class:`DataPlaneController` reads the recorder's per-step records and,
  every ``windowSteps`` steps, hill-climbs the live knobs with hysteresis
  — converging toward minimal non-COMPUTE residue and backing a change
  out when the next window shows the step time regressed:

  * **prefetch depth** (``PrefetchControl``): the ``device_prefetch``
    deque is resizable at iteration boundaries (data.py); DATA-bound
    windows deepen it toward ``maxDepth``, a regression reverts.
  * **host path** (``AsyncHost``): when HOST dominates the residue,
    heartbeat serialization + POSTs and log formatting move off the step
    thread onto a bounded worker (the step path pays an enqueue).
  * **checkpoint cadence**: when CHECKPOINT stalls dominate, the save
    interval stretches (×2 up to ``CHECKPOINT_CADENCE_CAP``× the
    payload's configured interval — never below it, so durability only
    ever *coarsens* within the bound, and a regression reverts). A
    gang's save is a collective, so in multi-process jobs the knob goes
    through the checkpointer's GANG-AGREED mode (``enable_gang_cadence``
    + the injectable ``agree_fn`` allgather-min): each base-interval
    boundary takes the gang MINIMUM of the per-process proposals, so a
    disagreeing gang saves at the most conservative member's cadence —
    the stretch only takes effect once every member's controller agrees,
    and the save barrier can never mismatch.

- :class:`HostPipeline` is the direct residue elimination next to the
  feedback loop: a bounded background thread runs the host iterator's
  ``next()`` + the ``put_global_batch`` conversion AHEAD of consumption.
  ``device_prefetch`` alone only overlaps the (async) device transfer —
  the host-side batch generation cost was serialized into DATA.

- Current knob values ride the heartbeat (``dataPlane`` body key) →
  statusserver sanitization → ``status.dataPlane`` + the
  ``job_prefetch_depth`` gauge and
  ``job_autotune_adjustments_total{knob,direction}`` counter.

Env contract (trainer/replicas.py injects when ``spec.dataPlane`` is
present): ``TPUJOB_DATAPLANE_PREFETCH_DEPTH`` (0 = auto — see
:func:`resolve_prefetch_depth`), ``TPUJOB_DATAPLANE_AUTOTUNE``,
``TPUJOB_DATAPLANE_MIN_DEPTH``, ``TPUJOB_DATAPLANE_MAX_DEPTH``,
``TPUJOB_DATAPLANE_WINDOW_STEPS``. Absent env = an inert runtime: the
static depth the caller passed, no controller, no threads — existing
jobs behave exactly as before.

Stdlib-only on purpose: the controller (statusserver sanitization,
schema) imports the adjustment-key names from here, and this module must
not drag jax into the control plane — same discipline as
``payload/steptrace.py``. The device-placement work the pipeline runs is
an injected callable.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
from typing import Any, Callable, Dict, Optional

from tpu_operator.payload import heartbeat as heartbeat_mod
from tpu_operator.payload import steptrace as steptrace_mod
from tpu_operator.util import lockdep

log = logging.getLogger(__name__)

# Operator env contract (trainer/replicas.py injects when spec.dataPlane
# is present; absent env = inert runtime, the pre-autotune behavior).
ENV_PREFETCH_DEPTH = "TPUJOB_DATAPLANE_PREFETCH_DEPTH"
ENV_AUTOTUNE = "TPUJOB_DATAPLANE_AUTOTUNE"
ENV_MIN_DEPTH = "TPUJOB_DATAPLANE_MIN_DEPTH"
ENV_MAX_DEPTH = "TPUJOB_DATAPLANE_MAX_DEPTH"
ENV_WINDOW_STEPS = "TPUJOB_DATAPLANE_WINDOW_STEPS"

# Default static prefetch depth — what ``prefetchDepth: 0`` (auto)
# resolves to before the controller starts moving it; identical to the
# depth train_loop always shipped, so auto-without-autotune is exactly
# the old behavior.
DEFAULT_PREFETCH_DEPTH = 2

# Autotune bounds/window defaults (spec.dataPlane.autotune mirrors these
# in types.py; the spec module is the contract home, this is the runtime
# fallback for env-driven construction).
DEFAULT_MIN_DEPTH = 1
DEFAULT_MAX_DEPTH = 8
DEFAULT_WINDOW_STEPS = 32

# Checkpoint cadence stretches by powers of two up to this multiple of
# the payload's configured save interval — the "spec bound": autotune may
# coarsen durability, never below the configured cadence and never past
# this cap.
CHECKPOINT_CADENCE_CAP = 4

# A window only triggers tuning when the non-COMPUTE residue is material:
# at least this fraction of the mean step (and the dominant phase at
# least half of it) — µs-level noise between phases must not move knobs.
RESIDUE_FLOOR_FRACTION = 0.02

# Regression hysteresis — the verdict compares the knob's ATTRIBUTABLE
# signal, the per-process local share (step seconds minus the COMPUTE
# wait): in a synchronous gang the collectives equalize whole-step time
# to the slowest member (payload/steptrace.py's straggler rationale), so
# a whole-step verdict would revert good local changes on peer noise and
# freeze knobs gang-wide. A change is reverted when the verdict window's
# local mean exceeds the pre-change baseline by more than this fraction
# OF THE WHOLE STEP (the absolute threshold scales with the step, so a
# µs-level local share can't flap on µs-level noise).
HYSTERESIS_FRACTION = 0.03

# Coarse whole-step guard on top of the local verdict: a knob move that
# regresses the WHOLE step this much past its baseline reverts even when
# the local share looks fine (e.g. a deeper prefetch window pressuring
# device memory shows up compute-side, not in the local share). 3x the
# local hysteresis so ordinary gang-wide noise doesn't trip it.
STEP_GUARD_FRACTION = 3 * HYSTERESIS_FRACTION

# Evaluation-window floor, ONE definition with the spec layer
# (validation.py and the schema minimum import it): a smaller window's
# phase means are noise, and the hill climb would chase it.
MIN_WINDOW_STEPS = 8

# After a reverted adjustment the knob freezes for this many windows, so
# a borderline signal cannot oscillate a knob every other window.
HOLD_WINDOWS = 8

# Wire keys of the per-knob adjustment counters the heartbeat carries
# (``dataPlane.adjustments``); the statusserver sanitizes against this
# tuple and the controller fold maps each to its {knob,direction} metric
# labels via KNOB_OF.
ADJUSTMENT_KEYS = ("prefetchUp", "prefetchDown", "hostUp", "hostDown",
                   "checkpointUp", "checkpointDown")
KNOB_OF = {
    "prefetchUp": ("prefetch", "up"),
    "prefetchDown": ("prefetch", "down"),
    "hostUp": ("host", "up"),
    "hostDown": ("host", "down"),
    "checkpointUp": ("checkpoint", "up"),
    "checkpointDown": ("checkpoint", "down"),
}


def add_prefetch_argument(parser: Any,
                          env: Optional[Dict[str, str]] = None) -> None:
    """The shared ``--prefetch-depth`` arg of the operator-launched
    payloads (cifar/transformer/moe/pipeline): defaults from the injected
    env, so ``spec.dataPlane.prefetchDepth`` reaches the loop without
    per-payload plumbing and a static depth is settable without
    autotune; 0 keeps the auto convention. One definition so the
    payloads cannot drift."""
    e = env if env is not None else os.environ
    default = _env_int(e, ENV_PREFETCH_DEPTH, 0)
    parser.add_argument(
        "--prefetch-depth", type=int, default=default,
        help="device-prefetch depth: batches kept in flight ahead of "
             "the step (0 = auto — the shipped default, tuned live when "
             "spec.dataPlane.autotune is enabled; defaults from the "
             "operator-injected $TPUJOB_DATAPLANE_PREFETCH_DEPTH)")


def resolve_prefetch_depth(depth: int,
                           default: int = DEFAULT_PREFETCH_DEPTH) -> int:
    """Resolve the spec/arg-level prefetch-depth convention to a concrete
    starting depth: ``> 0`` is an explicit static depth, ``0`` means AUTO
    (the runtime picks — ``default`` statically, the controller live when
    autotune is enabled). Negative is a config error and fails loudly —
    ``device_prefetch`` historically degenerated any ``depth <= 0`` to
    the unbuffered path silently, which made a spec-level 0 mean the
    opposite of its documented convention."""
    depth = int(depth)
    if depth < 0:
        raise ValueError(
            f"prefetch depth must be >= 0 (0 = auto), got {depth}")
    return depth if depth > 0 else int(default)


class PrefetchControl:
    """Live prefetch-depth knob shared between the controller (writer, on
    the step thread) and the prefetch path (reader — the step thread in
    synchronous mode, the :class:`HostPipeline` worker in pipelined
    mode). One int behind a leaf lock; reads off the step path."""

    def __init__(self, depth: int):
        self._lock = lockdep.lock("PrefetchControl._lock")
        self._depth = max(0, int(depth))  # guarded-by: _lock

    @property
    def depth(self) -> int:
        with self._lock:
            return self._depth

    def set_depth(self, depth: int) -> None:
        with self._lock:
            self._depth = max(0, int(depth))


class AsyncHost:
    """Bounded background worker for host-side telemetry: heartbeat
    serialization + POSTs and log formatting run here instead of on the
    step thread, so the step path pays an enqueue (one lock + append)
    rather than a socket round-trip. Telemetry is lossy by contract —
    when the queue is full (a wedged status server back-pressuring
    through the POST timeout) new work is DROPPED and counted, never
    queued unboundedly and never blocking a step."""

    def __init__(self, capacity: int = 64, name: str = "dataplane-host"):
        self.capacity = max(1, int(capacity))
        self._cond = lockdep.condition("AsyncHost._cond")
        self._queue: collections.deque = collections.deque()  # guarded-by: _cond
        self._closed = False   # guarded-by: _cond
        self._started = False  # guarded-by: _cond
        self.dropped = 0       # guarded-by: _cond
        self._warned_drop = False  # guarded-by: _cond
        self._name = name
        self._thread: Optional[threading.Thread] = None
        self._failed_once = False  # worker-thread only

    def submit(self, fn: Callable, *args: Any) -> bool:
        """Enqueue ``fn(*args)`` for the worker; False when dropped
        (queue full or closed). FIFO: posts retain their build order."""
        with self._cond:
            if self._closed:
                return False
            if len(self._queue) >= self.capacity:
                self.dropped += 1
                warn = not self._warned_drop
                self._warned_drop = True
                if warn:
                    # First of a streak, outside the hot path's happy
                    # case: lossy-by-contract must still be OBSERVABLE —
                    # the wire carries the running count (hostDropped).
                    log.warning(
                        "async host queue full (%d): dropping telemetry "
                        "work; drops ride the heartbeat as hostDropped",
                        self.capacity)
                return False
            self._queue.append((fn, args))
            self._warned_drop = False
            if not self._started:
                self._started = True
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name=self._name)
                self._thread.start()
            self._cond.notify_all()
        return True

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if not self._queue:
                    return  # closed and drained
                fn, args = self._queue.popleft()
                self._cond.notify_all()
            try:
                fn(*args)
            except Exception as e:  # noqa: BLE001 — telemetry never kills training
                if not self._failed_once:
                    log.warning("async host work failed: %s", e)
                    self._failed_once = True

    @property
    def dropped_count(self) -> int:
        with self._cond:
            return self.dropped

    def close(self, timeout: float = 5.0) -> None:
        """Stop accepting work and drain what is queued (bounded): the
        final heartbeats of a finishing run usually land, a wedged poster
        cannot park the exit."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join(timeout=timeout)


class HostPipeline:
    """Runs ``fill()`` — the host iterator's ``next()`` plus the
    ``put_global_batch`` device placement — on a background worker,
    bounded by the live prefetch depth, so host batch generation runs
    AHEAD of consumption instead of serialized into the step's DATA
    phase. Single worker: the stream order is exactly the iterator's.

    ``fill`` raises StopIteration at end of stream; any other exception
    is re-raised to the consumer at the position it occurred (the
    pipeline never silently truncates a failing stream)."""

    def __init__(self, fill: Callable[[], Any],
                 control: Optional[PrefetchControl] = None,
                 depth: int = DEFAULT_PREFETCH_DEPTH,
                 name: str = "dataplane-pipeline"):
        self._fill = fill
        self._control = control
        self._depth = max(1, int(depth))
        self._cond = lockdep.condition("HostPipeline._cond")
        self._buf: collections.deque = collections.deque()  # guarded-by: _cond
        self._done = False   # guarded-by: _cond
        self._stopped = False  # guarded-by: _cond
        self._error: Optional[BaseException] = None  # guarded-by: _cond
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._thread.start()

    def _target(self) -> int:
        # The live knob when wired, the fixed depth otherwise; depth
        # changes take effect at the worker's next refill decision.
        if self._control is not None:
            return max(1, self._control.depth)
        return self._depth

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._stopped and len(self._buf) >= self._target():
                    self._cond.wait()
                if self._stopped:
                    return
            # The fill — host RNG / file I/O / device placement — runs
            # OUTSIDE the lock: the consumer pops concurrently.
            try:
                item = self._fill()
            except StopIteration:
                with self._cond:
                    self._done = True
                    self._cond.notify_all()
                return
            except BaseException as e:  # noqa: BLE001 — re-raised to the consumer
                with self._cond:
                    self._error = e
                    self._done = True
                    self._cond.notify_all()
                return
            with self._cond:
                if self._stopped:
                    return
                self._buf.append(item)
                self._cond.notify_all()

    def get(self) -> Any:
        """Next batch in stream order; raises StopIteration at the end
        (or once the pipeline is closed — a post-close get must not park
        on a condition no worker will ever signal) and re-raises the
        worker's error at its stream position."""
        with self._cond:
            while not self._buf and not self._done and not self._stopped:
                self._cond.wait()
            if self._buf:
                item = self._buf.popleft()
                self._cond.notify_all()
                return item
            if self._error is not None:
                raise self._error
            raise StopIteration

    def close(self, timeout: float = 5.0) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)


class DataPlaneController:
    """Hill-climbs the live data-plane knobs from the flight recorder's
    per-step records (``StepRecorder`` ``on_commit`` observer).

    Every ``window_steps`` completed steps the controller evaluates ONE
    action, in strict priority order:

    1. **Settle/verdict** — if the previous window changed a knob, this
       window is the verdict: mean step time above the pre-change
       baseline (carried in the in-flight record) by more than
       ``HYSTERESIS_FRACTION`` reverts the change and freezes that knob
       for ``HOLD_WINDOWS`` windows; otherwise the change sticks. One
       change in flight at a time, so cause and effect stay
       attributable.
    2. **Climb** — with nothing in flight, walk the residue phases
       (DATA / HOST / CHECKPOINT) by descending share, above the
       materiality floor, and take the first knob with headroom: deepen
       prefetch, async the host path, stretch the checkpoint cadence.
       Clamped to [min_depth, max_depth] and ``CHECKPOINT_CADENCE_CAP``
       — a clamped or held knob falls through to the next phase rather
       than dead-ending the climb.

    Runs entirely on the step-loop thread (the observer fires at commit);
    the lock guards the counters/wire snapshot other threads read
    (heartbeat build may run on the AsyncHost worker)."""

    def __init__(self, control: PrefetchControl,
                 min_depth: int = DEFAULT_MIN_DEPTH,
                 max_depth: int = DEFAULT_MAX_DEPTH,
                 window_steps: int = DEFAULT_WINDOW_STEPS,
                 enable_host_async: Optional[Callable[[bool], None]] = None,
                 checkpointer: Optional[Any] = None):
        self.control = control
        self.min_depth = max(0, int(min_depth))
        self.max_depth = max(self.min_depth, int(max_depth))
        self.window_steps = max(MIN_WINDOW_STEPS, int(window_steps))
        self._enable_host_async = enable_host_async
        self._checkpointer = checkpointer
        self.host_async = False
        control.set_depth(min(self.max_depth,
                              max(self.min_depth, control.depth)))
        # Window accumulators: step-loop thread only.
        self._n = 0
        self._sums: Dict[str, float] = {}
        self._step_sum = 0.0
        self._local_sum = 0.0
        # One in-flight change: (knob, revert_fn, down_key,
        # pre-change local mean, pre-change step mean — the verdict's
        # baselines).
        self._pending: Optional[tuple] = None
        self._holds: Dict[str, int] = {}
        self.windows_evaluated = 0
        self._lock = lockdep.lock("DataPlaneController._lock")
        self._adjustments: Dict[str, int] = {  # guarded-by: _lock
            key: 0 for key in ADJUSTMENT_KEYS}

    # -- step-loop side --------------------------------------------------------

    def on_step(self, record: Dict[str, Any]) -> None:
        """StepRecorder commit observer: accumulate one step's phase laps
        (float adds only); evaluate at window boundaries."""
        self._n += 1
        seconds = record.get("seconds", 0.0)
        self._step_sum += seconds
        # The per-process LOCAL share (seconds minus the compute wait):
        # the verdict's signal — collectives equalize everything else.
        self._local_sum += max(
            0.0, seconds - record.get(steptrace_mod.COMPUTE, 0.0))
        for phase in (steptrace_mod.DATA, steptrace_mod.HOST,
                      steptrace_mod.CHECKPOINT, steptrace_mod.COMPUTE):
            if phase in record:
                self._sums[phase] = self._sums.get(phase, 0.0) \
                    + record[phase]
        if self._n >= self.window_steps:
            try:
                self._evaluate()
            except Exception:  # noqa: BLE001 — tuning must never kill training
                log.exception("autotune window evaluation failed; "
                              "knobs left as-is")
            self._n = 0
            self._sums = {}
            self._step_sum = 0.0
            self._local_sum = 0.0

    def _mean(self, phase: str) -> float:
        return self._sums.get(phase, 0.0) / max(1, self._n)

    def _count(self, key: str) -> None:
        with self._lock:
            self._adjustments[key] += 1

    def _evaluate(self) -> None:
        self.windows_evaluated += 1
        step_mean = self._step_sum / max(1, self._n)
        local_mean = self._local_sum / max(1, self._n)
        for knob in list(self._holds):
            self._holds[knob] -= 1
            if self._holds[knob] <= 0:
                del self._holds[knob]
        if self._pending is not None:
            knob, revert, down_key, base_local, base_step = self._pending
            self._pending = None
            # The sensitive verdict is the LOCAL share — the only signal
            # a gang's collectives don't equalize to the slowest member,
            # so peer noise can't revert a good local change (threshold
            # scaled by the whole step, see HYSTERESIS_FRACTION). The
            # coarse whole-step guard still catches a move whose cost
            # lands compute-side (e.g. device memory pressure).
            regressed = (
                local_mean > base_local + HYSTERESIS_FRACTION
                * max(step_mean, base_step)
                or (base_step > 0 and step_mean > base_step
                    * (1.0 + STEP_GUARD_FRACTION)))
            if regressed:
                # Back the change out and hold the knob.
                revert()
                self._count(down_key)
                self._holds[knob] = HOLD_WINDOWS
                log.info("autotune: reverted %s (local %.6fs vs %.6fs, "
                         "step %.6fs vs %.6fs)", knob, local_mean,
                         base_local, step_mean, base_step)
            # Accepted or reverted, the verdict WAS this window's one
            # action: climbing again immediately would put a second
            # change in flight against a baseline the verdict just moved.
            return
        data_m = self._mean(steptrace_mod.DATA)
        host_m = self._mean(steptrace_mod.HOST)
        ckpt_m = self._mean(steptrace_mod.CHECKPOINT)
        floor = RESIDUE_FLOOR_FRACTION * step_mean
        if data_m + host_m + ckpt_m < floor:
            return
        # Walk knobs by descending residue share instead of only the
        # single dominant one: a capped or held knob must not dead-end
        # the climb while another material phase still has headroom.
        for phase_mean, knob in sorted(
                ((data_m, "prefetch"), (host_m, "host"),
                 (ckpt_m, "checkpoint")), reverse=True):
            if phase_mean < floor / 2:
                return  # sorted: everything after is smaller still
            if knob in self._holds:
                continue
            if self._climb(knob, local_mean, step_mean):
                return

    def _climb(self, knob: str, local_mean: float,
               step_mean: float) -> bool:
        """Propose ``knob``'s next move as the window's in-flight change;
        False when the knob has no headroom (clamped at its bound, or
        its collaborator is absent) so ``_evaluate`` can try the
        next-most-material phase instead."""
        if knob == "prefetch":
            depth = self.control.depth
            if depth >= self.max_depth:
                return False
            self.control.set_depth(depth + 1)
            self._count("prefetchUp")
            self._pending = ("prefetch",
                             lambda: self.control.set_depth(depth),
                             "prefetchDown", local_mean, step_mean)
            return True
        if knob == "host":
            if self.host_async or self._enable_host_async is None:
                return False
            self._set_host_async(True)
            self._count("hostUp")
            self._pending = ("host", lambda: self._set_host_async(False),
                             "hostDown", local_mean, step_mean)
            return True
        ck = self._checkpointer
        if ck is None:
            return False
        mult = int(getattr(ck, "cadence_multiplier", 1))
        if mult >= CHECKPOINT_CADENCE_CAP:
            return False
        ck.cadence_multiplier = mult * 2

        def revert(ck=ck, mult=mult):
            ck.cadence_multiplier = mult

        self._count("checkpointUp")
        self._pending = ("checkpoint", revert, "checkpointDown",
                         local_mean, step_mean)
        return True

    def _set_host_async(self, enabled: bool) -> None:
        self.host_async = enabled
        if self._enable_host_async is not None:
            self._enable_host_async(enabled)

    # -- wire side -------------------------------------------------------------

    def adjustments(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._adjustments)


class DataPlaneRuntime:
    """One attempt's data-plane wiring, owned by ``train_loop``: the
    resolved prefetch depth, the live control + controller when autotune
    is on, the background host pipeline, and the async host worker. An
    INERT runtime (no spec.dataPlane env) carries only the static depth
    and costs the loop nothing — no threads, no wire key, no observer."""

    def __init__(self, depth: int, control: Optional[PrefetchControl] = None,
                 controller: Optional[DataPlaneController] = None,
                 pipeline: bool = False, active: bool = False):
        self.depth = depth
        self.control = control
        self.controller = controller
        self.pipeline = pipeline
        self.active = active
        self.host: Optional[AsyncHost] = None
        self._heartbeat: Optional[Any] = None
        self._hb_interval = heartbeat_mod.DEFAULT_INTERVAL

    @classmethod
    def static(cls, depth: int) -> "DataPlaneRuntime":
        """The inert runtime: the caller's depth verbatim (train_loop's
        historical contract — 0 = unbuffered; the 0=auto convention is a
        spec/arg-level concept resolved before depths reach here)."""
        return cls(int(depth))

    @property
    def host_async(self) -> bool:
        return self.controller is not None and self.controller.host_async

    def attach(self, recorder: Optional[Any] = None,
               heartbeat: Optional[Any] = None,
               checkpointer: Optional[Any] = None,
               processes: int = 1) -> None:
        """Bind the loop's collaborators: the recorder feeds the
        controller's windows, the heartbeat gains the async sink hook,
        the checkpointer exposes its cadence knob. The heartbeat's
        posting cadence comes from ``heartbeat.interval_of`` — the ONE
        cadence source the startup ticker uses too, so the autotuner's
        host-budget view and the ticker can never disagree.

        ``processes`` is the gang's process count: a gang's save is a
        COLLECTIVE and each process's controller tunes from its own
        phase sums, so a unilaterally stretched maybe_save gate would
        wedge the gang at the save barrier. Multi-process jobs therefore
        get the knob only through the checkpointer's GANG-AGREED mode
        (``enable_gang_cadence`` — each base-interval boundary
        allgather-mins the proposals, so a disagreeing gang saves at the
        most conservative member's cadence and the barrier stays
        matched); a checkpointer without that surface is withheld, the
        pre-agreement behavior. The prefetch/host knobs are
        per-process-local and always stay wired."""
        self._heartbeat = heartbeat
        self._hb_interval = heartbeat_mod.interval_of(heartbeat)
        if self.controller is None:
            return
        self.controller._enable_host_async = self._apply_host_async
        ck = checkpointer
        if ck is not None and int(processes) > 1:
            enable = getattr(ck, "enable_gang_cadence", None)
            if enable is not None:
                enable()
            else:
                ck = None  # no agreement surface: withhold the knob
        self.controller._checkpointer = ck
        if recorder is not None:
            recorder.on_commit = self.controller.on_step
        else:
            log.warning("autotune enabled but the step recorder is off "
                        "(TPUJOB_STEPTRACE_ENABLED=0): no phase digests "
                        "to tune from; knobs stay static")

    def _apply_host_async(self, enabled: bool) -> None:
        if enabled and self.host is None:
            # Capacity sized from the heartbeat cadence: the worker holds
            # at most a couple of intervals' worth of posts + log lines
            # before dropping (lossy telemetry, bounded memory).
            self.host = AsyncHost(capacity=max(
                16, int(4 * self._hb_interval)))
        hb = self._heartbeat
        if hb is not None:
            hb.async_sink = self.host.submit if enabled else None

    def submit_host(self, fn: Callable, *args: Any) -> bool:
        """Run host-side telemetry work (log formatting) off the step
        thread when the async host path is on; inline otherwise."""
        if self.host_async and self.host is not None:
            return self.host.submit(fn, *args)
        fn(*args)
        return True

    def wire(self) -> Optional[Dict[str, Any]]:
        """The heartbeat's ``dataPlane`` body: current knob values +
        adjustment counters. None for an inert runtime — jobs without
        spec.dataPlane post exactly the bodies they always did."""
        if not self.active:
            return None
        out: Dict[str, Any] = {
            "prefetchDepth": (self.control.depth
                              if self.control is not None else self.depth),
            "hostAsync": bool(self.host_async),
        }
        if self.host is not None:
            # Telemetry is lossy by contract; the shed amount is not
            # allowed to be invisible (a wedged status server otherwise
            # looks identical to a payload that just stopped reporting).
            out["hostDropped"] = self.host.dropped_count
        ctl = self.controller
        if ctl is not None:
            ck = ctl._checkpointer
            if ck is not None:
                mult = max(1, int(getattr(ck, "cadence_multiplier", 1)))
                every = int(getattr(ck, "save_every", 0))
                if every > 0:
                    out["checkpointIntervalSteps"] = every * mult
            out["adjustments"] = ctl.adjustments()
        return out

    def close(self) -> None:
        if self.host is not None:
            hb = self._heartbeat
            if hb is not None:
                hb.async_sink = None
            self.host.close()


def _env_int(e: Dict[str, str], var: str, default: int) -> int:
    try:
        return int(e.get(var) or default)
    except ValueError:
        log.warning("ignoring malformed %s=%r", var, e.get(var))
        return default


def from_env(prefetch: int = DEFAULT_PREFETCH_DEPTH,
             env: Optional[Dict[str, str]] = None) -> DataPlaneRuntime:
    """Build the attempt's data-plane runtime from the operator's env
    contract. ``prefetch`` is the caller's depth — for operator-launched
    payloads the ``--prefetch-depth`` arg, already defaulted from the
    injected env and resolved through the 0=auto convention by the arg
    parsers; for direct train_loop callers a verbatim concrete depth.
    Without any TPUJOB_DATAPLANE_* env the runtime is INERT: the caller's
    depth untouched (0 stays the explicit unbuffered mode), no threads,
    no controller — the pre-dataplane behavior exactly."""
    e = env if env is not None else os.environ
    active = ENV_PREFETCH_DEPTH in e or ENV_AUTOTUNE in e
    depth_request = int(prefetch)
    if not active:
        return DataPlaneRuntime(depth_request)
    if depth_request == 0:
        depth_request = _env_int(e, ENV_PREFETCH_DEPTH, 0)
    depth = resolve_prefetch_depth(depth_request)
    autotune_on = str(e.get(ENV_AUTOTUNE, "0")).lower() in ("1", "true")
    if not autotune_on:
        # spec.dataPlane present, autotune off: static depth, but the
        # background host pipeline still runs (the direct residue
        # elimination) and knob state rides the heartbeat.
        return DataPlaneRuntime(depth, pipeline=True, active=True)
    min_depth = _env_int(e, ENV_MIN_DEPTH, DEFAULT_MIN_DEPTH)
    max_depth = _env_int(e, ENV_MAX_DEPTH, DEFAULT_MAX_DEPTH)
    window = _env_int(e, ENV_WINDOW_STEPS, DEFAULT_WINDOW_STEPS)
    control = PrefetchControl(depth)
    controller = DataPlaneController(control, min_depth=min_depth,
                                     max_depth=max_depth,
                                     window_steps=window)
    return DataPlaneRuntime(control.depth, control=control,
                            controller=controller, pipeline=True,
                            active=True)
