"""Pipeline-parallel transformer LM payload (GPipe-style over a mesh axis).

``python -m tpu_operator.payload.pipeline`` — the pipeline-parallelism
member of the payload zoo. The reference operator hosts parallel training
strategies without expressing any (SURVEY.md §2 parallelism checklist: its
only strategy is process-level PS data parallelism, `replicas.go:235-260`);
here pipeline parallelism is a first-class, TPU-native payload capability
running on the process group the operator bootstraps.

Design (TPU-first, not a torch-style stage-per-process port):

- **mesh = (data, pipe)**: batch shards over ``data``; the *layer stack*
  shards over ``pipe``. Stage s holds layers [s·L/S, (s+1)·L/S).
  ``--tensor-parallel`` widens this to **(data, pipe, model)**: the tick
  schedule and hops stay hand-written (shard_map manual over data+pipe),
  while the ``model`` axis is left in *auto* mode — stage kernels are
  Megatron-sharded (stage_param_spec) and GSPMD inserts the per-matmul TP
  collectives inside each tick. ``--zero1`` shards adam moments over
  ``data`` (params/grads stay replicated): the optimizer update runs on
  1/N shards — the PP-compatible slice of FSDP's memory win, without
  gather traffic inside the tick loop.
- **SPMD pipelining inside one jit**: every stage is the *same* program on a
  different shard of the stacked stage parameters (leading dim S, sharded
  over ``pipe``). A ``lax.scan`` over M + S - 1 ticks streams M microbatches
  through; activations hop stage→stage via ``lax.ppermute`` (one ICI hop),
  exactly the collective-pipelining recipe XLA compiles well — no
  per-stage Python processes, no point-to-point sends outside the compiler.
- **Bubble** is the usual (S-1)/(M+S-1); pick microbatches >> stages.
- **Two schedules.** ``--schedule gpipe`` differentiates the forward scan
  with ``jax.grad`` — simple, but reverse-mode holds every tick's carry, so
  per-stage activation memory is O(M). ``--schedule 1f1b`` is the
  one-forward-one-backward schedule (PipeDream-flush / Megatron, public
  technique), hand-scheduled precisely because it *cannot* be expressed
  through jax.grad of a scan (round-1's open question): backward work for
  early microbatches must interleave with forward work for later ones.
  The implementation (``pipeline_1f1b_loss_and_grads``) runs a tick clock
  inside shard_map — stage s executes F(m) at tick 2m+s and B(m) at tick
  2m+2S-1-s; the two families have opposite tick parity, so each tick every
  stage runs exactly one of them under ``lax.cond`` (XLA Conditional:
  only the taken branch executes). Activations hop forward and cotangents
  hop backward on neighbor ppermutes every tick. Backward *recomputes* the
  stage forward from a stashed copy of its input via ``jax.vjp`` (stage-
  granular remat), so a stage holds at most S - s stashed inputs —
  activation memory O(S), independent of M — and gradients accumulate in
  the scan carry, never through autodiff of the schedule itself. The
  bubble fraction (S-1)/(M+S-1) is unchanged vs gpipe (both flush); the
  win is memory: M can grow to shrink the bubble without growing HBM.
- **Numerics**: house style (models.py) — bf16 matmuls on the MXU, f32
  LayerNorm/softmax/loss, f32 master params.
- Embedding and the LM head are position- and layer-local, so they run
  data-parallel *outside* the pipelined stack (replicated params); only the
  block stack pipelines.
"""

from __future__ import annotations

import argparse
import logging
import os
from typing import Optional

from tpu_operator.payload import bootstrap

log = logging.getLogger(__name__)


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--batch", type=int, default=32, help="global batch size")
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--dim", type=int, default=256)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--kv-heads", type=int, default=0,
                   help="grouped-query attention K/V heads (0 = MHA, "
                        "1 = MQA); must divide --heads")
    p.add_argument("--layers", type=int, default=8,
                   help="total decoder blocks (divisible by --pipeline)")
    p.add_argument("--pipeline", type=int, default=1,
                   help="pipeline stages (mesh pipe axis size)")
    p.add_argument("--tensor-parallel", type=int, default=1,
                   help="Megatron TP degree inside every stage: 3-axis "
                        "(data, pipe, model) mesh, stage q/k/v/mlp_up "
                        "kernels column-sharded and attn_out/mlp_down "
                        "row-sharded over ``model`` (the dense "
                        "transformer's split-qkv rule), activations still "
                        "hopping the pipe axis")
    p.add_argument("--split-qkv", choices=("auto", "on", "off"),
                   default="auto",
                   help="separate q/k/v stage projections (auto: on under "
                        "--tensor-parallel, so shards own whole heads)")
    p.add_argument("--zero1", action="store_true",
                   help="ZeRO-1: shard adam moments over the data axis "
                        "(params/grads stay replicated across DP; the "
                        "optimizer update runs on 1/N shards and GSPMD "
                        "gathers updated params) — the PP-compatible "
                        "optimizer-memory knob, ≈state/3 per rank at "
                        "adam's 2 moments")
    p.add_argument("--microbatches", type=int, default=4,
                   help="microbatches streamed through the pipeline per step")
    p.add_argument("--schedule", choices=("gpipe", "1f1b"), default="gpipe",
                   help="gpipe = scan-forward + jax.grad (activation memory "
                        "O(microbatches) per stage); 1f1b = hand-scheduled "
                        "one-forward-one-backward with manual vjp and "
                        "recompute-from-stash (activation memory O(stages), "
                        "independent of microbatches — the schedule for "
                        "M >> S runs that would not fit HBM under gpipe)")
    p.add_argument("--dtype", choices=("bf16", "f32"), default="bf16",
                   help="stage compute dtype (f32 for parity tests)")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="accumulate gradients over K sequential "
                        "microbatches inside the jit")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize each block on backward (jax.checkpoint"
                        "); with many microbatches in flight this bounds "
                        "per-stage activation memory")
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=50)
    p.add_argument("--data", default=os.environ.get("TPU_DATA_PATH", ""),
                   help="mounted .npy token file (1-D int array): "
                        "memory-mapped real-data stream (data.token_file_lm)"
                        "; empty = synthetic recurrence")
    p.add_argument("--checkpoint-dir", default="",
                   help="checkpoint/resume dir (default: $TPU_CHECKPOINT_DIR)")
    p.add_argument("--checkpoint-every", type=int, default=100)
    p.add_argument("--profile-dir",
                   default=os.environ.get("TPU_PROFILE_DIR", ""),
                   help="jax.profiler trace dir (default: $TPU_PROFILE_DIR)")
    return p.parse_args(argv)


def make_pipe_mesh(num_devices: Optional[int] = None, pipeline: int = 1,
                   devices: Optional[list] = None, num_slices: int = 1,
                   tensor_parallel: int = 1):
    """(data, pipe) mesh: DP outer, pipeline inner — consecutive stages land
    on neighboring devices so activation hops ride adjacent ICI links
    (multi-slice jobs keep all stages of one pipeline within a slice).

    ``tensor_parallel > 1`` composes PP × TP on a 3-axis
    (data, pipe, model) mesh (train.make_mesh3's layout and intra-slice
    guard): TP innermost — its psums fire per stage matmul, so they get
    the shortest ICI hops — the once-per-tick pipe hop around it, DP
    outermost / across DCN."""
    from tpu_operator.payload import train

    if tensor_parallel > 1:
        return train.make_mesh3(num_devices, seq_parallel=pipeline,
                                model_parallel=tensor_parallel,
                                devices=devices, num_slices=num_slices,
                                axis_names=("data", "pipe", "model"))
    return train.make_mesh(num_devices, model_parallel=pipeline,
                           devices=devices, axis_names=("data", "pipe"),
                           num_slices=num_slices)


def _stage_module(args, tp: int = 1):
    """One pipeline stage: layers_per_stage pre-LN decoder blocks.
    ``tp > 1`` turns on split-qkv (each model shard owns whole heads) and
    validates the TP divisibility contract; the sharding itself is purely
    a parameter-placement concern (stage_param_spec)."""
    import flax.linen as nn
    import jax.numpy as jnp

    from tpu_operator.payload import flash_attention as fa
    from tpu_operator.payload import ring_attention as ring

    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32

    def attend(q, k, v):
        if dtype == jnp.bfloat16 and fa.use_pallas_default():
            return fa.flash_attention(q, k, v, causal=True)
        return ring.reference_attention(q, k, v, causal=True)

    from tpu_operator.payload import models

    Block = (nn.remat(models.DecoderBlock) if getattr(args, "remat", False)
             else models.DecoderBlock)

    kv_heads = getattr(args, "kv_heads", 0)
    models.validate_heads_dims(args.heads, kv_heads, args.dim, tp)
    split_qkv = models.resolve_split_qkv(getattr(args, "split_qkv", "auto"),
                                         tp, log)

    class Stage(nn.Module):
        dim: int
        heads: int
        blocks: int

        @nn.compact
        def __call__(self, x):
            for i in range(self.blocks):
                x = Block(self.dim, self.heads, attend,
                          dtype=dtype, kv_heads=kv_heads,
                          split_qkv=split_qkv,
                          name=f"block{i}")(x)
            return x

    if args.layers % args.pipeline != 0:
        raise ValueError(
            f"--layers {args.layers} not divisible by --pipeline {args.pipeline}")
    return Stage(dim=args.dim, heads=args.heads,
                 blocks=args.layers // args.pipeline)


def stage_param_spec(keys, leaf, tp: int):
    """PartitionSpec for one *stacked* stage leaf ([S, ...], leading dim on
    ``pipe``). With ``tp > 1`` the intra-stage dims follow the dense
    transformer's Megatron rule (transformer.lm_tp_shardings): q/k/v and
    mlp_up kernels column-shard their output dim over ``model`` (whole
    heads / FFN columns per shard), attn_out and mlp_down row-shard their
    input dim (GSPMD inserts the psum after the matmul); the mlp_up bias
    follows its columns. LayerNorms and everything else replicate within
    the stage."""
    from jax.sharding import PartitionSpec as P

    nd = getattr(leaf, "ndim", 0)
    if nd < 1:
        return P()
    if tp > 1 and len(keys) >= 2:
        name, kind = keys[-2], keys[-1]
        if kind == "kernel" and nd == 3:
            if name in ("q", "k", "v", "qkv", "mlp_up"):
                return P("pipe", None, "model")
            if name in ("attn_out", "mlp_down"):
                return P("pipe", "model", None)
        if kind == "bias" and nd == 2 and name == "mlp_up":
            return P("pipe", "model")
    return P("pipe", *(None,) * (nd - 1))


def init_stacked_params(stage, rng, num_stages: int, sample):
    """vmap the stage init over per-stage rngs → every param leaf gains a
    leading [num_stages] dim (the dim that shards over ``pipe``)."""
    import jax

    rngs = jax.random.split(rng, num_stages)
    return jax.vmap(lambda r: stage.init(r, sample)["params"])(rngs)


def pipeline_apply(mesh, stage_apply, stacked_params, x, microbatches: int):
    """Run x [B, T, D] through the stacked stages with GPipe scheduling.

    ``stacked_params``: pytree whose leaves have leading dim S (sharded over
    mesh axis ``pipe``); ``stage_apply(params, x)`` applies one stage.
    Differentiable end-to-end: scan reverse-unrolls the schedule, ppermute
    transposes to the reverse hop, the final psum transposes to a broadcast.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    num_stages = mesh.shape["pipe"]

    def leaf_spec(leaf):
        return P("pipe", *(None,) * (leaf.ndim - 1))

    param_specs = jax.tree_util.tree_map(leaf_spec, stacked_params)
    x_spec = P("data", None, None)

    def body(params, x_local):
        # params leaves arrive [1, ...] (this device's stage); drop the dim.
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage_idx = lax.axis_index("pipe")
        b_loc, t, d = x_local.shape
        if b_loc % microbatches != 0:
            raise ValueError(
                f"per-datashard batch {b_loc} not divisible by "
                f"microbatches={microbatches}")
        mb = b_loc // microbatches
        x_mb = x_local.reshape(microbatches, mb, t, d)
        fwd_perm = [(i, i + 1) for i in range(num_stages - 1)]

        def tick(carry, step_i):
            act, outputs = carry
            # Stage 0 consumes microbatch step_i (clamped past the end —
            # those ticks only drain the pipe, results are never collected).
            inp = lax.dynamic_index_in_dim(
                x_mb, jnp.minimum(step_i, microbatches - 1), 0, keepdims=False)
            y = stage_apply(params, jnp.where(stage_idx == 0, inp, act))
            # The last stage finishes microbatch step_i - (S-1).
            out_idx = jnp.clip(step_i - (num_stages - 1), 0, microbatches - 1)
            collect = jnp.logical_and(stage_idx == num_stages - 1,
                                      step_i >= num_stages - 1)
            prev = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(collect, y, prev), out_idx, 0)
            # Hop forward one stage; stage 0's next input comes from x_mb, so
            # the zeros ppermute feeds unlisted destinations are never read.
            act = lax.ppermute(y, "pipe", fwd_perm)
            return (act, outputs), None

        init = (jnp.zeros((mb, t, d), x_local.dtype),
                jnp.zeros((microbatches, mb, t, d), x_local.dtype))
        (act, outputs), _ = lax.scan(
            tick, init, jnp.arange(microbatches + num_stages - 1))
        # Only the last stage holds real outputs; psum broadcasts them back
        # to every stage (single non-zero contributor per pipe group).
        is_last = (stage_idx == num_stages - 1).astype(outputs.dtype)
        out = lax.psum(outputs * is_last, "pipe")
        return out.reshape(b_loc, t, d)

    # Manual over (data, pipe) only: a 3-axis PP × TP mesh leaves ``model``
    # in GSPMD's hands inside the body — stage matmuls see their kernels
    # model-sharded (stage_param_spec) and the compiler inserts the TP
    # psums, while the tick schedule and ppermute hops stay hand-written.
    fn = jax.shard_map(body, mesh=mesh, in_specs=(param_specs, x_spec),
                       out_specs=x_spec, axis_names={"data", "pipe"},
                       check_vma=False)
    return fn(stacked_params, x)


def _init_params(args, mesh, rng):
    """Full param tree: replicated embed/head + pipe-stacked stage params."""
    import jax
    import jax.numpy as jnp

    stage = _stage_module(args, tp=mesh.shape.get("model", 1))
    num_stages = mesh.shape["pipe"]
    k_stage, k_tok, k_pos, k_head = jax.random.split(rng, 4)
    sample = jnp.zeros((1, args.seq_len, args.dim),
                       jnp.bfloat16 if args.dtype == "bf16" else jnp.float32)
    return stage, {
        "tok_embed": jax.random.normal(k_tok, (args.vocab, args.dim),
                                       jnp.float32) * 0.02,
        "pos_embed": jax.random.normal(k_pos, (args.seq_len, args.dim),
                                       jnp.float32) * 0.02,
        "stages": init_stacked_params(stage, k_stage, num_stages, sample),
        "ln_f": {"scale": jnp.ones((args.dim,), jnp.float32),
                 "bias": jnp.zeros((args.dim,), jnp.float32)},
        "head": jax.random.normal(k_head, (args.dim, args.vocab),
                                  jnp.float32) * 0.02,
    }


def _embed(embed_params, tokens, dtype):
    """tokens [B, T] → activations [B, T, D] (stage-0-local in 1f1b)."""
    x = embed_params["tok_embed"][tokens].astype(dtype)
    return x + embed_params["pos_embed"][:tokens.shape[1]].astype(dtype)[None]


def _head_logits(head_params, x, dtype):
    """Final LayerNorm + LM head (last-stage-local in 1f1b)."""
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    xf = (xf - mean) * (var + 1e-6) ** -0.5
    xf = xf * head_params["ln_f"]["scale"] + head_params["ln_f"]["bias"]
    return xf.astype(dtype) @ head_params["head"].astype(dtype)


def forward(args, mesh, stage, params, tokens):
    """Logits [B, T, V]: DP embed → pipelined stack → DP LayerNorm + head."""
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    x = _embed(params, tokens, dtype)
    x = pipeline_apply(mesh, lambda p, h: stage.apply({"params": p}, h),
                       params["stages"], x, args.microbatches)
    return _head_logits(params, x, dtype)


def onef1b_schedule(num_stages: int, microbatches: int):
    """The 1F1B tick table, for tests and introspection: per tick, per
    stage, ("F", m) / ("B", m) / None. Stage s: F(m) at tick 2m+s, B(m) at
    tick 2m + 2S-1-s — opposite parities, so no tick needs both."""
    s_, m_ = num_stages, microbatches
    total = 2 * (m_ + s_ - 1)
    table = []
    for t in range(total):
        row = []
        for s in range(s_):
            if (t - s) % 2 == 0 and 0 <= (t - s) // 2 < m_:
                row.append(("F", (t - s) // 2))
            elif (t - (2 * s_ - 1 - s)) % 2 == 0 \
                    and 0 <= (t - (2 * s_ - 1 - s)) // 2 < m_:
                row.append(("B", (t - (2 * s_ - 1 - s)) // 2))
            else:
                row.append(None)
        table.append(row)
    return table


def pipeline_1f1b_loss_and_grads(mesh, stage_apply, params, tokens,
                                 microbatches: int, dtype):
    """(loss, grads) for the full pipelined LM under the 1F1B schedule —
    manual differentiation, no jax.grad anywhere near the tick scan.

    Module docstring has the schedule; per tick each stage either

    - **F**: take the activation that hopped in (stage 0: embed its own
      microbatch), run the stage forward, stash the *input* (the remat
      residual), send the output up-ring; or
    - **B**: re-run the stage forward from the stashed input under
      ``jax.vjp``, seed the cotangent (last stage: d(loss_m)/dy from the
      head+loss vjp, scaled 1/M; others: the cotangent that hopped down),
      accumulate parameter gradients into the carry, send dx down-ring.

    Embed/head/ln_f params are replicated but only touched by the boundary
    stages, so their gradient contributions psum over ``pipe``; everything
    pmeans over ``data``. Stage-stack gradients come back sharded over
    ``pipe`` exactly like the parameters."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from tpu_operator.payload import train

    num_stages = mesh.shape["pipe"]

    def leaf_spec(leaf):
        return P("pipe", *(None,) * (leaf.ndim - 1))

    stage_specs = jax.tree_util.tree_map(leaf_spec, params["stages"])
    param_specs = {**{k: P() for k in params if k != "stages"},
                   "stages": stage_specs}
    tok_spec = P("data", None)
    grad_specs = (P(), param_specs)

    def body(params, tok_local):
        stage_params = jax.tree_util.tree_map(lambda p: p[0],
                                              params["stages"])
        embed_params = {"tok_embed": params["tok_embed"],
                        "pos_embed": params["pos_embed"]}
        head_params = {"ln_f": params["ln_f"], "head": params["head"]}
        s_idx = lax.axis_index("pipe")
        b_loc, t_len = tok_local.shape
        if b_loc % microbatches != 0:
            raise ValueError(
                f"per-datashard batch {b_loc} not divisible by "
                f"microbatches={microbatches}")
        mb = b_loc // microbatches
        tok_mb = tok_local.reshape(microbatches, mb, t_len)
        d = params["tok_embed"].shape[1]
        act_shape = (mb, t_len, d)
        up = [(i, i + 1) for i in range(num_stages - 1)]
        down = [(i + 1, i) for i in range(num_stages - 1)]

        def head_loss(hp, y, tgt_tokens):
            return train.next_token_nll(_head_logits(hp, y, dtype),
                                        tgt_tokens)

        zero_g = dict(
            stage=jax.tree_util.tree_map(jnp.zeros_like, stage_params),
            embed=jax.tree_util.tree_map(jnp.zeros_like, embed_params),
            head=jax.tree_util.tree_map(jnp.zeros_like, head_params),
        )

        def tick(carry, t):
            fwd_in, bwd_in, stash, g, loss_acc = carry
            is_f = jnp.logical_and((t - s_idx) % 2 == 0, t >= s_idx)
            m_f_raw = (t - s_idx) // 2
            f_valid = jnp.logical_and(is_f, m_f_raw < microbatches)
            m_f = jnp.clip(m_f_raw, 0, microbatches - 1)
            b_off = 2 * num_stages - 1 - s_idx
            m_b_raw = (t - b_off) // 2
            b_valid = jnp.logical_and(t >= b_off, m_b_raw < microbatches)
            m_b = jnp.clip(m_b_raw, 0, microbatches - 1)

            def f_branch(_):
                x_own = _embed(embed_params,
                               lax.dynamic_index_in_dim(tok_mb, m_f, 0,
                                                        keepdims=False),
                               dtype)
                x_in = jnp.where(s_idx == 0, x_own, fwd_in)
                y = stage_apply(stage_params, x_in)
                stash_upd = lax.dynamic_update_index_in_dim(
                    stash, x_in, m_f % num_stages, 0)
                new_stash = jnp.where(f_valid, stash_upd, stash)
                return (y, jnp.zeros(act_shape, dtype), new_stash,
                        zero_g, jnp.float32(0.0))

            def b_branch(_):
                x_saved = lax.dynamic_index_in_dim(stash, m_b % num_stages,
                                                   0, keepdims=False)
                y_b, stage_vjp = jax.vjp(stage_apply, stage_params, x_saved)
                tgt = lax.dynamic_index_in_dim(tok_mb, m_b, 0,
                                               keepdims=False)

                def last(_):
                    loss_m, head_vjp = jax.vjp(
                        lambda hp, y: head_loss(hp, y, tgt), head_params,
                        y_b)
                    g_head, dy = head_vjp(jnp.float32(1.0 / microbatches))
                    return loss_m, g_head, dy.astype(dtype)

                def other(_):
                    return (jnp.float32(0.0),
                            jax.tree_util.tree_map(jnp.zeros_like,
                                                   head_params),
                            bwd_in)

                loss_m, g_head_d, dy = lax.cond(s_idx == num_stages - 1,
                                                last, other, None)
                g_stage_d, dx = stage_vjp(dy)

                def s0(_):
                    _x, embed_vjp = jax.vjp(
                        lambda ep: _embed(ep, tgt, dtype), embed_params)
                    (g_embed_d,) = embed_vjp(dx)
                    return g_embed_d

                g_embed_d = lax.cond(
                    s_idx == 0, s0,
                    lambda _: jax.tree_util.tree_map(jnp.zeros_like,
                                                     embed_params),
                    None)
                mask = b_valid.astype(jnp.float32)
                g_d = dict(stage=g_stage_d, embed=g_embed_d, head=g_head_d)
                g_d = jax.tree_util.tree_map(lambda x: x * mask, g_d)
                return (jnp.zeros(act_shape, dtype), dx, stash, g_d,
                        loss_m * mask / microbatches)

            y_send, dx_send, stash, g_d, loss_d = lax.cond(
                is_f, f_branch, b_branch, None)
            g = jax.tree_util.tree_map(jnp.add, g, g_d)
            fwd_in = lax.ppermute(y_send, "pipe", up)
            bwd_in = lax.ppermute(dx_send, "pipe", down)
            return (fwd_in, bwd_in, stash, g, loss_acc + loss_d), None

        init = (jnp.zeros(act_shape, dtype), jnp.zeros(act_shape, dtype),
                jnp.zeros((num_stages, *act_shape), dtype), zero_g,
                jnp.float32(0.0))
        total_ticks = 2 * (microbatches + num_stages - 1)
        (_f, _b, _stash, g, loss), _ = lax.scan(
            tick, init, jnp.arange(total_ticks))

        # Reduce: loss lives on the last stage only; replicated-param grads
        # live on their boundary stages only.
        loss = lax.pmean(lax.psum(loss, "pipe"), "data")
        g_stage = jax.tree_util.tree_map(
            lambda x: lax.pmean(x, "data")[None], g["stage"])
        g_rep = jax.tree_util.tree_map(
            lambda x: lax.pmean(lax.psum(x, "pipe"), "data"),
            {"embed": g["embed"], "head": g["head"]})
        grads = {
            "tok_embed": g_rep["embed"]["tok_embed"],
            "pos_embed": g_rep["embed"]["pos_embed"],
            "stages": g_stage,
            "ln_f": g_rep["head"]["ln_f"],
            "head": g_rep["head"]["head"],
        }
        return loss, grads

    # Manual over (data, pipe); ``model`` (PP × TP meshes) stays auto so
    # GSPMD shards the stage matmuls — see pipeline_apply.
    fn = jax.shard_map(body, mesh=mesh, in_specs=(param_specs, tok_spec),
                       out_specs=grad_specs, axis_names={"data", "pipe"},
                       check_vma=False)
    return fn(params, tokens)


def state_shardings(mesh, state, zero1: bool = False):
    """Shardings for the pipeline state: every leaf under a ``stages`` path
    (params and the params-shaped adam moments) shards its leading stage
    dim over ``pipe`` — plus, on a PP × TP mesh, its intra-stage dims over
    ``model`` (stage_param_spec); everything else replicates.

    ``zero1`` additionally shards *optimizer-state* leaves (only) over the
    ``data`` axis on their first still-unsharded divisible dim — params and
    gradients stay replicated across DP (the 1F1B body's pmean contract is
    untouched); the adam update then runs on 1/N of each moment and GSPMD
    gathers the updated params, which is exactly ZeRO-1."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_operator.payload import train

    tp = mesh.shape.get("model", 1)
    data = mesh.shape["data"]

    def param_rule(keys, leaf):
        if "stages" in keys and getattr(leaf, "ndim", 0) >= 1:
            return stage_param_spec(keys, leaf, tp)
        return P()

    def opt_rule(keys, leaf):
        spec = param_rule(keys, leaf)
        shape = getattr(leaf, "shape", ())
        if not zero1 or getattr(leaf, "size", 0) < 1024:
            return spec
        parts = list(spec) + [None] * (len(shape) - len(spec))
        for i, dim in enumerate(shape):
            if parts[i] is None and dim % data == 0:
                parts[i] = "data"
                return P(*parts)
        return spec

    def build(tree, rule):
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: NamedSharding(
                mesh,
                rule(tuple(getattr(p, "key", str(p)) for p in path), leaf)),
            tree)

    return train.TrainState(
        step=NamedSharding(mesh, P()),
        params=build(state.params, param_rule),
        batch_stats=build(state.batch_stats, param_rule),
        opt_state=build(state.opt_state, opt_rule),
    )


def make_pipe_train_step(args, stage, mesh, state, tx, shardings=None):
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpu_operator.payload import train

    shardings = shardings or state_shardings(
        mesh, state, zero1=getattr(args, "zero1", False))

    if getattr(args, "schedule", "gpipe") == "1f1b":
        if getattr(args, "grad_accum", 1) != 1:
            raise ValueError(
                "--schedule 1f1b already streams microbatches; use "
                "--microbatches instead of --grad-accum")
        dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32

        def grads_and_metrics(params, tokens):
            loss, grads = pipeline_1f1b_loss_and_grads(
                mesh, lambda p, h: stage.apply({"params": p}, h),
                params, tokens, args.microbatches, dtype)
            return grads, {"loss": loss}

        return train.make_grads_train_step(
            grads_and_metrics, tx, mesh, state, shardings,
            batch_spec=P("data", None))

    def loss_fn(params, tokens):
        loss = train.next_token_nll(
            forward(args, mesh, stage, params, tokens), tokens)
        return loss, {"loss": loss}

    return train.make_loss_train_step(
        loss_fn, tx, mesh, state, shardings,
        batch_spec=P("data", None),
        grad_accum=getattr(args, "grad_accum", 1))


def build(args, mesh=None, num_slices: int = 1):
    """(mesh, stage, state, train_step, batches) for the given config."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpu_operator.payload import data as data_mod
    from tpu_operator.payload import train

    mesh = mesh or make_pipe_mesh(
        pipeline=args.pipeline, num_slices=num_slices,
        tensor_parallel=getattr(args, "tensor_parallel", 1))
    data_shards = mesh.shape["data"]
    grad_accum = getattr(args, "grad_accum", 1)
    if args.batch % (data_shards * args.microbatches * grad_accum) != 0:
        # grad_accum divides the batch before the loss_fn sees it, so it
        # belongs in the divisibility check: failing here beats a trace-time
        # shape error inside pipeline_apply.
        raise ValueError(
            f"--batch {args.batch} must divide by data shards × microbatches "
            f"× grad_accum ({data_shards} × {args.microbatches} × {grad_accum})")
    stage, params = _init_params(args, mesh, jax.random.key(args.seed))
    tx = optax.adam(args.lr)
    state = train.TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats={},
        opt_state=tx.init(params),
    )
    shardings = state_shardings(mesh, state,
                                zero1=getattr(args, "zero1", False))
    state = train.place_state(mesh, state, shardings)
    step = make_pipe_train_step(args, stage, mesh, state, tx, shardings)
    batches = data_mod.lm_batches(args)
    return mesh, stage, state, step, batches


def run(info: bootstrap.ProcessInfo, args=None) -> dict:
    from tpu_operator.payload import checkpoint, train

    args = args or parse_args([])
    mesh, _stage, state, step, batches = build(
        args, num_slices=info.num_slices)
    log.info("mesh: %s over %d devices; %d layers / %d stages, %d microbatches",
             dict(zip(mesh.axis_names, mesh.devices.shape)),
             mesh.devices.size, args.layers, args.pipeline, args.microbatches)
    ckpt = checkpoint.from_env_or_args(args.checkpoint_dir,
                                       save_every=args.checkpoint_every)
    if ckpt is not None and ckpt.latest_step() is not None:
        log.info("attempt %d: resuming from %s (latest step: %d)",
                 info.attempt, ckpt.directory, ckpt.latest_step())
    try:
        state, metrics = train.train_loop(
            mesh, step, state, batches, args.steps,
            log_every=args.log_every,
            log_fn=lambda i, m: log.info("step %d loss %.4f", i, m["loss"]),
            checkpointer=ckpt,
            profile_dir=args.profile_dir,
        )
    finally:
        if ckpt is not None:
            ckpt.close()
    log.info("final: loss %.4f", metrics.get("loss", float("nan")))
    return metrics


def main() -> None:
    args = parse_args()
    bootstrap.main_wrapper(lambda info: run(info, args))


if __name__ == "__main__":
    main()
