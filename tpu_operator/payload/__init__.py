"""The data plane: what runs inside the ``tpu`` container.

The reference keeps all training math in user Docker images
(stefanofioravanzo/mxnet-linear-dist, mxnet-cifar10-dist — README.md:66-96,
126-180); the repo itself ships none. This package is the TPU-native
equivalent of those images' contents, shipped in-repo so the BASELINE
configs are reproducible end-to-end:

- ``bootstrap``     jax.distributed process-group formation from the env the
                    operator injects (the consumer of replicas.py's contract)
- ``data``          deterministic on-device data pipeline (synthetic CIFAR-10)
- ``models``        Flax model zoo (CIFAR ResNet family, linear)
- ``train``         the generic sharded training loop (DP × TP over a Mesh)
- ``linear``        distributed linear regression (BASELINE config 2)
- ``cifar``         data-parallel CIFAR-10 ResNet (BASELINE config 3)

Everything here is jit-first: static shapes, no data-dependent Python control
flow under jit, bf16 matmul/conv with fp32 accumulation — the MXU-friendly
defaults — and sharding expressed once via NamedSharding over a Mesh, with
XLA inserting the ICI collectives.
"""
