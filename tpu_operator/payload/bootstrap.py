"""Process-group bootstrap inside the ``tpu`` container.

This is the consumer of the operator's env contract
(trainer/replicas.py build_replica_env — the TPU-native replacement for the
MXNet side of the reference's DMLC_* rendezvous, README.md:103-121):
``jax.distributed.initialize`` pointed at the coordinator Service the
operator created, with retry while the coordinator's DNS name warms up
(SURVEY.md §7 hard part (c): the reference leaned on MXNet client retry for
exactly this window).

Also owns the exit-code side of the contract (training.go:172-208 /
README.md:107-121): ``run_payload`` maps clean completion → 0, application
errors → 1 (permanent), and SIGTERM (preemption/eviction) → 143 (retryable),
so the operator's whole-group restart machinery sees exactly the signals it
classifies.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import socket
import sys
import threading
import time
from typing import Callable, Optional

from tpu_operator.payload import startup as startup_mod

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ProcessInfo:
    """This process's place in the job (parsed injected env)."""

    coordinator_address: str  # host:port
    process_id: int
    num_processes: int
    worker_id: int
    worker_hostnames: tuple
    job_name: str = ""
    replica_type: str = "worker"
    attempt: int = 0
    num_slices: int = 1
    slice_id: int = 0
    # Operator-stamped identity, carried so payload logs/artifacts can be
    # correlated with the exact child-resource generation that produced
    # them (child names embed the runtime id; the replica index is this
    # process's stable slot, unlike the pod's random-suffixed name).
    runtime_id: str = ""
    replica_index: int = 0


def process_info_from_env(env: Optional[dict] = None) -> ProcessInfo:
    e = env if env is not None else os.environ
    return ProcessInfo(
        coordinator_address=e.get("JAX_COORDINATOR_ADDRESS", ""),
        process_id=int(e.get("JAX_PROCESS_ID", "0")),
        num_processes=int(e.get("JAX_NUM_PROCESSES", "1")),
        worker_id=int(e.get("TPU_WORKER_ID", e.get("JAX_PROCESS_ID", "0"))),
        worker_hostnames=tuple(
            h for h in e.get("TPU_WORKER_HOSTNAMES", "").split(",") if h
        ),
        job_name=e.get("TPUJOB_NAME", ""),
        replica_type=e.get("TPUJOB_REPLICA_TYPE", "worker"),
        attempt=int(e.get("TPUJOB_ATTEMPT", "0")),
        num_slices=int(e.get("MEGASCALE_NUM_SLICES", "1")),
        slice_id=int(e.get("MEGASCALE_SLICE_ID", "0")),
        runtime_id=e.get("TPUJOB_RUNTIME_ID", ""),
        replica_index=int(e.get("TPUJOB_REPLICA_INDEX", "0")),
    )


# First DNS poll delay; doubles up to the ``interval`` cap.
INITIAL_DNS_POLL = 0.05


def wait_for_coordinator(address: str, timeout: float = 300.0,
                         interval: float = 2.0,
                         sleep: Callable[[float], None] = time.sleep,
                         clock: Callable[[], float] = time.monotonic) -> None:
    """Block until the coordinator's DNS name resolves (the Service exists
    before any pod by construction — trainer/training.py creates services
    first — but cluster DNS propagation still takes seconds).

    Polls tightly at first (50 ms) with capped exponential backoff up to
    ``interval``: on a warm restart the Service — and usually its DNS
    record — already exists, so the common case costs milliseconds instead
    of a full coarse poll period, while a genuinely cold cluster degrades
    to the old 2 s cadence. ``sleep``/``clock`` are injectable for tests.
    """
    host = address.rsplit(":", 1)[0]
    deadline = clock() + timeout
    delay = min(INITIAL_DNS_POLL, interval) if interval > 0 else 0.0
    while True:
        try:
            socket.getaddrinfo(host, None)
            return
        except socket.gaierror:
            now = clock()
            if now >= deadline:
                raise TimeoutError(
                    f"coordinator DNS {host!r} did not resolve in {timeout:.0f}s"
                )
            # The tight early polls would spam INFO; log them at debug and
            # only surface the wait once it is actually taking a while.
            if delay >= interval:
                log.info("waiting for coordinator DNS %s ...", host)
            else:
                log.debug("waiting for coordinator DNS %s ...", host)
            sleep(min(delay, max(0.0, deadline - now)))
            delay = min(delay * 2 if delay > 0 else interval, interval)


def initialize(info: Optional[ProcessInfo] = None) -> ProcessInfo:
    """Form the process group. Single-process jobs skip jax.distributed
    entirely (a v4-8 single-worker job needs no coordinator —
    BASELINE config 2 degenerates to plain jax). The DNS wait + rendezvous
    time is recorded as the RENDEZVOUS stage of the startup breakdown.

    The remote warm-start store prefetch (payload/warmstore.py) starts
    FIRST and joins LAST: the compilation-cache + latest-checkpoint
    download runs concurrently with the DNS/rendezvous wait that is
    already on the critical path, so on a fresh node the warm bytes are
    usually in place the moment the group forms — only the tail that
    outlives rendezvous is paid (recorded as the PREFETCH stage)."""
    from tpu_operator.payload import warmstore

    info = info or process_info_from_env()
    prefetching = warmstore.start_prefetch()
    if info.num_processes <= 1:
        log.info("single-process job; skipping jax.distributed")
        startup_mod.record_rendezvous(0.0)
        if prefetching:
            warmstore.finish_prefetch()
        return info
    import jax

    t0 = time.perf_counter()
    wait_for_coordinator(info.coordinator_address)
    jax.distributed.initialize(
        coordinator_address=info.coordinator_address,
        num_processes=info.num_processes,
        process_id=info.process_id,
    )
    startup_mod.record_rendezvous(time.perf_counter() - t0)
    if prefetching:
        warmstore.finish_prefetch()
    log.info("process %d/%d joined group at %s (%d devices visible)",
             info.process_id, info.num_processes, info.coordinator_address,
             jax.device_count())
    return info


def enable_compilation_cache(env: Optional[dict] = None) -> str:
    """Point JAX's persistent compilation cache at the operator-mounted
    volume (JAX_COMPILATION_CACHE_DIR / TPUJOB_CACHE_*, injected by
    trainer/replicas.py when ``spec.compilationCache`` is enabled) and
    force min-entry-size/min-compile-time to 0 so every executable — not
    just the slow ones JAX's defaults admit — is reusable on the next
    attempt. Returns the cache dir, or "" when caching is off or the dir
    is unusable.

    Strictly best-effort: a corrupt, read-only, or otherwise unwritable
    cache dir logs a warning and the attempt proceeds with a cold compile
    — a broken cache volume must degrade warm restarts, never fail them.
    """
    e = env if env is not None else os.environ
    # TPUJOB_CACHE_PATH is the operator's own mirror of the mount point:
    # honoring it as a fallback means a template that strips or overrides
    # the ambient JAX var still gets the operator-wired cache (the mirror
    # was injected-but-unread dead weight before the env-contract
    # analyzer flagged it).
    path = e.get("JAX_COMPILATION_CACHE_DIR", "") \
        or e.get("TPUJOB_CACHE_PATH", "")
    if not path:
        return ""
    if e.get("TPUJOB_CACHE_ENABLED", "1").lower() in ("0", "false"):
        return ""
    try:
        os.makedirs(path, exist_ok=True)
        probe = os.path.join(path, f".tpujob-cache-probe-{os.getpid()}")
        with open(probe, "w", encoding="utf-8") as f:
            f.write("ok")
        os.remove(probe)
    except OSError as err:
        log.warning("compilation cache dir %s unusable (%s); proceeding "
                    "with cold compilation", path, err)
        return ""
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # Defaults skip small/fast compiles; a warm restart wants every
        # executable back, so persist all of them.
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception as err:  # noqa: BLE001 — config drift must not kill us
        log.warning("enabling the persistent compilation cache failed (%s); "
                    "proceeding with cold compilation", err)
        return ""
    startup_mod.set_cache_dir(path)
    log.info("persistent compilation cache at %s (medium %s)",
             path, e.get("TPUJOB_CACHE_MEDIUM", "unset"))
    return path


EXIT_RETRYABLE = 143  # 128 + SIGTERM: the retryable band (training.go:172-208)

# Operator-initiated PLANNED exit: a cooperative-drain directive (rode a
# heartbeat ACK) asked this gang to checkpoint and restart on purpose —
# live resize, graceful preemption, node maintenance. In the retryable
# band (so a pre-upgrade operator still restarts the gang) but distinct
# from 143: the classifier bills it to the preemption-factor budget and
# never to the crash-loop backoff streak. Deliberately NOT 128+signal of
# anything a kubelet sends — no real signal can alias it.
EXIT_PLANNED = 160

# SIGTERM inside the step loop requests a cooperative drain: train_loop
# notices at the next step boundary, saves a checkpoint of the *current*
# step (single-process jobs), and exits 143 — so a preempted attempt loses
# zero completed steps instead of rolling back to the last interval save.
# Outside the step loop (bootstrap, data loading, non-loop payloads) — or on
# a second SIGTERM — the process exits immediately, as before; kubelet's
# SIGKILL at the grace deadline is the final backstop.
#
# A drain DIRECTIVE (the operator's cooperative-drain protocol) arms the
# same latch plus _planned: the gang agrees on a boundary step exactly
# like the SIGTERM path, but exits EXIT_PLANNED so the restart is billed
# as planned, not preempted.
_drain = threading.Event()
_planned = threading.Event()
_in_step_loop = threading.Event()


def request_drain() -> None:
    _drain.set()


def request_planned_drain() -> None:
    """Arm the drain latch for an operator-directed (planned) restart:
    drain at the next step boundary, gang-save, exit EXIT_PLANNED."""
    _planned.set()
    _drain.set()


def draining() -> bool:
    return _drain.is_set()


def planned_drain() -> bool:
    return _planned.is_set()


def drain_exit_code() -> int:
    """The exit code the current drain latch maps to: EXIT_PLANNED for a
    directive-driven drain, EXIT_RETRYABLE for a signal-driven one."""
    return EXIT_PLANNED if _planned.is_set() else EXIT_RETRYABLE


def reset_drain() -> None:
    """Test hook: clear the module-level drain latches."""
    _drain.clear()
    _planned.clear()


def enter_step_loop() -> None:
    """train_loop marks itself drainable; SIGTERM then defers to the next
    step boundary instead of killing the process mid-step."""
    _in_step_loop.set()


def exit_step_loop() -> None:
    _in_step_loop.clear()


def run_payload(fn: Callable[[ProcessInfo], None]) -> int:
    """Run a training payload under the exit-code contract. SIGTERM (pod
    preemption) exits 143 → retryable → whole-group restart; while the step
    loop runs, the exit defers one step boundary so the current step gets
    checkpointed (a second SIGTERM exits immediately); any other exception
    exits 1 → permanent failure."""

    def _sigterm(_signum, _frame):
        if _drain.is_set() or not _in_step_loop.is_set():
            raise SystemExit(EXIT_RETRYABLE)
        log.info("SIGTERM: draining — checkpoint at next step boundary "
                 "(send again to exit immediately)")
        request_drain()

    signal.signal(signal.SIGTERM, _sigterm)
    try:
        info = initialize()
        enable_compilation_cache()
        # jax.distributed.initialize installs its own C++ SIGTERM handler
        # (the preemption notifier, preemption_notifier.cc) which *replaces*
        # the drain handler above. Left in place, SIGTERM would never set
        # the drain latch; instead orbax's out-of-band preemption save path
        # triggers and its finalize barrier deadlocks against the still-
        # looping peers. Re-install ours so the operator's drain contract —
        # agree on a boundary step, group-save, exit 143 — owns preemption.
        signal.signal(signal.SIGTERM, _sigterm)
        fn(info)
        code = 0
    except SystemExit as e:
        code = int(e.code or 0)
    except Exception:  # noqa: BLE001 — the contract: app error = permanent
        log.exception("payload failed")
        return 1
    if code in (0, EXIT_RETRYABLE, EXIT_PLANNED):
        # Ship this attempt's compiled executables to the warm-start
        # store on the clean/drain exit paths: jobs with a store but no
        # checkpointing have no write-behind uploader, and even
        # checkpointed attempts may compile then drain before their
        # first save. Best-effort set-difference sync, process 0 only.
        from tpu_operator.payload import warmstore

        warmstore.upload_cache_once()
    return code


def main_wrapper(fn: Callable[[ProcessInfo], None]) -> None:
    logging.basicConfig(level=logging.INFO,
                       format="%(asctime)s %(levelname)s %(message)s")
    sys.exit(run_payload(fn))
