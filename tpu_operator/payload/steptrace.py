"""Data-plane flight recorder: per-step phase timing for the train loop.

The control plane and the restart path are instrumented end to end (PR 1
metrics, PR 5 startup breakdown, PR 8 goodput), but steady-state step time
— where a training job spends almost all of its life — was a single
averaged ``stepTimeSeconds`` on the heartbeat: no split between input
wait, device compute, host work, and checkpoint stalls, and no way to see
that ONE replica in a gang is pacing the collective for everyone. This
module is the payload half of that gap:

- :class:`StepRecorder` times each step's phases into a fixed-size ring
  buffer. The step path pays **timestamps only** (one ``clock()`` call and
  one dict store per phase boundary, one lock-guarded append per step);
  percentile aggregation runs off-loop, on the heartbeat cadence.
- :meth:`StepRecorder.summary` drains the since-last-summary window into
  the wire-format digest the heartbeat carries (``stepTiming``): per-phase
  p50/p95/max plus whole-step percentiles. Windowed on purpose — each
  digest describes a disjoint span of steps, so the controller can feed
  histograms without double counting and the straggler detector sees
  time-local cadence, not a lifetime average.
- On a retryable payload exit the ring buffer dumps as a JSON artifact
  next to the checkpoint dir (:func:`postmortem_dump`) — and ships through
  the write-behind store worker when ``spec.store`` is wired — so a
  postmortem of a preempted or stalled attempt sees the last N steps'
  phase timings, not just the final heartbeat.

Phase definitions (one step, in loop order):

- ``DATA`` — input/data wait: time blocked in ``next()`` on the
  ``device_prefetch`` stream. Near zero while the prefetcher keeps up;
  growth here means host batch generation or H2D transfer fell behind.
- ``DISPATCH`` — the jitted step call itself: async enqueue of the device
  program. Growth means trace/compile on the dispatch path or the runtime
  throttling a too-deep queue.
- ``COMPUTE`` — device execution: the host's residual wait, bounded by
  ``block_until_ready`` fenced ONE STEP DEEP (after dispatching step i
  the loop blocks on step i-1's metrics) so dispatch pipelining is
  preserved — a same-step fence serialized host dispatch against device
  compute and cost measurable throughput. The dominant phase on a
  healthy, device-bound step; shrinkage here with wall time flat means
  the host became the bottleneck.
- ``CHECKPOINT`` — the ``maybe_save`` boundary: normally the async
  handoff (~0), spiking when a save blocks on the previous one.
- ``HOST`` — everything else host-side: logging, metrics fetch, the
  heartbeat post.

Stdlib-only on purpose: the controller (statusserver sanitization, schema)
imports the phase names from here, and this module must not drag jax into
the control plane — same discipline as ``payload/startup.py``.
"""

from __future__ import annotations

import collections
import json
import logging
import math
import os
import time
from typing import Any, Callable, Dict, List, Optional
from tpu_operator.util import lockdep

log = logging.getLogger(__name__)

# Step phases, in loop order.
DATA = "DATA"
DISPATCH = "DISPATCH"
COMPUTE = "COMPUTE"
CHECKPOINT = "CHECKPOINT"
HOST = "HOST"

PHASES = (DATA, DISPATCH, COMPUTE, CHECKPOINT, HOST)

# Wire-format field name per phase: the keys of ``stepTiming.phases`` on
# the heartbeat, in ``status.stepTiming``, and in the postmortem artifact.
PHASE_FIELDS = {
    DATA: "dataWait",
    DISPATCH: "dispatch",
    COMPUTE: "compute",
    CHECKPOINT: "checkpoint",
    HOST: "host",
}

# Per-phase digest stats carried for each phase field.
DIGEST_KEYS = ("p50Seconds", "p95Seconds", "maxSeconds")

# Ring-buffer capacity default (last N steps retained for the postmortem).
DEFAULT_BUFFER_STEPS = 512

# Operator env contract (trainer/replicas.py injects when spec.stepTrace
# is present; absent env keeps the recorder on at defaults — it costs
# timestamps only).
ENV_ENABLED = "TPUJOB_STEPTRACE_ENABLED"
ENV_BUFFER = "TPUJOB_STEPTRACE_BUFFER"


def _pct(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile of an already-sorted list."""
    if not sorted_vals:
        return 0.0
    rank = max(0, min(len(sorted_vals) - 1,
                      math.ceil(q * len(sorted_vals)) - 1))
    return sorted_vals[rank]


def digest(values: List[float]) -> Dict[str, float]:
    """{p50Seconds, p95Seconds, maxSeconds} of one phase's samples."""
    s = sorted(values)
    return {
        "p50Seconds": round(_pct(s, 0.50), 6),
        "p95Seconds": round(_pct(s, 0.95), 6),
        "maxSeconds": round(s[-1], 6) if s else 0.0,
    }


class StepRecorder:
    """Per-step phase timing into a bounded ring buffer.

    Step-loop usage (one thread — the train loop — drives begin/lap/
    commit; ``summary``/``snapshot``/``dump`` may be called from any
    thread, hence the lock on the shared buffers)::

        rec.begin(i)
        batch = next(stream);            rec.lap(steptrace.DATA)
        state, m = step(state, batch);   rec.lap(steptrace.DISPATCH)
        block_until_ready(prev_m);       rec.lap(steptrace.COMPUTE)
        ckpt.maybe_save(i + 1, state);   rec.lap(steptrace.CHECKPOINT)
        log/heartbeat;                   rec.lap(steptrace.HOST)
        rec.commit();                    prev_m = m

    ``lap`` attributes the time since the previous boundary to the named
    phase (re-entering a phase accumulates). ``clock`` is injectable for
    deterministic tests.
    """

    def __init__(self, capacity: int = DEFAULT_BUFFER_STEPS,
                 clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self.capacity = max(8, int(capacity))
        self._lock = lockdep.lock("StepRecorder._lock")
        # Last-N completed step records: {"step": i, "seconds": total,
        # DATA: dt, ...} with raw phase-name keys.
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)  # guarded-by: _lock
        # Since-last-summary window: phase -> samples, whole-step totals,
        # and per-step LOCAL time (total minus the COMPUTE wait) — the
        # straggler detector's signal. Drained and reset by summary();
        # BOUNDED at the ring capacity because summary() only runs when a
        # heartbeat is wired — a standalone payload (no TPUJOB_STATUS_URL)
        # with the recorder default-ON must not accumulate O(steps) floats
        # forever. A window that hit the bound simply digests the newest
        # `capacity` steps, same retention story as the ring itself.
        self._window: Dict[str, collections.deque] = {}  # guarded-by: _lock
        self._window_steps: collections.deque = collections.deque(
            maxlen=self.capacity)  # guarded-by: _lock
        self._window_local: collections.deque = collections.deque(
            maxlen=self.capacity)  # guarded-by: _lock
        # In-flight step state: step-loop thread only, never shared.
        self._cur: Optional[Dict[str, Any]] = None
        self._t0 = 0.0
        self._tlast = 0.0
        self.steps_recorded = 0
        # Optional per-step observer (the autotune controller): called on
        # the step-loop thread with each committed record, AFTER the ring
        # bookkeeping and outside the lock. Must be cheap (the controller
        # does float adds, evaluating once per window) and must not raise
        # — commit shields the loop regardless.
        self.on_commit: Optional[Callable[[Dict[str, Any]], None]] = None

    # -- step path (timestamps only) -------------------------------------------

    def begin(self, step: int) -> None:
        self._cur = {"step": int(step)}
        self._t0 = self._tlast = self._clock()

    def lap(self, phase: str) -> None:
        """Attribute time since the previous boundary to ``phase``."""
        cur = self._cur
        if cur is None:
            return
        now = self._clock()
        cur[phase] = cur.get(phase, 0.0) + (now - self._tlast)
        self._tlast = now

    def commit(self) -> None:
        cur = self._cur
        if cur is None:
            return
        self._cur = None
        cur["seconds"] = self._clock() - self._t0
        with self._lock:
            self._ring.append(cur)
            self._window_steps.append(cur["seconds"])
            # Local time = everything the COMPUTE fence did NOT cover. In
            # a synchronous gang every member's step (and compute wait)
            # converges on the slowest member — the collective equalizes
            # them — so whole-step cadence can never single out a
            # straggler; the local share is the only per-process signal
            # that stays per-process.
            self._window_local.append(
                max(0.0, cur["seconds"] - cur.get(COMPUTE, 0.0)))
            for phase in PHASES:
                if phase in cur:
                    if phase not in self._window:
                        self._window[phase] = collections.deque(
                            maxlen=self.capacity)
                    self._window[phase].append(cur[phase])
        self.steps_recorded += 1
        observer = self.on_commit
        if observer is not None:
            try:
                observer(cur)
            except Exception:  # noqa: BLE001 — observers never kill the loop
                log.exception("steptrace commit observer failed; detaching")
                self.on_commit = None

    def abandon(self) -> None:
        """Drop the in-flight step (loop exiting mid-step): a partial
        record would skew every digest low."""
        self._cur = None

    # -- off-loop aggregation --------------------------------------------------

    def summary(self) -> Optional[Dict[str, Any]]:
        """Drain the since-last-summary window into the heartbeat's
        ``stepTiming`` wire dict, or None when no step completed since the
        previous summary. Each summary describes a disjoint step span, so
        downstream histogram observation never double-counts."""
        with self._lock:
            steps = list(self._window_steps)
            local = list(self._window_local)
            window = {phase: list(v) for phase, v in self._window.items()}
            if not steps:
                return None
            self._window_steps.clear()
            self._window_local.clear()
            self._window = {}
        whole = digest(steps)
        out: Dict[str, Any] = {
            "steps": len(steps),
            "stepP50Seconds": whole["p50Seconds"],
            "stepP95Seconds": whole["p95Seconds"],
            "stepMaxSeconds": whole["maxSeconds"],
            # The straggler detector's signal: p95 of per-step LOCAL time
            # (step minus the compute wait) — see commit().
            "stepLocalP95Seconds": round(_pct(sorted(local), 0.95), 6),
        }
        phases = {
            PHASE_FIELDS[phase]: digest(values)
            for phase, values in window.items()
        }
        if phases:
            out["phases"] = phases
        return out

    def snapshot(self) -> List[Dict[str, Any]]:
        """The ring buffer's records, oldest first, with wire-format phase
        names — the postmortem artifact body."""
        with self._lock:
            ring = list(self._ring)
        out = []
        for rec in ring:
            row: Dict[str, Any] = {"step": rec["step"],
                                   "stepSeconds": round(rec["seconds"], 6)}
            for phase in PHASES:
                if phase in rec:
                    row[PHASE_FIELDS[phase]] = round(rec[phase], 6)
            out.append(row)
        return out

    def dump(self, path: str, meta: Optional[Dict[str, Any]] = None) -> str:
        """Write the ring buffer as a JSON artifact (atomic tmp+rename).
        Raises OSError on an unwritable destination — callers on exit
        paths should use :func:`postmortem_dump`, which never raises."""
        body: Dict[str, Any] = {
            "kind": "tpujob-steptrace",
            "capacity": self.capacity,
            "stepsRecorded": self.steps_recorded,
            **(meta or {}),
            "steps": self.snapshot(),
        }
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(body, f)
        os.replace(tmp, path)
        return path


def from_env(env: Optional[Dict[str, str]] = None) -> Optional[StepRecorder]:
    """Recorder from the operator's env contract. Default ON (absent env):
    the recorder costs timestamps only, and a black-box data plane costs
    more. ``TPUJOB_STEPTRACE_ENABLED=0`` opts out; TPUJOB_STEPTRACE_BUFFER
    sizes the ring."""
    e = env if env is not None else os.environ
    if str(e.get(ENV_ENABLED, "1")).lower() in ("0", "false"):
        return None
    try:
        capacity = int(e.get(ENV_BUFFER) or DEFAULT_BUFFER_STEPS)
    except ValueError:
        log.warning("ignoring malformed %s=%r", ENV_BUFFER, e.get(ENV_BUFFER))
        capacity = DEFAULT_BUFFER_STEPS
    return StepRecorder(capacity=capacity)


def postmortem_path(checkpoint_dir: str, attempt: int,
                    process_id: int) -> str:
    """The artifact path for one attempt's trace: a sibling of the
    checkpoint dir (same volume — it survives the pod exactly as long as
    the checkpoints do), named by attempt + process so successive attempts
    and gang members never clobber each other. When the checkpoint dir IS
    a top-level mount point (``checkpointDir: /ckpt`` with the PVC at
    /ckpt), its parent is the container root fs — outside the volume —
    so the artifact goes INSIDE the checkpoint dir instead (a
    non-numeric file there is invisible to both the orbax step walk and
    the quarantine scan)."""
    name = f"steptrace-attempt{int(attempt)}-p{int(process_id)}.json"
    ckpt = os.path.abspath(checkpoint_dir.rstrip("/") or "/")
    base = os.path.dirname(ckpt)
    if base == os.path.dirname(base):  # parent of a top-level dir: rootfs
        return os.path.join(ckpt, name)
    return os.path.join(base, name)


def postmortem_dump(recorder: StepRecorder, checkpoint_dir: str,
                    env: Optional[Dict[str, str]] = None) -> Optional[str]:
    """Best-effort ring-buffer dump on a retryable exit: writes the
    artifact next to the checkpoint dir and returns its path, or None
    (logged) when there is nowhere to write or the write failed — a
    postmortem aid must never turn a retryable exit into a permanent
    one."""
    e = env if env is not None else os.environ
    if not checkpoint_dir:
        log.debug("steptrace: no checkpoint dir; skipping postmortem dump")
        return None

    def _num(var: str) -> int:
        try:
            return int(e.get(var) or 0)
        except ValueError:
            return 0

    attempt, process_id = _num("TPUJOB_ATTEMPT"), _num("JAX_PROCESS_ID")
    path = postmortem_path(checkpoint_dir, attempt, process_id)
    meta = {
        "job": e.get("TPUJOB_NAME", ""),
        "namespace": e.get("TPUJOB_NAMESPACE", "default"),
        "attempt": attempt,
        "processId": process_id,
    }
    try:
        recorder.dump(path, meta=meta)
    except OSError as err:
        # The sibling slot can be unwritable (read-only parent, the
        # checkpoint dir deeper than the mount): fall back INSIDE the
        # checkpoint dir, which the payload provably writes.
        fallback = os.path.join(os.path.abspath(checkpoint_dir),
                                os.path.basename(path))
        if fallback == path:
            log.warning("steptrace: postmortem dump to %s failed: %s",
                        path, err)
            return None
        try:
            recorder.dump(fallback, meta=meta)
            path = fallback
        except OSError as err2:
            log.warning("steptrace: postmortem dump failed (%s: %s; "
                        "%s: %s)", path, err, fallback, err2)
            return None
    log.info("steptrace: dumped last %d step timings to %s",
             min(recorder.steps_recorded, recorder.capacity), path)
    return path
