"""Flax model zoo for the payload images.

The reference's model code lives in external MXNet images
(mxnet-linear-dist: linear regression; mxnet-cifar10-dist: CIFAR-10 ResNet —
README.md:66-96,126-167). These are their TPU-native counterparts, written
MXU-first:

- compute in **bfloat16** (matmuls/convs hit the MXU at full rate), params
  and loss in float32 (stable accumulation);
- static shapes everywhere; no Python control flow that would retrace;
- BatchNorm statistics reduce over the *global* batch: under jit with a
  sharded batch, XLA inserts the cross-device psums automatically — no
  pmap-style axis_name bookkeeping;
- optional tensor parallelism expressed purely as sharding constraints
  (``param_partition_spec``): wide layers shard over the ``model`` mesh
  axis, and GSPMD derives the collectives.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class BasicBlock(nn.Module):
    """CIFAR-style residual basic block: two 3x3 convs + identity/projection."""

    features: int
    strides: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        residual = x
        y = nn.Conv(self.features, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", use_bias=False, dtype=self.dtype,
                    name="conv1")(x)
        y = nn.BatchNorm(use_running_average=not train, dtype=jnp.float32,
                         name="bn1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype, name="conv2")(y)
        y = nn.BatchNorm(use_running_average=not train, dtype=jnp.float32,
                         name="bn2")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False, dtype=self.dtype,
                               name="proj")(residual)
            residual = nn.BatchNorm(use_running_average=not train,
                                    dtype=jnp.float32, name="bn_proj")(residual)
        return nn.relu(y + residual.astype(y.dtype))


class _ScanBlock(nn.Module):
    """``nn.scan`` adapter for :class:`BasicBlock`: the scanned module must
    return a ``(carry, out)`` pair, and ``train`` must ride as an attribute
    because scan broadcasts only the carry/xs call arguments."""

    features: int
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x: jnp.ndarray, _):
        y = BasicBlock(self.features, 1, self.dtype,
                       name="block")(x, train=self.train)
        return y, None


class CifarResNet(nn.Module):
    """ResNet-6n+2 for 32x32 inputs (He et al. CIFAR variant): 3x3 stem,
    three stages at widths ``widths`` with ``blocks_per_stage`` blocks each,
    global average pool, dense head.

    ``depth 20`` = blocks_per_stage 3; the flagship bench config. Tiny
    configs (blocks 1, widths (8,16,32)) keep CPU-mesh tests fast.

    ``scan_blocks`` rolls each stage's stride-1 tail (blocks 1..n-1 — all
    identical in shape) into one ``nn.scan``'d block with stacked params,
    so XLA compiles ONE block body per stage instead of ``n`` inlined
    copies — compile time stops scaling with depth (ROADMAP item 1's
    scan-over-blocks). The stage's stride-2 entry block keeps its own
    params (its projection shortcut differs in shape). Param tree changes
    (``stage{s}_scan/block/...`` leaves gain a leading [n-1] axis), so
    checkpoints do NOT resume across a scan_blocks flip, and the TP rule
    in :func:`param_partition_spec` skips the now-5D conv kernels —
    scan_blocks is the data-parallel compile-time option.
    """

    num_classes: int = 10
    blocks_per_stage: int = 3
    widths: Sequence[int] = (16, 32, 64)
    dtype: Any = jnp.bfloat16
    scan_blocks: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        x = x.astype(self.dtype)
        x = nn.Conv(self.widths[0], (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype, name="stem")(x)
        x = nn.BatchNorm(use_running_average=not train, dtype=jnp.float32,
                         name="bn_stem")(x)
        x = nn.relu(x)
        for stage, width in enumerate(self.widths):
            if self.scan_blocks and self.blocks_per_stage > 1:
                strides = 2 if stage > 0 else 1
                x = BasicBlock(width, strides, self.dtype,
                               name=f"stage{stage}_block0")(x, train=train)
                Scan = nn.scan(
                    _ScanBlock,
                    variable_axes={"params": 0, "batch_stats": 0},
                    split_rngs={"params": True},
                    length=self.blocks_per_stage - 1)
                x, _ = Scan(width, self.dtype, train,
                            name=f"stage{stage}_scan")(x, None)
                continue
            for block in range(self.blocks_per_stage):
                strides = 2 if (stage > 0 and block == 0) else 1
                x = BasicBlock(width, strides, self.dtype,
                               name=f"stage{stage}_block{block}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        # Head computes in f32: small matmul, and logits feed the loss.
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


class DecoderBlock(nn.Module):
    """Pre-LN transformer decoder block, the shared unit of the LM payloads
    (transformer.py's sequence-parallel stack, pipeline.py's stages).

    ``attend`` is injected by the caller — ring attention on a seq-sharded
    mesh, the Pallas flash kernel on a single shard, the jnp oracle on CPU —
    so the block itself stays mesh-agnostic. ``mlp`` optionally replaces the
    dense FFN with a caller-built module factory (the MoE payload passes its
    expert-parallel MoEMLP). Compute dtype parameterized (bf16 on the MXU;
    f32 for parity tests); LayerNorms always f32.
    """

    dim: int
    heads: int
    attend: Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]
    dtype: Any = jnp.bfloat16
    mlp: Optional[Callable[[str], nn.Module]] = None
    # Separate q/k/v projections instead of one fused [dim, 3*dim] kernel.
    # Fused is the single-big-GEMM default; split is what tensor parallelism
    # wants — P(None, "model") on each projection keeps whole heads on one
    # shard, so attention is head-local with no reshard (a fused kernel's
    # contiguous column shards straddle the q/k/v thirds).
    split_qkv: bool = False
    # Grouped-query attention (Ainslie et al. 2023, public technique):
    # K/V project to kv_heads < heads and each K/V head serves
    # heads/kv_heads query heads. Cuts K/V projection params, their
    # gradients, activations, and (at inference) the KV cache by the
    # group factor. K/V go to ``attend`` at kv_heads size — the flash
    # kernels index K/V heads by group (flash_attention.py module
    # docstring), ring rotates kv-sized blocks (group-factor less ICI
    # traffic), ulysses all-to-alls kv-sized K/V, and the jnp oracle
    # broadcasts internally. 0 = MHA (kv_heads == heads); 1 = MQA.
    kv_heads: int = 0

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, t, _ = x.shape
        head_dim = self.dim // self.heads
        kv_heads = self.kv_heads or self.heads
        if kv_heads < 0 or self.heads % kv_heads != 0:
            # Note 4 % -1 == 0 in Python: the sign check cannot be folded
            # into the divisibility one.
            raise ValueError(
                f"heads {self.heads} must divide by kv_heads {kv_heads} > 0")
        kv_dim = kv_heads * head_dim
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(x)
        if self.split_qkv or kv_heads != self.heads:
            # GQA always splits: a fused [d, q+2kv] kernel's thirds are no
            # longer equal, and TP sharding needs per-projection columns.
            q = nn.Dense(self.dim, use_bias=False, dtype=self.dtype,
                         name="q")(h)
            k = nn.Dense(kv_dim, use_bias=False, dtype=self.dtype,
                         name="k")(h)
            v = nn.Dense(kv_dim, use_bias=False, dtype=self.dtype,
                         name="v")(h)
        else:
            qkv = nn.Dense(3 * self.dim, use_bias=False, dtype=self.dtype,
                           name="qkv")(h)
            q, k, v = jnp.split(qkv, 3, axis=-1)
        from jax.ad_checkpoint import checkpoint_name

        # Named for remat policies (no-ops otherwise). "attn_block" saves
        # q/k/v — the flash backward's operands, so their projections are
        # not re-run — and the post-attention residual, which severs the
        # block's serial recompute chain: with q/k/v + attn_residual +
        # the flash residuals resident, the only matmul left to recompute
        # is mlp_up (mlp_down's output is DCE'd from the backward anyway).
        q = checkpoint_name(q.reshape(b, t, self.heads, head_dim), "attn_q")
        # K/V stay at kv_heads: every attend implementation is GQA-native
        # (no jnp.repeat — a broadcast here would materialize full-head
        # K/V activations + gradients, forfeiting GQA's bandwidth win).
        k = checkpoint_name(k.reshape(b, t, kv_heads, head_dim), "attn_k")
        v = checkpoint_name(v.reshape(b, t, kv_heads, head_dim), "attn_v")
        out = self.attend(q, k, v)
        out = nn.Dense(self.dim, use_bias=False, dtype=self.dtype,
                       name="attn_out")(out.reshape(b, t, self.dim))
        x = checkpoint_name(x + out, "attn_residual")
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_mlp")(x)
        if self.mlp is not None:
            return x + self.mlp("moe")(h)
        h = nn.Dense(4 * self.dim, dtype=self.dtype, name="mlp_up")(h)
        # "dots" saves matmul outputs but not the gelu, so mlp_down's
        # backward recomputes the transcendental over the 4*dim hidden —
        # the widest elementwise in the block. A save_only_these_names
        # policy can keep it instead (--remat-policy dots_attn_gelu).
        h = checkpoint_name(nn.gelu(h), "mlp_gelu")
        h = nn.Dense(self.dim, dtype=self.dtype, name="mlp_down")(h)
        return x + h


class LinearRegressor(nn.Module):
    """The linear-regression payload (ref image mxnet-linear-dist,
    README.md:66-96): y = Wx + b."""

    features: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        return nn.Dense(self.features, dtype=jnp.float32, name="linear")(x)


REMAT_POLICIES = ("full", "dots", "dots_attn", "dots_attn_gelu", "attn",
                  "attn_block")


def remat_policy(mode: str):
    """jax.checkpoint policy for a ``--remat-policy`` mode — the ONE
    construction site all LM payloads (transformer, pipeline, MoE) share,
    so the flag cannot be silently ignored by one builder. ``full``
    returns None (recompute everything). ``dots`` saves matmul outputs.
    ``dots_attn`` additionally saves the flash kernels' named residuals
    (output + logsumexp) so attention is not re-run in the backward.
    ``dots_attn_gelu`` additionally saves the MLP gelu output — measured
    slower at the flagship (docs/benchmarks.md negative results) and kept
    as the documented trade. ``attn`` saves ONLY the flash residuals —
    every block matmul recomputes, but the attention forward (over half
    the FLOPs at 32k context, quadratic in T) does not: per-layer
    residency is one [B, T, dim] output + an [B, H, T] logsumexp
    (~130 MiB/layer at the 32k flagship, vs ~1 GiB/layer for dots_attn
    whose saved set includes the 4·dim-wide mlp_up) — the long-context
    policy between ``full`` and ``dots_attn``. On attend paths without
    the flash kernels (CPU oracle, jnp reference) the names never occur
    and ``attn`` degrades to ``full``."""
    import jax

    if mode not in REMAT_POLICIES:
        raise ValueError(f"unknown remat policy {mode!r}")
    if mode == "full":
        return None
    if mode == "attn":
        return jax.checkpoint_policies.save_only_these_names(
            "flash_attn_out", "flash_attn_lse")
    if mode == "attn_block":
        # flash residuals + q/k/v + post-attention residual: the backward
        # recomputes ONLY the mlp_up matmul + gelu (DecoderBlock comment) —
        # ~3.5x less saved bytes than dots_attn (no 4·dim mlp_up/gelu
        # stream), ~4x less recompute than "attn".
        return jax.checkpoint_policies.save_only_these_names(
            "flash_attn_out", "flash_attn_lse", "attn_q", "attn_k",
            "attn_v", "attn_residual")
    if mode == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    names = ["flash_attn_out", "flash_attn_lse"]
    if mode == "dots_attn_gelu":
        names.append("mlp_gelu")
    return jax.checkpoint_policies.save_from_both_policies(
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        jax.checkpoint_policies.save_only_these_names(*names))


def add_remat_policy_flag(parser) -> None:
    """``--remat-policy`` CLI flag, shared by every LM payload parser."""
    parser.add_argument(
        "--remat-policy", choices=REMAT_POLICIES, default="full",
        help="what --remat recomputes: full = everything (min memory); "
             "dots = save matmul outputs, recompute elementwise; "
             "dots_attn = dots + the flash kernels' residuals (attention "
             "not re-run in the backward — the flagship setting); "
             "dots_attn_gelu = dots_attn + the MLP gelu output "
             "(measured slower at the flagship, see "
             "docs/benchmarks.md negative results); "
             "attn = ONLY the flash residuals (block matmuls recompute, "
             "attention does not — the long-context setting where "
             "dots_attn's saved set does not fit); "
             "attn_block = attn + q/k/v + the post-attention residual "
             "(only mlp_up+gelu recompute; between attn and dots_attn "
             "in residency)")


def resolve_split_qkv(mode: str, tp: int, log) -> bool:
    """The shared --split-qkv resolution for every LM payload: 'auto'
    splits under TP (each model shard owns whole heads); an explicit 'off'
    under TP is allowed (fused-kernel checkpoint layouts) but warned — the
    fused [d, 3d] kernel's contiguous column shards straddle the q/k/v
    thirds, so heads stop being shard-local."""
    if mode == "off" and tp > 1:
        log.warning(
            "--split-qkv off with --tensor-parallel %d: the fused qkv "
            "kernel's column shards straddle the q/k/v thirds (heads not "
            "shard-local); use auto/on unless checkpoint layout "
            "compatibility requires the fused kernel", tp)
    return mode == "on" or (mode == "auto" and tp > 1)


def validate_heads_dims(heads: int, kv_heads: int, dim: int, tp: int) -> None:
    """The shared --kv-heads / --tensor-parallel divisibility contract:
    heads (and K/V heads, if grouped) divide by TP so shards own whole
    heads; dim divides by TP for the column/row kernel shards. Raises
    ValueError with the flag names the operator actually typed."""
    if kv_heads < 0:
        raise ValueError(f"--kv-heads must be >= 0, got {kv_heads}")
    if kv_heads and heads % kv_heads != 0:
        # Note 4 % -1 == 0 in Python: the sign check above cannot be
        # folded into this divisibility one.
        raise ValueError(
            f"--heads {heads} must divide by --kv-heads {kv_heads}")
    if tp > 1:
        if heads % tp != 0:
            raise ValueError(
                f"--heads {heads} must divide by --tensor-parallel {tp} "
                f"(TP shards whole heads)")
        if kv_heads and kv_heads % tp != 0:
            raise ValueError(
                f"--kv-heads {kv_heads} must divide by --tensor-parallel "
                f"{tp} (TP shards whole K/V heads)")
        if dim % tp != 0:
            raise ValueError(
                f"--dim {dim} must divide by --tensor-parallel {tp}")


def param_partition_spec(path: Tuple[str, ...], leaf: Any) -> P:
    """Sharding rule for tensor parallelism over the ``model`` mesh axis.

    DP-only meshes (model axis size 1) make every spec a no-op replication;
    with model > 1, the classifier head and the widest (stage-2) conv kernels
    shard their output-channel dimension, and GSPMD inserts the collectives.
    Conv kernels are HWIO; Dense kernels are (in, out).
    """
    names = [p for p in path]
    if "head" in names and names[-1] == "kernel":
        return P(None, "model")
    if any(n.startswith("stage2") for n in names) and names[-1] == "kernel" \
            and getattr(leaf, "ndim", 0) == 4:
        return P(None, None, None, "model")
    return P()  # replicate


# --- cached-decode mirrors ----------------------------------------------------
#
# The serve payload's incremental decode (payload/kvcache.py) runs the SAME
# math as DecoderBlock / the transformer payload's TransformerLM, but over a
# one-token (or prompt-length) slice with a caller-owned attention — the
# cached K/V live outside the param tree, so the flax module (whose attend is
# baked in at construction) cannot express it. These mirrors re-apply the
# exact same flax submodules *standalone* against the trained param subtrees
# (nn.Dense(...).apply({"params": params["q"]}, h) is bit-identical to the
# in-module call — same kernel, same dtype casts, same op order), so decode
# shares weights AND numerics with training without a second model
# definition. checkpoint_name tags are identity outside jax.checkpoint and
# decode never differentiates, so they are simply omitted.


def decoder_block_decode(params, x: jnp.ndarray, attend: Callable,
                         *, dim: int, heads: int, kv_heads: int = 0,
                         dtype: Any = jnp.bfloat16) -> jnp.ndarray:
    """Functional mirror of :class:`DecoderBlock` over one block's param
    subtree. ``attend`` receives (q [B,T,H,Dh], k, v [B,T,KVH,Dh]) exactly
    as in the module — the decode caller writes k/v into its cache and
    attends against the gathered span; the prefill caller runs the plain
    causal forward. Fused-qkv and split/GQA param layouts both load (the
    subtree shape says which one trained)."""
    b, t, _ = x.shape
    head_dim = dim // heads
    kvh = kv_heads or heads
    kv_dim = kvh * head_dim
    h = nn.LayerNorm(dtype=jnp.float32).apply(
        {"params": params["ln_attn"]}, x)
    if "qkv" in params:
        qkv = nn.Dense(3 * dim, use_bias=False, dtype=dtype).apply(
            {"params": params["qkv"]}, h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
    else:
        q = nn.Dense(dim, use_bias=False, dtype=dtype).apply(
            {"params": params["q"]}, h)
        k = nn.Dense(kv_dim, use_bias=False, dtype=dtype).apply(
            {"params": params["k"]}, h)
        v = nn.Dense(kv_dim, use_bias=False, dtype=dtype).apply(
            {"params": params["v"]}, h)
    q = q.reshape(b, t, heads, head_dim)
    k = k.reshape(b, t, kvh, head_dim)
    v = v.reshape(b, t, kvh, head_dim)
    out = attend(q, k, v)
    out = nn.Dense(dim, use_bias=False, dtype=dtype).apply(
        {"params": params["attn_out"]}, out.reshape(b, t, dim))
    x = x + out
    h = nn.LayerNorm(dtype=jnp.float32).apply(
        {"params": params["ln_mlp"]}, x)
    h = nn.Dense(4 * dim, dtype=dtype).apply(
        {"params": params["mlp_up"]}, h)
    h = nn.gelu(h)
    h = nn.Dense(dim, dtype=dtype).apply(
        {"params": params["mlp_down"]}, h)
    return x + h


def lm_decode_apply(params, tokens: jnp.ndarray, positions: jnp.ndarray,
                    attend_for_layer: Callable[[int], Callable],
                    *, vocab: int, dim: int, heads: int, layers: int,
                    max_seq: int, kv_heads: int = 0,
                    dtype: Any = jnp.bfloat16) -> jnp.ndarray:
    """Functional mirror of the transformer payload's TransformerLM
    forward (embed + blocks + ln_final + lm_head) with explicit per-row
    ``positions`` [B, T] and a per-layer attention factory —
    ``attend_for_layer(i)`` returns the attend callable for block ``i``
    (each layer owns a distinct cache region). Returns [B, T, vocab]
    logits in bf16, exactly as the module does."""
    x = nn.Embed(vocab, dim, dtype=jnp.bfloat16).apply(
        {"params": params["tok_embed"]}, tokens)
    pos = nn.Embed(max_seq, dim, dtype=jnp.bfloat16).apply(
        {"params": params["pos_embed"]}, positions)
    x = x + pos
    for i in range(layers):
        x = decoder_block_decode(params[f"block{i}"], x,
                                 attend_for_layer(i), dim=dim, heads=heads,
                                 kv_heads=kv_heads, dtype=dtype)
    x = nn.LayerNorm(dtype=jnp.float32).apply(
        {"params": params["ln_final"]}, x)
    return nn.Dense(vocab, use_bias=False, dtype=jnp.bfloat16).apply(
        {"params": params["lm_head"]}, x)


Model = Callable[..., nn.Module]
