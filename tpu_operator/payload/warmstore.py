"""Payload-side adapter for the remote warm-start store.

This is the env-contract consumer half of ``spec.store``: the operator
(trainer/replicas.py) injects ``TPUJOB_STORE_*``; this module turns it
into a :class:`tpu_operator.store.WarmStartStore`, a write-behind
uploader for the checkpointer, and the rendezvous-overlapped prefetch
bootstrap runs.

Injected env contract:

- ``TPUJOB_STORE_BACKEND``     — localfs | fake (spec.store.backend)
- ``TPUJOB_STORE_URI``         — blob-store root the backend resolves
- ``TPUJOB_STORE_PARALLELISM`` — chunk-transfer fan-out
- ``TPUJOB_STORE_PREFETCH``    — "0"/"false" skips the startup download

Job identity (``TPUJOB_NAMESPACE``/``TPUJOB_NAME``) scopes the store
prefix, so many jobs share one bucket/mount without collisions.

Everything here is strictly best-effort at startup: a misconfigured or
unreachable store logs and the attempt proceeds cold — the store may
never ADD a way for an attempt to fail. (Persistent UPLOAD failures do
escalate, but through the checkpointer's save-failure contract, where
the operator's restart machinery owns the outcome.)

Prefetch sequencing (the critical-path design): ``start_prefetch`` is
called by bootstrap.initialize BEFORE the coordinator DNS wait, and
``finish_prefetch`` after the process group forms — so the download runs
concurrently with the rendezvous that is already on every attempt's
critical path, and only the tail that outlives it is actually paid
(recorded as the PREFETCH startup stage). The checkpoint lands in the
local checkpoint dir, where PR 4's verified-restore walk picks it up
like any other on-disk step — prefetch adds bytes, never trust.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Optional

from tpu_operator.payload import startup as startup_mod
from tpu_operator.util import lockdep

log = logging.getLogger(__name__)

# Injected by trainer/replicas.py when spec.store is set.
ENV_BACKEND = "TPUJOB_STORE_BACKEND"
ENV_URI = "TPUJOB_STORE_URI"
ENV_PARALLELISM = "TPUJOB_STORE_PARALLELISM"
ENV_PREFETCH = "TPUJOB_STORE_PREFETCH"
# Retention GC (spec.store.keepSnapshots > 0): the write-behind worker
# keeps only the newest N verified snapshots remotely.
ENV_KEEP = "TPUJOB_STORE_KEEP"

# How long finish_prefetch will wait for the download tail after
# rendezvous before proceeding cold (the store must never hang startup;
# the stall watchdog would otherwise eventually restart the group into
# the same wait).
PREFETCH_JOIN_TIMEOUT = 300.0


def store_from_env(env: Optional[Dict[str, str]] = None
                   ) -> Optional[Any]:
    """Build the job's WarmStartStore from the injected env, or None when
    the store is not wired. Never raises: a bad URI/backend logs and
    returns None (attempt proceeds store-less)."""
    e = env if env is not None else os.environ
    uri = e.get(ENV_URI, "")
    if not uri:
        return None
    from tpu_operator.store import WarmStartStore, blob

    try:
        backend = blob.from_uri(uri)
    except Exception as err:  # noqa: BLE001 — never fail the attempt
        # Broader than BlobError on purpose: LocalFSBackend.__init__
        # makedirs an unmounted/read-only root (OSError), and a
        # deployment-registered factory can raise anything — any of it
        # must degrade the attempt to store-less, never crash it into
        # run_payload's permanent-failure exit.
        log.warning("warm-start store disabled (unusable %s=%r): %s",
                    ENV_URI, uri, err)
        return None
    try:
        parallelism = int(e.get(ENV_PARALLELISM) or 4)
    except ValueError:
        log.warning("ignoring malformed %s=%r", ENV_PARALLELISM,
                    e.get(ENV_PARALLELISM))
        parallelism = 4
    namespace = e.get("TPUJOB_NAMESPACE", "default") or "default"
    job = e.get("TPUJOB_NAME", "") or "job"
    return WarmStartStore(backend, prefix=f"{namespace}/{job}",
                          upload_parallelism=parallelism)


def _is_process_zero(env: Dict[str, str]) -> bool:
    try:
        return int(env.get("JAX_PROCESS_ID") or 0) == 0
    except ValueError:
        return True


def uploader_from_env(env: Optional[Dict[str, str]] = None,
                      fail_after: Optional[int] = None) -> Optional[Any]:
    """The write-behind uploader for this process, or None when the store
    is unwired OR this is not process 0 — one writer per job keeps the
    remote layout race-free, the same single-writer discipline as the
    local checkpoint manifest."""
    e = env if env is not None else os.environ
    if not _is_process_zero(dict(e)):
        return None
    store = store_from_env(e)
    if store is None:
        return None
    from tpu_operator.store import writebehind

    try:
        keep = int(e.get(ENV_KEEP) or 0)
    except ValueError:
        log.warning("ignoring malformed %s=%r", ENV_KEEP, e.get(ENV_KEEP))
        keep = 0
    return writebehind.WriteBehindUploader(
        store,
        fail_after=(fail_after if fail_after is not None
                    else writebehind.DEFAULT_FAIL_AFTER),
        # Resolved at upload time: bootstrap enables the cache after the
        # checkpointer (and thus this uploader) may already exist.
        cache_dir_fn=startup_mod.cache_dir,
        keep_snapshots=keep)


# --- rendezvous-overlapped prefetch ------------------------------------------

_prefetch_lock = lockdep.lock("warmstore._prefetch_lock")
_prefetch_thread: Optional[threading.Thread] = None  # guarded-by: _prefetch_lock
_prefetch_result: Dict[str, Any] = {}  # guarded-by: _prefetch_lock


def _prefetch_worker(store: Any, cache_dir: str, ckpt_dir: str) -> None:
    result: Dict[str, Any] = {"checkpointStep": None, "cacheFiles": 0,
                              "fallbacks": 0}
    try:
        if cache_dir:
            result["cacheFiles"] = store.prefetch_cache(cache_dir)
        if ckpt_dir:
            step, fallbacks = store.prefetch_checkpoint(ckpt_dir)
            result["checkpointStep"] = step
            result["fallbacks"] = fallbacks
    except Exception as e:  # noqa: BLE001 — prefetch must never fail startup
        log.warning("warm-start prefetch failed (proceeding cold): %s", e)
        result["error"] = str(e)
    with _prefetch_lock:
        _prefetch_result.update(result)


def start_prefetch(env: Optional[Dict[str, str]] = None) -> bool:
    """Kick off the store download on a worker thread (idempotent; False
    when the store is unwired or prefetch is disabled). Call BEFORE the
    rendezvous wait so the bytes move while DNS warms up."""
    global _prefetch_thread
    e = env if env is not None else os.environ
    if str(e.get(ENV_PREFETCH, "1")).lower() in ("0", "false"):
        return False
    store = store_from_env(e)
    if store is None:
        return False
    # The compilation-cache dir comes from the same env bootstrap reads;
    # the checkpoint dir from the PR 4 contract (TPU_CHECKPOINT_DIR).
    cache_dir = e.get("JAX_COMPILATION_CACHE_DIR", "") \
        or e.get("TPUJOB_CACHE_PATH", "")
    ckpt_dir = e.get("TPU_CHECKPOINT_DIR", "")
    if not cache_dir and not ckpt_dir:
        return False
    with _prefetch_lock:
        if _prefetch_thread is not None:
            return True
        _prefetch_result.clear()
        _prefetch_result["started_at"] = time.perf_counter()
        _prefetch_thread = threading.Thread(
            target=_prefetch_worker, args=(store, cache_dir, ckpt_dir),
            daemon=True, name="store-prefetch")
        _prefetch_thread.start()
    return True


def finish_prefetch(timeout: float = PREFETCH_JOIN_TIMEOUT
                    ) -> Optional[Dict[str, Any]]:
    """Join the prefetch (bounded) and record the PREFETCH startup stage:
    the recorded duration is the tail paid HERE — i.e. beyond whatever
    the download overlapped — which is the store's true critical-path
    cost. Returns the result dict, or None when no prefetch ran."""
    global _prefetch_thread
    with _prefetch_lock:
        thread = _prefetch_thread
    if thread is None:
        return None
    t0 = time.perf_counter()
    thread.join(timeout)
    tail = time.perf_counter() - t0
    if thread.is_alive():
        log.warning("warm-start prefetch still running after %.0fs; "
                    "proceeding cold (download continues best-effort)",
                    timeout)
        startup_mod.record_prefetch(tail, False)
        return {"timeout": True}
    with _prefetch_lock:
        result = dict(_prefetch_result)
        _prefetch_thread = None
    hit = bool(result.get("cacheFiles")) \
        or result.get("checkpointStep") is not None
    startup_mod.record_prefetch(tail, hit)
    result["tailSeconds"] = tail
    if hit:
        log.info(
            "warm-start prefetch: checkpoint step %s, %d cache entries "
            "(%.2fs beyond rendezvous)", result.get("checkpointStep"),
            result.get("cacheFiles", 0), tail)
    else:
        log.info("warm-start prefetch: nothing to fetch (cold store)")
    return result


def upload_cache_once(env: Optional[Dict[str, str]] = None) -> int:
    """One-shot best-effort compilation-cache sync (process 0 only):
    bootstrap.run_payload calls this at payload exit so jobs with a store
    but NO checkpointing — where no write-behind uploader ever exists —
    still populate the remote cache, and a checkpointed attempt that
    compiled but exited before its first save ships its executables on
    the clean/drain path. Returns files uploaded (0 on any failure)."""
    e = env if env is not None else os.environ
    if not _is_process_zero(dict(e)):
        return 0
    store = store_from_env(e)
    if store is None:
        return 0
    # The module-level cache_dir() (what bootstrap actually enabled) is
    # authoritative ONLY for the ambient path (env=None, production): an
    # explicit env mapping is the caller's whole contract, and consulting
    # ambient process state from it let one test's enable_compilation_
    # cache() leak its tmp dir into a later test's upload (order-
    # dependent tier-1 flake, reproduced on the unmodified tree).
    cache_dir = e.get("JAX_COMPILATION_CACHE_DIR", "") \
        or e.get("TPUJOB_CACHE_PATH", "")
    if env is None:
        cache_dir = startup_mod.cache_dir() or cache_dir
    if not cache_dir:
        return 0
    try:
        n = store.upload_cache(cache_dir)
    except Exception as err:  # noqa: BLE001 — exit-path best-effort
        log.warning("exit-path compilation-cache upload failed: %s", err)
        return 0
    if n:
        log.info("exit-path cache sync: uploaded %d compilation-cache "
                 "entries", n)
    return n


def reset_prefetch() -> None:
    """Test hook: forget any in-flight/finished prefetch state."""
    global _prefetch_thread
    with _prefetch_lock:
        _prefetch_thread = None
        _prefetch_result.clear()
    startup_mod.reset_prefetch()
