"""On-demand deep profiling: one-shot capture of raw per-step laps.

The flight recorder (``steptrace.py``) ships windowed *digests* on the
heartbeat — p50/p95/max per phase — which is the right steady-state cost
but the wrong artifact for "why is step 41k slow on THIS job RIGHT NOW":
a digest has no per-step resolution and no device trace. This module is
the payload half of the profile directive round-trip:

- the controller stamps ``status.profile`` (state ``Requested``) from a
  ``tpujobctl profile`` annotation;
- the status server piggybacks the directive on a heartbeat ACK to
  process 0 (no new channel, no payload-facing port);
- :class:`ProfileCapture` then records the NEXT N committed steps' raw
  wall laps, merges the flight recorder's per-phase rows for the same
  step span when the recorder is on, and optionally brackets the window
  with a ``jax.profiler`` trace (gated: jax may be absent, and the
  loop's own ``--profile`` window owns the profiler when active);
- the JSON artifact ships through the PR-8 write-behind ``artifacts/``
  path and the result rides back on the next heartbeat, where the
  controller folds ``status.profile`` to ``Captured``.

Stdlib-only on purpose (same discipline as ``steptrace.py``): the
controller and tests import this module's constants and must not drag
jax into the control plane; ``jax.profiler`` is imported lazily inside a
broad try/except at trace start/stop only.
"""

from __future__ import annotations

import json
import logging
import os
import re
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

# Directive defaults/bounds. The controller clamps ``steps`` at admission
# too, but the payload re-clamps: the directive crossed two trust
# boundaries (annotation JSON, heartbeat ACK body) to get here.
DEFAULT_STEPS = 8
MAX_STEPS = 512

ARTIFACT_KIND = "tpujob-profile"


def _safe_id(raw: str) -> str:
    """Directive ids become file names; strip anything path-hostile."""
    cleaned = re.sub(r"[^A-Za-z0-9._-]", "_", raw or "")
    return cleaned or "anon"


class ProfileCapture:
    """One in-flight capture window. Step-loop thread only, never shared:
    armed when the heartbeat ACK delivers a directive, ticked once per
    committed step, finished when the requested window is full.

    The wall lap is measured between consecutive :meth:`tick` calls —
    the tick site sits at a fixed point of the loop body (after
    ``recorder.commit()``), so the delta spans exactly one full step
    including every host phase, with zero added fences."""

    def __init__(self, directive: Dict[str, Any], base_dir: str = "",
                 allow_jax_trace: bool = True):
        self.id = str(directive.get("id") or "")
        try:
            steps = int(directive.get("steps") or DEFAULT_STEPS)
        except (TypeError, ValueError):
            steps = DEFAULT_STEPS
        self.steps = max(1, min(MAX_STEPS, steps))
        self.base_dir = base_dir or tempfile.gettempdir()
        self._allow_trace = allow_jax_trace
        self._laps: List[Dict[str, Any]] = []
        self._t_last: Optional[float] = None
        self._tracing = False
        self.trace_dir = ""
        self.first_step: Optional[int] = None
        self.last_step: Optional[int] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self, completed_step: int) -> None:
        """Arm the window: ``completed_step`` is the step that just
        finished (the directive rode its heartbeat ACK); capture begins
        with the NEXT step so every lap is a whole step."""
        self.first_step = completed_step + 1
        self._t_last = time.perf_counter()
        if self._allow_trace:
            try:
                import jax  # noqa: PLC0415 — payload-only, absent on the control plane

                self.trace_dir = os.path.join(
                    self.base_dir, "profile-trace-%s" % _safe_id(self.id))
                jax.profiler.start_trace(self.trace_dir)
                self._tracing = True
            except Exception:  # noqa: BLE001 — trace is a bonus, never a blocker
                self.trace_dir = ""
        log.info("profile %s: capturing %d step(s) from step %d "
                 "(jax trace: %s)", self.id or "<anon>", self.steps,
                 self.first_step, "on" if self._tracing else "off")

    def tick(self, completed_step: int) -> bool:
        """Record the wall lap for the step that just committed; True once
        the requested window is full (caller then calls :meth:`finish`)."""
        now = time.perf_counter()
        if (self._t_last is not None and self.first_step is not None
                and completed_step >= self.first_step):
            self._laps.append({
                "step": completed_step,
                "wallSeconds": round(now - self._t_last, 6),
            })
            self.last_step = completed_step
        self._t_last = now
        return len(self._laps) >= self.steps

    def _stop_trace(self) -> None:
        if not self._tracing:
            return
        self._tracing = False
        try:
            import jax  # noqa: PLC0415

            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 — a failed stop must not kill the step loop
            log.debug("profile %s: jax trace stop failed", self.id,
                      exc_info=True)

    def abandon(self) -> None:
        """Teardown path: close any open jax trace, drop the laps. Called
        from the loop's ``finally`` so a preemption mid-capture never
        leaves the profiler started."""
        self._stop_trace()
        self._laps = []

    # -- artifact -----------------------------------------------------------

    def _merge_recorder(self, recorder: Any) -> List[Dict[str, Any]]:
        """Join the flight recorder's per-phase rows onto the wall laps.
        The ring keys steps by the 0-based loop index (``begin(i)``) while
        the heartbeat — and this capture — speak 1-based completed steps,
        hence the ``-1``. Best-effort: the ring may have already evicted
        the span's head on tiny capacities."""
        rows = [dict(lap) for lap in self._laps]
        if recorder is None or not rows:
            return rows
        try:
            by_step = {rec.get("step"): rec for rec in recorder.snapshot()}
        except Exception:  # noqa: BLE001 — recorder is observability, not control flow
            return rows
        for row in rows:
            rec = by_step.get(row["step"] - 1)
            if not rec:
                continue
            for key, value in rec.items():
                if key != "step":
                    row.setdefault(key, value)
        return rows

    def finish(self, recorder: Any = None
               ) -> Tuple[str, Dict[str, Any]]:
        """Close the window: stop the trace, write the artifact JSON
        (atomic tmp+rename), and return ``(path, result)`` where result
        is the heartbeat's ``profile`` payload. A failed write returns an
        empty path with the result intact — the controller still folds
        ``Captured`` (sans artifactKey) instead of re-requesting forever."""
        self._stop_trace()
        steps = self._merge_recorder(recorder)
        result: Dict[str, Any] = {
            "id": self.id,
            "capturedSteps": len(steps),
        }
        body: Dict[str, Any] = {
            "kind": ARTIFACT_KIND,
            "id": self.id,
            "requestedSteps": self.steps,
            "capturedSteps": len(steps),
            "firstStep": self.first_step,
            "lastStep": self.last_step,
            "jaxTraceDir": self.trace_dir,
            "steps": steps,
        }
        path = os.path.join(self.base_dir,
                            "profile-%s.json" % _safe_id(self.id))
        try:
            os.makedirs(self.base_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(body, f, indent=1)
            os.replace(tmp, path)
        except OSError:
            log.warning("profile %s: artifact write to %s failed",
                        self.id, path, exc_info=True)
            return "", result
        return path, result
