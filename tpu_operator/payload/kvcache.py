"""Block-paged KV cache + incremental decode engine for ``mode: serve``.

PR 13's serve loop re-forwards the whole ``[batch, window]`` request matrix
for every generated token: per-token cost O(window · model), the direct
analogue of the quadratic-prefill-per-token trap PagedAttention (Kwon et
al., vLLM — public technique) exists to remove. This module is the
incremental engine that replaces it:

- **Paged cache.** K/V for every request live in fixed-size *pages* of a
  shared device pool (``[layers, pages, page_size, kv_heads, head_dim]``,
  bf16). A request owns ``ceil((prompt + budget) / page_size)`` pages,
  allocated at admission and freed the moment it completes — a finished
  short request's pages immediately serve a waiting long one. Per-slot
  *page tables* (host int32, shipped to device each step) map positions to
  pages; the gather through the table is what makes slot memory contiguous
  to the kernel without ever being contiguous in HBM.
- **Prefill = the batched forward.** Admission runs ONE causal forward over
  the (padded) prompt on the ordinary attention path, emits the first
  generated token, and scatters the prompt's K/V into the slot's pages.
- **Decode = one token per slot per step.** The jitted step embeds each
  active slot's last token at its current position, writes its K/V through
  the page table, and attends against the gathered cache span with
  :func:`flash_attention.flash_decode` (length-masked, GQA-native). Cost
  per token is O(length · kv) instead of O(window · model).

Masking discipline (what makes paged == dense *bit-equal*): any cache
position ≥ a slot's length — zero-init, stale pages from a released
request, the padded prompt tail — scores NEG_INF, whose probability
underflows to exactly 0.0 in f32, so finite garbage contributes exactly
nothing. Invalid writes (padded tail past a request's capacity, inactive
slots) are steered to a sacrificial *trash page* (index ``num_pages``)
that no table ever reads as valid, so they can never corrupt a neighbour.

Threading: the engine is owned by the serve loop's single decode thread
(serve.py's design — the reload watcher and HTTP ingress threads never
touch it); the host-side tables/allocator therefore need no lock. Params
are an *argument* to every jitted call, which is the hot-reload contract:
swapping weights swaps nothing here, so live KV pages survive a reload.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable, List, Optional

log = logging.getLogger(__name__)

DEFAULT_PAGE_SIZE = 16


class PageAllocator:
    """Free-list page allocator with strict invariants: a page is either
    free or held, double-free and foreign-free raise, and allocation is
    all-or-nothing (a request that cannot get every page it needs gets
    none, so admission never deadlocks holding a partial set)."""

    def __init__(self, num_pages: int):
        if num_pages <= 0:
            raise ValueError(f"num_pages must be positive, got {num_pages}")
        self.num_pages = int(num_pages)
        self._free: List[int] = list(range(num_pages - 1, -1, -1))
        self._held: set = set()

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def held_pages(self) -> int:
        return len(self._held)

    def utilization(self) -> float:
        return len(self._held) / self.num_pages

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` pages, or None when the pool cannot satisfy all of them
        (the caller leaves the request queued — backpressure, not error)."""
        if n <= 0:
            raise ValueError(f"alloc needs a positive page count, got {n}")
        if len(self._free) < n:
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._held.update(pages)
        return pages

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if p not in self._held:
                raise ValueError(
                    f"page {p} is not held (double free or foreign page)")
            self._held.discard(p)
            self._free.append(p)


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """The decode mirrors' model shape (models.lm_decode_apply args)."""

    vocab: int
    dim: int
    heads: int
    layers: int
    max_seq: int
    kv_heads: int = 0

    @property
    def grouped_kv_heads(self) -> int:
        return self.kv_heads or self.heads

    @property
    def head_dim(self) -> int:
        return self.dim // self.heads


def _prefill_attend(q, k, v):
    """Prompt attention = the ordinary batched causal forward, on the same
    path the transformer payload selects (kernel on TPU, jnp elsewhere)."""
    from tpu_operator.payload import flash_attention as fa
    from tpu_operator.payload import ring_attention as ring

    if fa.use_pallas_default():
        return fa.flash_attention(q, k, v, causal=True)
    return ring.reference_attention(q, k, v, causal=True)


class DecodeEngine:
    """Paged-cache incremental decode over ``slots`` concurrent requests.

    Host side: page allocator + per-slot page tables / lengths / last
    tokens (numpy). Device side: the page pool and two jitted functions —
    ``prefill`` (one request) and ``step`` (all slots). Params are passed
    per call; the engine never holds weights.
    """

    def __init__(self, spec: ModelSpec, *, slots: int,
                 prompt_pad: int, max_new: int,
                 page_size: int = DEFAULT_PAGE_SIZE, num_pages: int = 0,
                 dtype: Any = None):
        import jax
        import jax.numpy as jnp
        import numpy as np

        if slots <= 0:
            raise ValueError(f"slots must be positive, got {slots}")
        if page_size <= 0:
            raise ValueError(f"page_size must be positive, got {page_size}")
        self.spec = spec
        self.slots = int(slots)
        self.page_size = int(page_size)
        self.prompt_pad = int(prompt_pad)
        self.max_context = int(prompt_pad + max_new)
        if self.max_context > spec.max_seq:
            raise ValueError(
                f"max context {self.max_context} (prompt {prompt_pad} + "
                f"{max_new} new) exceeds the model's max_seq {spec.max_seq}")
        self.pages_per_slot = -(-self.max_context // self.page_size)
        self.num_pages = int(num_pages) or self.slots * self.pages_per_slot
        # The gathered span per slot: the table is a fixed pages_per_slot
        # wide, so the kernel sees one static padded capacity.
        self.capacity_tokens = self.pages_per_slot * self.page_size
        self._trash = self.num_pages  # sacrificial page for invalid writes
        self._np = np
        self._jnp = jnp
        dtype = dtype or jnp.bfloat16
        shape = (spec.layers, self.num_pages + 1, self.page_size,
                 spec.grouped_kv_heads, spec.head_dim)
        self._k_pages = jnp.zeros(shape, dtype)
        self._v_pages = jnp.zeros(shape, dtype)
        self.allocator = PageAllocator(self.num_pages)
        self._tables = np.full((self.slots, self.pages_per_slot),
                               self._trash, np.int32)
        self._lengths = np.zeros(self.slots, np.int32)
        self._last = np.zeros(self.slots, np.int32)
        self._capacity = np.zeros(self.slots, np.int32)
        self._owned: List[Optional[List[int]]] = [None] * self.slots
        self._prefill = jax.jit(self._prefill_impl)
        self._step = jax.jit(self._step_impl)

    # -- jitted compute --------------------------------------------------------

    def _lm(self, params, tokens, positions, attend_for_layer):
        from tpu_operator.payload import models

        s = self.spec
        return models.lm_decode_apply(
            params, tokens, positions, attend_for_layer, vocab=s.vocab,
            dim=s.dim, heads=s.heads, kv_heads=s.kv_heads, layers=s.layers,
            max_seq=s.max_seq)

    def _prefill_impl(self, params, k_pages, v_pages, tokens, length, table):
        """One request's admission forward: causal attention over the
        padded prompt, first-token argmax at ``length - 1``, prompt K/V
        scattered through the slot's page table. Padded-tail positions
        land in owned-but-not-yet-valid slots (masked until decode
        overwrites them) or the trash page — never a neighbour."""
        import jax.numpy as jnp

        collected = []

        def attend_for_layer(_i):
            def attend(q, k, v):
                collected.append((k, v))
                return _prefill_attend(q, k, v)
            return attend

        positions = jnp.arange(self.prompt_pad, dtype=jnp.int32)[None, :]
        logits = self._lm(params, tokens, positions, attend_for_layer)
        nxt = jnp.argmax(
            logits[0, length - 1].astype(jnp.float32)).astype(jnp.int32)
        pos = jnp.arange(self.prompt_pad, dtype=jnp.int32)
        page_ids = table[pos // self.page_size]
        offs = pos % self.page_size
        for i, (k, v) in enumerate(collected):
            k_pages = k_pages.at[i, page_ids, offs].set(k[0])
            v_pages = v_pages.at[i, page_ids, offs].set(v[0])
        return nxt, k_pages, v_pages

    def _step_impl(self, params, k_pages, v_pages, last, lengths, tables,
                   active):
        """One decode iteration over every slot: embed each slot's last
        token at its current position, write its K/V through the page
        table (inactive slots write the trash page), and attend against
        the gathered span with the length-masked decode kernel."""
        import jax.numpy as jnp

        from tpu_operator.payload import flash_attention as fa

        s = self.spec
        kvh, hd = s.grouped_kv_heads, s.head_dim
        tokens = last[:, None]
        positions = jnp.minimum(lengths, s.max_seq - 1)[:, None]
        page_sel = jnp.take_along_axis(
            tables, (lengths // self.page_size)[:, None], axis=1)[:, 0]
        page_sel = jnp.where(active, page_sel, self._trash)
        offs = lengths % self.page_size

        def attend_for_layer(i):
            def attend(q, k, v):
                nonlocal k_pages, v_pages
                k_pages = k_pages.at[i, page_sel, offs].set(k[:, 0])
                v_pages = v_pages.at[i, page_sel, offs].set(v[:, 0])
                kd = k_pages[i][tables].reshape(
                    self.slots, self.capacity_tokens, kvh, hd)
                vd = v_pages[i][tables].reshape(
                    self.slots, self.capacity_tokens, kvh, hd)
                return fa.flash_decode(q, kd, vd, lengths + 1)
            return attend

        logits = self._lm(params, tokens, positions, attend_for_layer)
        nxt = jnp.argmax(logits[:, 0].astype(jnp.float32),
                         axis=-1).astype(jnp.int32)
        return nxt, k_pages, v_pages

    # -- host-side slot management ---------------------------------------------

    def pages_needed(self, prompt_len: int, new_tokens: int) -> int:
        return -(-(prompt_len + new_tokens) // self.page_size)

    def can_admit(self, prompt_len: int, new_tokens: int) -> bool:
        return (self.allocator.free_pages
                >= self.pages_needed(prompt_len, new_tokens))

    def free_slots(self) -> List[int]:
        return [i for i in range(self.slots) if self._owned[i] is None]

    def admit(self, slot: int, prompt, new_tokens: int,
              params) -> Optional[int]:
        """Admit a request into ``slot``: allocate its pages, prefill, and
        return the FIRST generated token (it counts against the request's
        budget). None = page pool exhausted; the request stays queued."""
        np = self._np
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self._owned[slot] is not None:
            raise ValueError(f"slot {slot} is already occupied")
        if new_tokens <= 0:
            raise ValueError(f"new_tokens must be positive, got {new_tokens}")
        if len(prompt) == 0 or len(prompt) > self.prompt_pad:
            raise ValueError(
                f"prompt length {len(prompt)} not in [1, {self.prompt_pad}]")
        if len(prompt) + new_tokens > self.max_context:
            raise ValueError(
                f"prompt {len(prompt)} + {new_tokens} new tokens exceeds "
                f"max context {self.max_context}")
        pages = self.allocator.alloc(
            self.pages_needed(len(prompt), new_tokens))
        if pages is None:
            return None
        table = np.full(self.pages_per_slot, self._trash, np.int32)
        table[:len(pages)] = pages
        padded = np.zeros(self.prompt_pad, np.int32)
        padded[:len(prompt)] = prompt
        nxt, self._k_pages, self._v_pages = self._prefill(
            params, self._k_pages, self._v_pages, padded[None, :],
            np.int32(len(prompt)), table)
        self._tables[slot] = table
        self._lengths[slot] = len(prompt)
        self._last[slot] = int(nxt)
        self._capacity[slot] = len(prompt) + new_tokens
        self._owned[slot] = pages
        return int(nxt)

    def step(self, params, active) -> Any:
        """One decode iteration; ``active`` is a bool [slots] mask. Returns
        the int32 [slots] next tokens (garbage at inactive slots). Active
        slots advance one position — their previous token's K/V is written
        before it attends, so the new token sees its own key."""
        np = self._np
        active = np.asarray(active, bool)
        for slot in np.nonzero(active)[0]:
            if self._owned[slot] is None:
                raise ValueError(f"slot {slot} is active but unoccupied")
            # A step advances the slot to length + 1; the prefill's first
            # token already counted, so a slot whose next token would
            # land past prompt + budget is already over budget.
            if self._lengths[slot] + 1 >= self._capacity[slot]:
                raise ValueError(
                    f"slot {slot} at capacity {self._capacity[slot]}")
        nxt, self._k_pages, self._v_pages = self._step(
            params, self._k_pages, self._v_pages, self._last,
            self._lengths, self._tables, active)
        out = np.asarray(nxt).astype(np.int32)
        self._lengths[active] += 1
        self._last[active] = out[active]
        return out

    def release(self, slot: int) -> None:
        """Free the slot's pages back to the pool — the moment a request
        completes, not at a batch boundary."""
        pages = self._owned[slot]
        if pages is None:
            raise ValueError(f"slot {slot} is not occupied")
        self.allocator.free(pages)
        self._owned[slot] = None
        self._tables[slot] = self._trash
        self._lengths[slot] = 0
        self._capacity[slot] = 0

    def utilization(self) -> float:
        """Held fraction of the page pool (the heartbeat's
        ``kvCacheUtilization``)."""
        return self.allocator.utilization()

    def slot_pages(self, slot: int) -> Optional[List[int]]:
        """The slot's owned pages (tests assert reuse invariants)."""
        pages = self._owned[slot]
        return None if pages is None else list(pages)

    def slot_length(self, slot: int) -> int:
        return int(self._lengths[slot])
