"""Distributed linear regression — BASELINE config 2.

The TPU-native counterpart of the reference's ``mxnet-linear-dist`` image
(README.md:66-96): the canonical smallest end-to-end payload. Run as the
``tpu`` container command::

    python -m tpu_operator.payload.linear --steps 200

Exit code follows the operator contract (bootstrap.run_payload): 0 on
convergence, 1 on failure, 143 on preemption.
"""

from __future__ import annotations

import argparse
import logging
import os

from tpu_operator.payload import bootstrap

log = logging.getLogger(__name__)


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch", type=int, default=1024)
    p.add_argument("--dim", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--target-loss", type=float, default=1e-3,
                   help="exit nonzero unless final MSE is below this")
    from tpu_operator.payload import autotune, compute, optimizers

    autotune.add_prefetch_argument(p)
    # Optimizer selection from the shared compute surface (sgd default =
    # the seed path; the model has no blocks/loss to remat or fuse, so
    # the rest of the classifier flag set does not apply here).
    optimizers.add_optimizer_flag(p, choices=compute.CLASSIFIER_OPTIMIZERS,
                                  default="sgd")
    p.add_argument("--profile-dir",
                   default=os.environ.get("TPU_PROFILE_DIR", ""),
                   help="jax.profiler trace dir (default: $TPU_PROFILE_DIR)")
    return p.parse_args(argv)


def run(info: bootstrap.ProcessInfo, args=None) -> float:
    import jax

    from tpu_operator.payload import autotune, compute
    from tpu_operator.payload import data as data_mod
    from tpu_operator.payload import models, train

    args = args or parse_args([])
    mesh = train.make_mesh()
    model = models.LinearRegressor()
    tx = compute.make_optimizer(args, default="sgd")
    sample = jax.numpy.zeros((args.batch, args.dim), jax.numpy.float32)
    state = train.create_train_state(model, jax.random.key(args.seed), sample, tx)
    shardings = train.state_shardings(mesh, state)
    state = train.place_state(mesh, state, shardings)
    step = train.make_regression_train_step(model, tx, mesh, state, shardings)
    # Every process draws the same global stream; put_global_batch shards it
    # over the data axis (per-process slicing in multi-process jobs).
    batches = data_mod.synthetic_linear(args.seed, args.batch, args.dim)
    state, metrics = train.train_loop(
        mesh, step, state, batches, args.steps,
        log_every=max(1, args.steps // 10),
        log_fn=lambda i, m: log.info("step %d loss %.6f", i, m["loss"]),
        profile_dir=args.profile_dir,
        prefetch=autotune.resolve_prefetch_depth(args.prefetch_depth),
    )
    loss = float(metrics["loss"])
    log.info("final loss %.6f over %d devices", loss, len(mesh.devices.flat))
    if loss > args.target_loss:
        raise RuntimeError(f"did not converge: loss {loss} > {args.target_loss}")
    return loss


def main() -> None:
    args = parse_args()
    bootstrap.main_wrapper(lambda info: run(info, args))


if __name__ == "__main__":
    main()
