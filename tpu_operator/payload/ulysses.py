"""Ulysses sequence parallelism: all-to-all head-scatter attention.

The second sequence-parallel strategy next to ring attention
(ring_attention.py) — the DeepSpeed-Ulysses recipe (public technique),
written the XLA way:

- Activations arrive sequence-sharded [B, T/P, H, D] on mesh axis ``seq``.
- One ``lax.all_to_all`` re-shards them head-wise: every device gets the
  *full* sequence for H/P of the heads. Attention then runs entirely
  locally (the fused Pallas flash kernel on TPU), with no per-step
  communication — softmax never crosses devices.
- A second all-to-all restores the sequence-sharded layout for the
  position-local ops around attention.

Trade-off vs ring attention (why both exist): Ulysses does 2 all-to-alls
of the activations total (O(1) latency hops, bandwidth ~B·T·H·D/P per
device) but needs heads % seq_shards == 0 and holds full-T K/V per head
on one device; ring keeps per-device memory strictly O(T/P) at the cost
of P-1 neighbor hops. Long-context jobs pick per workload via
``--sp-mode`` on the transformer payload.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _ulysses_local(q, k, v, axis_name: str, causal: bool, use_pallas: bool):
    """Per-shard body: [B, T/P, H, D] → head-scatter → full attention →
    gather back. Inside shard_map; differentiable (all_to_all transposes to
    the reverse all_to_all)."""
    from tpu_operator.payload import flash_attention as fa

    def scatter_heads(x):
        # [B, T/P, H, D] → [B, T, H/P, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    q, k, v = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    # flash_attention's streaming-softmax jnp path doubles as the non-kernel
    # fallback, so one call serves TPU and CPU.
    out = fa.flash_attention(q, k, v, causal=causal, use_pallas=use_pallas)
    # [B, T, H/P, D] → [B, T/P, H, D]
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, mesh: Mesh, *, seq_axis: str = "seq",
                      batch_axis: Optional[str] = "data",
                      causal: bool = True,
                      use_pallas: Optional[bool] = None):
    """Exact attention over globally [B, T, H, D] arrays whose T dim is
    sharded on ``mesh`` axis ``seq_axis`` — drop-in equal to
    ring_attention.ring_attention (and the dense oracle), different comms
    shape. Requires H divisible by the seq axis size."""
    if use_pallas is None:
        from tpu_operator.payload import flash_attention as fa

        use_pallas = fa.use_pallas_default()
    shards = mesh.shape[seq_axis]
    heads = q.shape[2]
    kv_heads = k.shape[2]
    if heads % shards != 0:
        raise ValueError(
            f"ulysses needs heads ({heads}) divisible by the {seq_axis!r} "
            f"axis size ({shards}); use --sp-mode ring otherwise")
    if kv_heads % shards != 0:
        # GQA: K/V scatter at their own (smaller) head count, so the kv
        # group must also split evenly across the seq shards.
        raise ValueError(
            f"ulysses needs kv_heads ({kv_heads}) divisible by the "
            f"{seq_axis!r} axis size ({shards}); use --sp-mode ring or a "
            f"larger --kv-heads")
    spec = P(batch_axis, seq_axis, None, None)
    body = functools.partial(_ulysses_local, axis_name=seq_axis,
                             causal=causal, use_pallas=use_pallas)
    fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)
