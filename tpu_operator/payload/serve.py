"""Serving payload: continuous-batching incremental decode (``mode: serve``).

``python -m tpu_operator.payload.serve`` — the inference half of the
north star. Where every other payload steps to a finite ``--steps`` and
exits, this one runs a **decode service**:

- **Incremental decode on a paged KV cache** (payload/kvcache.py, the
  default ``--decode-engine paged``): each request's K/V live in
  fixed-size pages of a shared pool, admission runs ONE batched prefill
  over the prompt, and every subsequent step attends one new token per
  slot against the cached span (flash_attention.flash_decode — the GQA
  kernel's cached-decode path). Per-token cost is O(context · kv), not
  O(window · model); ``--decode-engine reforward`` keeps the PR-13
  whole-matrix re-forward as the measured baseline (bench.py --serve
  asserts the A/B).
- **Continuous batching.** Requests admit into the in-flight batch at
  iteration boundaries — slot-level scheduling, no drain-the-batch
  barrier — and a request finishing mid-iteration frees its slot AND its
  cache pages immediately, so a finished short request's pages serve a
  waiting long one on the very next admission.
- **Backpressure.** The ingress queue is depth-bounded (``--max-queue``;
  past it new requests shed) and age-bounded (``--queue-deadline``;
  queued requests older than the deadline shed oldest-first). Shedding
  is visible: ``queueDepth`` and ``kvCacheUtilization`` ride the serving
  heartbeat next to ``tokensPerSecond``.
- **HTTP ingress.** ``--http-port`` (operator-injected as
  ``$TPUJOB_SERVE_PORT``) serves ``POST /v1/decode``
  (``{"prompt": [ints], "maxTokens": n}`` → ``{"tokens": [...]}``) and
  ``GET /healthz`` — the readiness-gated per-replica Services carry real
  request traffic, not just the in-process generator.
- **Synthetic load generator.** ``--load "rps:seconds,…"`` drives
  open-loop arrivals at a piecewise-constant requests/sec schedule; each
  request asks for ``--decode-tokens`` tokens and its latency is
  measured admission-to-completion. Per-window p50/p95/p99, tokens/sec,
  and requests/sec ride the heartbeat's ``serving`` body into
  ``status.serving`` and the ``job_serving_*`` metrics.
- **Readiness protocol.** A replica posts ``ready: true`` only after its
  weights are loaded AND the decode engine compiled; readiness drops
  (an immediate forced beat) for the duration of a weight reload — the
  operator deletes the replica's Service for exactly that window.
- **Hot weight reload.** A watcher thread polls the remote warm-start
  store for a newer VERIFIED snapshot (presence of a committed manifest
  — the PR-8 invariant, so a torn upload can never be "newer"); on
  observation the loop drops readiness at a step boundary, prefetches
  the snapshot into the local checkpoint dir, restores through the PR-4
  verified walk, swaps the params in place, and re-posts ready — no
  process restart, no attempt bump, and NO cache invalidation: the
  engine takes params per call, so live KV pages survive the swap.
  Replicas stagger their reloads by ``--reload-stagger × replicaIndex``
  so the fleet rolls instead of dropping all capacity at once.

Env contract (trainer/replicas.py injects under ``spec.mode: serve``):
``TPUJOB_SERVE`` (the mode flag), ``TPUJOB_SERVE_RELOAD_POLL`` (the
store watch cadence), and ``TPUJOB_SERVE_PORT`` (the per-replica HTTP
ingress port — the same port the replica Service targets). The remote
store rides the ordinary ``TPUJOB_STORE_*`` contract; serve replicas
are READERS — they never attach a write-behind uploader.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpu_operator.payload import bootstrap
from tpu_operator.payload import heartbeat as heartbeat_mod
from tpu_operator.payload import steptrace as steptrace_mod
from tpu_operator.util import lockdep

log = logging.getLogger(__name__)

# Operator env contract (injected when spec.mode is serve).
ENV_SERVE = "TPUJOB_SERVE"
ENV_RELOAD_POLL = "TPUJOB_SERVE_RELOAD_POLL"
ENV_SERVE_PORT = "TPUJOB_SERVE_PORT"

# Idle poll when no request slot is active: the loop must not spin.
IDLE_SLEEP = 0.002

# Consecutive decode failures after which the service gives up: a step
# that fails persistently (bad mesh, poisoned device) would otherwise
# spin the loop forever against requests it can never complete — a
# permanent payload error (exit 1) hands the replica to the operator's
# per-pod restart machinery instead.
MAX_CONSECUTIVE_FAILURES = 8

# Default stagger between replica reloads (× replicaIndex): the fleet
# rolls through a reload instead of dropping every Service at once.
DEFAULT_RELOAD_STAGGER = 0.5

# Cap on the run-level latency record (the bench's SLO summary); beyond
# it percentiles come from the first CAP samples — plenty for a gate.
RUN_LATENCY_CAP = 65536


def parse_args(argv=None):
    from tpu_operator.payload import kvcache

    p = argparse.ArgumentParser()
    p.add_argument("--load", default="5:30",
                   help="requests/sec schedule, 'rps:seconds[,rps:seconds"
                        "...]' — piecewise-constant open-loop arrivals; "
                        "the service exits when the schedule ends "
                        "(0 duration segment = hold forever)")
    p.add_argument("--batch", type=int, default=4,
                   help="decode slots: concurrent requests per step")
    p.add_argument("--decode-tokens", type=int, default=8,
                   help="tokens generated per request")
    p.add_argument("--window", type=int, default=64,
                   help="prompt context window (paged decode grows the "
                        "context past it by up to --decode-tokens)")
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--kv-heads", type=int, default=2,
                   help="grouped-query attention K/V heads (the GQA "
                        "decode path; 0 = MHA)")
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--decode-engine", choices=("paged", "reforward"),
                   default="paged",
                   help="paged = incremental decode on the paged KV cache "
                        "(kvcache.py, O(1) forwards per token); reforward "
                        "= the whole-matrix re-forward baseline the bench "
                        "A/Bs against")
    p.add_argument("--page-size", type=int,
                   default=kvcache.DEFAULT_PAGE_SIZE,
                   help="KV cache page size in tokens (paged engine)")
    p.add_argument("--kv-pages", type=int, default=0,
                   help="KV cache pool size in pages (0 = auto: slots x "
                        "pages-per-request, no admission ever waits on "
                        "pages; smaller oversubscribes the pool and "
                        "admission backpressures on page exhaustion)")
    p.add_argument("--max-queue", type=int, default=256,
                   help="ingress queue depth bound: arrivals past it are "
                        "shed at admission (backpressure, surfaced as "
                        "queueDepth on the heartbeat)")
    p.add_argument("--queue-deadline", type=float, default=30.0,
                   help="seconds a request may wait queued before being "
                        "shed oldest-first (0 = never shed on age)")
    p.add_argument("--http-port", type=int,
                   default=int(os.environ.get(ENV_SERVE_PORT) or 0),
                   help="HTTP ingress port for POST /v1/decode + GET "
                        "/healthz (defaults from the operator-injected "
                        "$TPUJOB_SERVE_PORT; 0 = no HTTP ingress)")
    p.add_argument("--checkpoint-dir", default="",
                   help="weight source (default: $TPU_CHECKPOINT_DIR); "
                        "restored through the verified walk, hot-reloaded "
                        "when the remote store commits a newer snapshot")
    p.add_argument("--reload-poll", type=float,
                   default=float(os.environ.get(ENV_RELOAD_POLL) or 0) or 10.0,
                   help="seconds between remote-store newer-snapshot "
                        "polls (defaults from the operator-injected "
                        "$TPUJOB_SERVE_RELOAD_POLL)")
    p.add_argument("--reload-stagger", type=float,
                   default=DEFAULT_RELOAD_STAGGER,
                   help="seconds × replicaIndex to delay a reload so the "
                        "fleet rolls (0 = reload immediately)")
    return p.parse_args(argv)


# --- load generation ----------------------------------------------------------


class LoadSchedule:
    """Piecewise-constant requests/sec over time: ``[(rps, seconds), …]``.
    A zero-duration final segment holds its rate forever (a real service
    has no natural end; tests and the bench give finite schedules)."""

    def __init__(self, segments: List[Tuple[float, float]]):
        if not segments:
            raise ValueError("load schedule needs at least one segment")
        for rps, seconds in segments:
            if rps < 0 or seconds < 0:
                raise ValueError(
                    f"load segment ({rps}:{seconds}) must be non-negative")
        self.segments = list(segments)

    @classmethod
    def parse(cls, text: str) -> "LoadSchedule":
        segments = []
        for part in str(text).split(","):
            part = part.strip()
            if not part:
                continue
            rate, _, seconds = part.partition(":")
            segments.append((float(rate), float(seconds or 0)))
        return cls(segments)

    def rate_at(self, t: float) -> Optional[float]:
        """Requests/sec at elapsed time ``t``; None = schedule over."""
        at = 0.0
        for rps, seconds in self.segments:
            if seconds <= 0:  # hold forever
                return rps
            if t < at + seconds:
                return rps
            at += seconds
        return None

    def duration(self) -> Optional[float]:
        """Total schedule length, or None for a hold-forever schedule."""
        total = 0.0
        for _rps, seconds in self.segments:
            if seconds <= 0:
                return None
            total += seconds
        return total


class LoadGenerator:
    """Open-loop arrivals at the schedule's rate: deterministic fractional
    accumulation (rate × elapsed), so a 5 rps segment delivers exactly 5
    requests per second of wall time regardless of poll cadence."""

    def __init__(self, schedule: LoadSchedule):
        self.schedule = schedule
        self._t0: Optional[float] = None
        self._last: Optional[float] = None
        self._accum = 0.0
        self.total_arrivals = 0

    def due(self, now: float) -> Optional[int]:
        """Arrivals since the previous call; None once the schedule is
        over (drain what's in flight and exit)."""
        if self._t0 is None:
            self._t0 = self._last = now
            return 0
        rate = self.schedule.rate_at(now - self._t0)
        if rate is None:
            return None
        self._accum += max(0.0, now - self._last) * rate
        self._last = now
        n = int(self._accum)
        self._accum -= n
        self.total_arrivals += n
        return n


class LatencyWindow:
    """Per-request latency + token samples since the last drain (bounded),
    plus arrival accounting — the heartbeat's serving body is built from
    one drain per beat, so each window is disjoint (the steptrace digest
    discipline)."""

    CAP = 4096

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = lockdep.lock("LatencyWindow._lock")
        self._samples: List[float] = []  # guarded-by: _lock
        self._arrivals = 0  # guarded-by: _lock
        self._tokens = 0  # guarded-by: _lock
        self._since = clock()  # guarded-by: _lock

    def arrived(self, n: int = 1) -> None:
        with self._lock:
            self._arrivals += n

    def generated(self, n: int = 1) -> None:
        """Count decoded tokens (the throughput numerator)."""
        with self._lock:
            self._tokens += n

    def record(self, seconds: float) -> None:
        with self._lock:
            if len(self._samples) < self.CAP:
                self._samples.append(float(seconds))

    def drain(self) -> Dict[str, float]:
        """{requestsPerSecond (offered), tokensPerSecond, p50, p95, p99,
        completed} over the window since the previous drain; resets the
        window."""
        now = self._clock()
        with self._lock:
            samples = sorted(self._samples)
            arrivals, since = self._arrivals, self._since
            tokens = self._tokens
            self._samples, self._arrivals, self._tokens = [], 0, 0
            self._since = now
        elapsed = max(1e-9, now - since)
        out: Dict[str, float] = {
            "requestsPerSecond": arrivals / elapsed,
            "tokensPerSecond": tokens / elapsed,
            "completed": float(len(samples)),
        }
        if samples:
            for name, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
                out[name] = samples[min(len(samples) - 1,
                                        int(q * len(samples)))]
        return out


# --- requests -----------------------------------------------------------------


class Request:
    """One decode request: prompt in, up to ``max_tokens`` out. ``done``
    is set on completion OR shed — HTTP ingress threads wait on it; the
    synthetic generator never does. ``tokens`` is written only by the
    decode loop; readers wait for ``done`` first."""

    __slots__ = ("arrived", "prompt", "max_tokens", "tokens", "done", "shed")

    def __init__(self, prompt, max_tokens: int, arrived: float):
        self.arrived = float(arrived)
        self.prompt = prompt
        self.max_tokens = int(max_tokens)
        self.tokens: List[int] = []
        self.done = threading.Event()
        self.shed = False

    def finish(self) -> None:
        self.done.set()

    def shed_now(self) -> None:
        self.shed = True
        self.done.set()


# --- the decode engines -------------------------------------------------------


def build_decode(args, mesh=None):
    """(mesh, model, template_state, decode_fn, token_spec): the decode
    forward — the transformer payload's decoder on the flash-attention
    GQA path — jitted over the whole request matrix. ``template_state``
    is a full TrainState (optimizer state included) so trainer-written
    checkpoints restore through the unchanged verified walk; decode only
    ever reads ``params``. The model's position table spans
    ``window + decode_tokens`` so the paged engine's growing contexts
    have positions (the re-forward baseline only ever uses the first
    ``window`` rows)."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpu_operator.payload import train
    from tpu_operator.payload import transformer

    mesh = mesh or train.make_mesh(axis_names=("data", "model"))
    shim = argparse.Namespace(
        vocab=args.vocab, dim=args.dim, heads=args.heads,
        kv_heads=args.kv_heads, layers=args.layers,
        seq_len=args.window + args.decode_tokens,
        seq_parallel=1, tensor_parallel=1, split_qkv="auto",
        sp_mode="ring", sp_layout="contiguous", remat=False)
    model = transformer._build_model(shim, mesh)
    tx = optax.adam(1e-3)
    sample = jnp.zeros((args.batch, args.window), jnp.int32)
    state = train.create_train_state(model, jax.random.key(args.seed),
                                     sample, tx)
    shardings = train.state_shardings(mesh, state)
    state = train.place_state(mesh, state, shardings)

    from jax.sharding import NamedSharding, PartitionSpec as P

    # The request matrix shards over data only when the slot count
    # divides the axis; tiny batches (or test meshes wider than the
    # batch) replicate — decode correctness never depends on it.
    if args.batch % mesh.shape["data"] == 0:
        token_sharding = NamedSharding(mesh, P("data", None))
    else:
        token_sharding = NamedSharding(mesh, P(None, None))

    def decode(params, tokens):
        logits = model.apply({"params": params}, tokens)
        return jnp.argmax(logits[:, -1, :].astype(jnp.float32), axis=-1)

    decode_fn = jax.jit(decode,
                        in_shardings=(shardings.params, token_sharding),
                        out_shardings=None)
    return mesh, model, state, decode_fn, token_sharding


class ReforwardEngine:
    """The PR-13 baseline: one jitted forward over the whole
    ``[batch, window]`` sliding request matrix per generated token —
    per-token cost O(window · model). Kept selectable so the bench's
    incremental-vs-reforward A/B measures against the real thing."""

    kind = "reforward"

    def __init__(self, args, decode_fn, token_sharding):
        import numpy as np

        self.args = args
        self._np = np
        self._decode_fn = decode_fn
        self._token_sharding = token_sharding
        self._tokens = np.zeros((args.batch, args.window), np.int32)

    def can_admit(self, prompt_len: int, new_tokens: int) -> bool:
        return True

    def admit(self, slot: int, prompt, new_tokens: int,
              params) -> Tuple[bool, Optional[int]]:
        np = self._np
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        row = np.zeros(self.args.window, np.int32)
        row[-len(prompt):] = prompt[-self.args.window:]
        self._tokens[slot] = row
        return True, None  # first token comes from the next step

    def step(self, params, active):
        import jax

        next_tokens = self._decode_fn(
            params, jax.device_put(self._tokens, self._token_sharding))
        out = self._np.asarray(jax.device_get(next_tokens)).astype(
            self._np.int32)
        for slot in self._np.nonzero(self._np.asarray(active, bool))[0]:
            self._tokens[slot, :-1] = self._tokens[slot, 1:]
            self._tokens[slot, -1] = out[slot]
        return out

    def release(self, slot: int) -> None:
        self._tokens[slot] = 0

    def utilization(self) -> float:
        return 0.0

    def warmup(self, params) -> None:
        self.step(params, self._np.zeros(self.args.batch, bool))


class PagedEngine:
    """Incremental decode on the paged KV cache (payload/kvcache.py):
    prefill once at admission, then one-token steps against the cached
    span. The engine takes params per call — hot reload swaps weights
    without touching live pages."""

    kind = "paged"

    def __init__(self, args):
        import numpy as np

        from tpu_operator.payload import kvcache

        self.args = args
        self._np = np
        spec = kvcache.ModelSpec(
            vocab=args.vocab, dim=args.dim, heads=args.heads,
            layers=args.layers, max_seq=args.window + args.decode_tokens,
            kv_heads=args.kv_heads)
        self.cache = kvcache.DecodeEngine(
            spec, slots=args.batch, prompt_pad=args.window,
            max_new=args.decode_tokens, page_size=args.page_size,
            num_pages=args.kv_pages)

    def can_admit(self, prompt_len: int, new_tokens: int) -> bool:
        return self.cache.can_admit(prompt_len, new_tokens)

    def admit(self, slot: int, prompt, new_tokens: int,
              params) -> Tuple[bool, Optional[int]]:
        token = self.cache.admit(slot, prompt, new_tokens, params)
        if token is None:
            return False, None  # page pool exhausted; request stays queued
        return True, token

    def step(self, params, active):
        return self.cache.step(params, active)

    def release(self, slot: int) -> None:
        self.cache.release(slot)

    def utilization(self) -> float:
        return self.cache.utilization()

    def warmup(self, params) -> None:
        """Compile both jitted paths (prefill + step) before readiness,
        through a throwaway request in slot 0."""
        np = self._np
        prompt = np.ones(self.args.window, np.int32)
        self.cache.admit(0, prompt, self.args.decode_tokens, params)
        active = np.zeros(self.args.batch, bool)
        active[0] = self.args.decode_tokens > 1
        self.cache.step(params, active)
        self.cache.release(0)
        # The step's pool outputs can carry a different device layout
        # than the freshly-zeroed pools the first admission compiled
        # against, and XLA compiles a separate executable per input
        # layout — admit once more so the steady-state admit-after-step
        # path is also compiled before the replica reports ready.
        self.cache.admit(0, prompt, self.args.decode_tokens, params)
        self.cache.release(0)


def make_engine(args, decode_fn=None, token_sharding=None):
    """Engine factory for --decode-engine (the bench constructs both)."""
    if args.decode_engine == "reforward":
        return ReforwardEngine(args, decode_fn, token_sharding)
    return PagedEngine(args)


# --- the serve loop -----------------------------------------------------------


class ServeLoop:
    """One replica's decode service: the ingress queue, slot-level
    continuous batching over the decode engine, readiness + reload
    orchestration, and serving heartbeats.

    Single-threaded decode (the step loop owns the params and the
    engine); the reload WATCHER communicates through one flag consumed at
    a step boundary, and HTTP ingress threads touch ONLY the queue (under
    ``_ingress_lock``) and each Request's ``done`` event — the decode
    forward never races a params swap or a table write."""

    def __init__(self, args, info: bootstrap.ProcessInfo,
                 heartbeat: Optional[Any] = "auto",
                 store: Optional[Any] = "auto",
                 recorder: Optional[Any] = "auto",
                 clock: Callable[[], float] = time.monotonic):
        import numpy as np

        self.args = args
        self.info = info
        self._clock = clock
        self._np = np
        if heartbeat == "auto":
            heartbeat = heartbeat_mod.from_env()
        self.heartbeat = heartbeat
        self.recorder = steptrace_mod.from_env() if recorder == "auto" \
            else recorder
        if store == "auto":
            from tpu_operator.payload import warmstore

            store = warmstore.store_from_env() \
                if os.environ.get(ENV_SERVE) else None
        self.store = store
        (self.mesh, self.model, self._state, self._decode_fn,
         self._token_sharding) = build_decode(args)
        self.engine = make_engine(args, self._decode_fn,
                                  self._token_sharding)
        self.window = LatencyWindow(clock=clock)
        self.ready = False
        self.reloads = 0
        self.failed_steps = 0
        self._consecutive_failures = 0
        self.completed = 0
        self.steps = 0
        self.tokens_generated = 0
        # In-flight requests by slot (decode-loop-only) and the ingress
        # queue (shared with HTTP threads).
        self._requests: List[Optional[Request]] = [None] * args.batch
        self._arrival_seq = 0
        self._run_latencies: List[float] = []
        self._ingress_lock = lockdep.lock("ServeLoop._ingress_lock")
        self._queue: List[Request] = []  # guarded-by: _ingress_lock
        self._shed = 0  # guarded-by: _ingress_lock
        # Reload handshake between the decode loop (owner of the params)
        # and the store watcher thread: the loaded step and the pending
        # target share one lock — the watcher compares-and-arms, the loop
        # consumes at a step boundary.
        self._reload_lock = lockdep.lock("ServeLoop._reload_lock")
        self._loaded_step = 0  # guarded-by: _reload_lock
        self._reload_target: Optional[int] = None  # guarded-by: _reload_lock
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        self._http: Optional[Any] = None

    @property
    def loaded_step(self) -> int:
        with self._reload_lock:
            return self._loaded_step

    def _set_loaded_step(self, step: int) -> None:
        with self._reload_lock:
            self._loaded_step = int(step)

    @property
    def shed(self) -> int:
        with self._ingress_lock:
            return self._shed

    def queue_depth(self) -> int:
        with self._ingress_lock:
            return len(self._queue)

    # -- weights ---------------------------------------------------------------

    def _restore_weights(self) -> int:
        """Restore the newest verified checkpoint into the template state
        (params swap; the decode engine takes params per call so no
        recompile and no cache invalidation). Returns the restored step
        (0 = fresh init weights)."""
        from tpu_operator.payload import checkpoint as checkpoint_mod

        directory = self.args.checkpoint_dir \
            or os.environ.get(checkpoint_mod.ENV_VAR, "")
        if not directory:
            return 0
        # A fresh Checkpointer per (re)load: serve replicas are READERS —
        # no uploader, no save-side state worth caching across reloads.
        ck = checkpoint_mod.Checkpointer(directory, save_every=1)
        try:
            state, step = ck.restore(self._state)
        finally:
            ck.close()
        self._state = state
        return int(step)

    def _prefetch_newer(self) -> None:
        """Materialize the newest healthy remote snapshot into the local
        checkpoint dir (where the verified walk finds it). Best-effort:
        a broken store degrades the reload to a no-op, never kills the
        service."""
        if self.store is None:
            return
        from tpu_operator.payload import checkpoint as checkpoint_mod

        directory = self.args.checkpoint_dir \
            or os.environ.get(checkpoint_mod.ENV_VAR, "")
        if not directory:
            return
        try:
            self.store.prefetch_checkpoint(directory)
        except Exception as e:  # noqa: BLE001 — reload is best-effort
            log.warning("serve: snapshot prefetch failed: %s", e)

    # -- readiness + heartbeats ------------------------------------------------

    def serving_wire(self) -> Dict[str, Any]:
        stats = self.window.drain()
        out: Dict[str, Any] = {
            "ready": bool(self.ready),
            "requestsPerSecond": round(stats["requestsPerSecond"], 3),
            "tokensPerSecond": round(stats["tokensPerSecond"], 3),
            "queueDepth": self.queue_depth(),
            "kvCacheUtilization": round(self.engine.utilization(), 4),
            "loadedStep": int(self.loaded_step),
            "reloads": int(self.reloads),
        }
        if "p50" in stats:
            out["p50LatencySeconds"] = round(stats["p50"], 6)
            out["p95LatencySeconds"] = round(stats["p95"], 6)
        return out

    def _post_beat(self, force: bool = False) -> None:
        hb = self.heartbeat
        if hb is None:
            return
        if force or hb.due(self.steps):
            hb.report(self.steps, serving=self.serving_wire(),
                      steptiming=(self.recorder.summary()
                                  if self.recorder is not None else None))

    def _set_ready(self, ready: bool) -> None:
        """Readiness transitions post a FORCED beat: the operator's
        Service gate must learn a reload started NOW, not at the next
        due interval."""
        if self.ready == ready:
            return
        self.ready = ready
        self._post_beat(force=True)

    # -- hot reload ------------------------------------------------------------

    def _watch_store(self) -> None:
        """Watcher thread: a newer VERIFIED remote snapshot (committed
        manifest — the PR-8 invariant) arms the reload flag; the decode
        loop executes it at a step boundary."""
        while not self._stop.wait(max(0.1, float(self.args.reload_poll))):
            try:
                newest = self.store.last_uploaded_step()
            except Exception as e:  # noqa: BLE001 — watch is best-effort
                log.warning("serve: store poll failed: %s", e)
                continue
            if newest is not None and newest > self.loaded_step:
                with self._reload_lock:
                    self._reload_target = int(newest)

    def _maybe_reload(self) -> bool:
        """Step-boundary reload: drop readiness (Service removed),
        stagger, prefetch + verified restore, swap params, re-post
        ready. Returns True when a reload ran. Live KV pages are NOT
        touched — in-flight requests keep decoding against their cached
        context, on the new weights, the moment readiness returns."""
        with self._reload_lock:
            target = self._reload_target
            self._reload_target = None
        if target is None:
            return False
        log.info("serve: newer verified snapshot (step %d > loaded %d); "
                 "rolling reload", target, self.loaded_step)
        self._set_ready(False)
        stagger = float(self.args.reload_stagger) * self.info.replica_index
        if stagger > 0:
            # The roll: replica k waits k×stagger so the fleet never
            # loses every Service at once.
            self._stop.wait(stagger)
        self._prefetch_newer()
        try:
            step = self._restore_weights()
        except Exception:  # noqa: BLE001 — keep serving the old weights
            log.exception("serve: reload restore failed; continuing on "
                          "loaded step %d", self.loaded_step)
            self._set_ready(True)
            return False
        if step > self.loaded_step:
            self._set_loaded_step(step)
            self.reloads += 1
            log.info("serve: weights hot-reloaded at step %d "
                     "(reload %d, no restart)", step, self.reloads)
        self._set_ready(True)
        return True

    # -- ingress ---------------------------------------------------------------

    def submit(self, prompt, max_tokens: int,
               now: Optional[float] = None) -> Optional[Request]:
        """Queue a request (HTTP ingress threads and the synthetic
        generator both land here). Returns None when the queue is at
        ``--max-queue`` — depth-bounded admission, the shed counted and
        the caller answered 503. Offered load (``requestsPerSecond``)
        counts shed arrivals too: the heartbeat must show demand the
        replica turned away."""
        now = self._clock() if now is None else now
        max_tokens = max(1, min(int(max_tokens), self.args.decode_tokens))
        req = Request(prompt, max_tokens, now)
        with self._ingress_lock:
            if len(self._queue) >= self.args.max_queue:
                self._shed += 1
                req = None
            else:
                self._queue.append(req)
        self.window.arrived(1)
        return req

    def _synthetic_request(self, now: float) -> None:
        """One generated arrival: a seeded full-window prompt (request id
        mixed in so batches aren't degenerate) asking for the standard
        budget."""
        np = self._np
        self._arrival_seq += 1
        prompt = (np.arange(self.args.window) + self._arrival_seq) \
            % self.args.vocab
        self.submit(prompt.astype(np.int32), self.args.decode_tokens,
                    now=now)

    def _shed_expired(self, now: float) -> None:
        """Age-bounded queue: requests waiting past --queue-deadline shed
        oldest-first (they would only add latency to everything behind
        them)."""
        deadline = float(self.args.queue_deadline)
        if deadline <= 0:
            return
        expired: List[Request] = []
        with self._ingress_lock:
            keep: List[Request] = []
            for req in self._queue:
                if now - req.arrived > deadline:
                    self._shed += 1
                    expired.append(req)
                else:
                    keep.append(req)
            self._queue[:] = keep
        for req in expired:
            req.shed_now()

    def _admit_from_queue(self) -> None:
        """Iteration-boundary admission: pull queued requests into free
        slots until slots or cache pages run out — which must happen even
        with zero new arrivals, or requests queued during an overload
        burst would starve once the arrival stream pauses. A request the
        cache cannot hold yet goes back to the queue HEAD (it keeps its
        place; a finished request's freed pages admit it next round)."""
        for slot in range(self.args.batch):
            if self._requests[slot] is not None:
                continue
            with self._ingress_lock:
                if not self._queue:
                    return
                req = self._queue.pop(0)
            admitted, token = self.engine.admit(
                slot, req.prompt, req.max_tokens, self._state.params)
            if not admitted:
                with self._ingress_lock:
                    self._queue.insert(0, req)
                return
            self._requests[slot] = req
            if token is not None:
                # The paged prefill emits the first token at admission.
                self._deliver(slot, req, token, self._clock())

    # -- the decode loop -------------------------------------------------------

    def _deliver(self, slot: int, req: Request, token: int,
                 now: float) -> None:
        """Hand one generated token to its request; on completion free
        the slot AND its cache pages immediately — mid-iteration, not at
        a batch boundary — so the next admission can use them."""
        req.tokens.append(int(token))
        self.tokens_generated += 1
        self.window.generated(1)
        if len(req.tokens) >= req.max_tokens:
            latency = now - req.arrived
            self.window.record(latency)
            if len(self._run_latencies) < RUN_LATENCY_CAP:
                self._run_latencies.append(latency)
            self.completed += 1
            self._requests[slot] = None
            self.engine.release(slot)
            req.finish()

    def _active_mask(self):
        return self._np.array([r is not None for r in self._requests],
                              bool)

    def _decode_step(self) -> None:
        rec = self.recorder
        if rec is not None:
            rec.begin(self.steps)
            rec.lap(steptrace_mod.DATA)
        active = self._active_mask()
        try:
            next_tokens = self.engine.step(self._state.params, active)
        except Exception:  # noqa: BLE001 — a failed step must be visible
            self.failed_steps += 1
            self._consecutive_failures += 1
            log.exception("serve: decode step failed")
            if rec is not None:
                rec.abandon()
            if self._consecutive_failures >= MAX_CONSECUTIVE_FAILURES:
                # Persistent failure: this replica can never complete its
                # requests — spinning against them forever would pin a
                # core and hide the breakage. Permanent exit; the per-pod
                # restart path recreates the replica.
                raise RuntimeError(
                    f"serve: {self._consecutive_failures} consecutive "
                    f"decode failures; giving up")
            return
        self._consecutive_failures = 0
        if rec is not None:
            rec.lap(steptrace_mod.COMPUTE)
        now = self._clock()
        for slot in range(self.args.batch):
            req = self._requests[slot]
            if req is None or not active[slot]:
                continue
            self._deliver(slot, req, int(next_tokens[slot]), now)
        if rec is not None:
            rec.lap(steptrace_mod.HOST)
            rec.commit()

    def _warmup(self) -> None:
        """Compile the engine's jitted paths before readiness — a Service
        must never route to a replica that would stall its first request
        on XLA. Failure rides the same consecutive-failure machinery as a
        decode step (a replica whose warm-up failed must not go ready)."""
        try:
            self.engine.warmup(self._state.params)
        except Exception:  # noqa: BLE001 — a failed warm-up must be visible
            self.failed_steps += 1
            self._consecutive_failures += 1
            log.exception("serve: engine warm-up failed")
            return
        self._consecutive_failures = 0

    def _start_http(self) -> None:
        if self.args.http_port <= 0:
            return
        self._http = _make_http_server(self, int(self.args.http_port))
        thread = threading.Thread(target=self._http.serve_forever,
                                  daemon=True, name="serve-http")
        thread.start()
        log.info("serve: HTTP ingress on port %d",
                 self._http.server_address[1])

    def run(self, duration: Optional[float] = None) -> Dict[str, Any]:
        """Serve until the load schedule ends (or ``duration`` caps it);
        returns a summary the bench asserts on."""
        schedule = LoadSchedule.parse(self.args.load)
        gen = LoadGenerator(schedule)
        self._set_loaded_step(self._restore_weights())
        self._warmup()
        self.steps += 1
        self._set_ready(self._consecutive_failures == 0)
        if self.store is not None:
            self._watcher = threading.Thread(target=self._watch_store,
                                             daemon=True,
                                             name="serve-reload-watch")
            self._watcher.start()
        self._start_http()
        t0 = self._clock()
        try:
            while not self._stop.is_set():
                now = self._clock()
                if duration is not None and now - t0 >= duration:
                    break
                arrivals = gen.due(now)
                if (arrivals is None and self.queue_depth() == 0
                        and all(r is None for r in self._requests)):
                    break  # schedule over, queue + in-flight drained
                for _ in range(arrivals or 0):
                    self._synthetic_request(now)
                self._shed_expired(now)
                # Fill slots from the backlog EVERY iteration (not only
                # on new arrivals): a burst queues past the slot count,
                # and the queued requests must drain as slots free even
                # after the arrival stream pauses or ends.
                self._admit_from_queue()
                self._maybe_reload()
                if any(r is not None for r in self._requests):
                    self._decode_step()
                    self.steps += 1
                    if not self.ready and self._consecutive_failures == 0:
                        # A replica whose warm-up (or a transient streak)
                        # failed re-earns readiness on its first
                        # successful decode.
                        self._set_ready(True)
                else:
                    time.sleep(IDLE_SLEEP)
                self._post_beat()
        finally:
            self._stop.set()
            self._set_ready(False)
            # Unblock HTTP waiters: anything still queued or in flight at
            # shutdown is shed, not silently abandoned until its timeout.
            with self._ingress_lock:
                leftover, self._queue[:] = list(self._queue), []
                self._shed += len(leftover)
            for req in leftover:
                req.shed_now()
            for slot in range(self.args.batch):
                req = self._requests[slot]
                if req is not None and not req.done.is_set():
                    req.shed_now()
            if self._http is not None:
                self._http.shutdown()
                self._http.server_close()
            if self._watcher is not None:
                self._watcher.join(timeout=2.0)
        elapsed = max(1e-9, self._clock() - t0)
        summary: Dict[str, Any] = {
            "steps": self.steps,
            "completed": self.completed,
            "arrivals": gen.total_arrivals,
            "failedSteps": self.failed_steps,
            "reloads": self.reloads,
            "loadedStep": self.loaded_step,
            "shed": self.shed,
            "tokensGenerated": self.tokens_generated,
            "elapsedSeconds": elapsed,
            "tokensPerSecond": self.tokens_generated / elapsed,
            "kvCacheUtilization": self.engine.utilization(),
        }
        lat = sorted(self._run_latencies)
        if lat:
            for name, q in (("p50LatencySeconds", 0.50),
                            ("p95LatencySeconds", 0.95),
                            ("p99LatencySeconds", 0.99)):
                summary[name] = lat[min(len(lat) - 1, int(q * len(lat)))]
        return summary

    def stop(self) -> None:
        self._stop.set()


# --- HTTP ingress -------------------------------------------------------------


def _make_http_server(loop: ServeLoop, port: int):
    """ThreadingHTTPServer for the per-replica decode endpoint. Handler
    threads queue through :meth:`ServeLoop.submit` and block on the
    request's ``done`` event — they never touch the engine or the
    params."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code: int, body: Dict[str, Any]) -> None:
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 — http.server API
            if self.path != "/healthz":
                self._reply(404, {"error": "not found"})
                return
            if loop.ready:
                self._reply(200, {"ready": True})
            else:
                self._reply(503, {"ready": False})

        def do_POST(self):  # noqa: N802 — http.server API
            if self.path != "/v1/decode":
                self._reply(404, {"error": "not found"})
                return
            try:
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length) or b"{}")
                prompt = [int(t) for t in body["prompt"]]
                if not 1 <= len(prompt) <= loop.args.window:
                    raise ValueError(
                        f"prompt length {len(prompt)} not in "
                        f"[1, {loop.args.window}]")
                max_tokens = int(body.get("maxTokens",
                                          loop.args.decode_tokens))
            except Exception as e:  # noqa: BLE001 — bad request, not a bug
                self._reply(400, {"error": str(e)})
                return
            req = loop.submit(prompt, max_tokens)
            if req is None:
                self._reply(503, {"error": "queue full"})
                return
            # Generous bound: queueing deadline + decode time; a shed or
            # stopped loop sets done early with shed=True.
            deadline = max(30.0, float(loop.args.queue_deadline) + 30.0)
            if not req.done.wait(timeout=deadline) or req.shed:
                self._reply(503, {"error": "request shed"})
                return
            self._reply(200, {"tokens": req.tokens})

        def log_message(self, fmt, *fmt_args):
            log.debug("serve http: " + fmt, *fmt_args)

    server = ThreadingHTTPServer(("", port), Handler)
    server.daemon_threads = True
    return server


def run(info: bootstrap.ProcessInfo, args=None) -> Dict[str, Any]:
    args = args or parse_args([])
    loop = ServeLoop(args, info)
    summary = loop.run()
    log.info("serve: %d steps, %d/%d requests completed (%d shed), "
             "%.0f tokens/sec, %d reloads, %d failed steps",
             summary["steps"], summary["completed"], summary["arrivals"],
             summary["shed"], summary["tokensPerSecond"],
             summary["reloads"], summary["failedSteps"])
    return summary


def main() -> None:
    """Serve replicas are independent servers: no process group is formed
    (the operator injects JAX_NUM_PROCESSES=1 under mode: serve, so even
    bootstrap.initialize would be a single-process no-op) — the
    run_payload wrapper still owns the exit-code contract: SIGTERM
    (preemption of one replica) exits 143 → the per-pod restart path
    recreates exactly that replica."""
    args = parse_args()
    bootstrap.main_wrapper(lambda info: run(info, args))


if __name__ == "__main__":
    main()
