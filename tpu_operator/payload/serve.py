"""Serving payload: long-lived batched transformer decode (``mode: serve``).

``python -m tpu_operator.payload.serve`` — the inference half of the
north star. Where every other payload steps to a finite ``--steps`` and
exits, this one runs a **decode service**:

- **Batched decode on the GQA path.** The model is the transformer
  payload's decoder (``models.DecoderBlock`` with grouped-query
  attention via ``--kv-heads``); on TPU the attention runs the fused
  Pallas flash-attention kernel, exactly the decode-ready path
  BENCH_SUITE measures. Each decode step is ONE jitted forward over the
  whole ``[batch, window]`` request matrix — every active request slot
  advances one token per step, so throughput scales with batch
  occupancy, not request count.
- **Synthetic load generator.** ``--load "rps:seconds,rps:seconds,…"``
  drives open-loop arrivals at a piecewise-constant requests/sec
  schedule; each request asks for ``--decode-tokens`` tokens and its
  latency is measured admission-to-completion. Per-window p50/p95 and
  requests/sec ride the heartbeat's ``serving`` body into
  ``status.serving`` and the ``job_serving_*`` metrics.
- **Readiness protocol.** A replica posts ``ready: true`` only after its
  weights are loaded AND the first decode step compiled; readiness drops
  (an immediate forced beat) for the duration of a weight reload — the
  operator deletes the replica's Service for exactly that window.
- **Hot weight reload.** A watcher thread polls the remote warm-start
  store for a newer VERIFIED snapshot (presence of a committed manifest
  — the PR-8 invariant, so a torn upload can never be "newer"); on
  observation the loop drops readiness at a step boundary, prefetches
  the snapshot into the local checkpoint dir, restores through the PR-4
  verified walk, swaps the params in place, and re-posts ready — no
  process restart, no attempt bump. Replicas stagger their reloads by
  ``--reload-stagger × replicaIndex`` so the fleet rolls instead of
  dropping all capacity at once.

Env contract (trainer/replicas.py injects under ``spec.mode: serve``):
``TPUJOB_SERVE`` (the mode flag) and ``TPUJOB_SERVE_RELOAD_POLL`` (the
store watch cadence). The remote store rides the ordinary
``TPUJOB_STORE_*`` contract; serve replicas are READERS — they never
attach a write-behind uploader.
"""

from __future__ import annotations

import argparse
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpu_operator.payload import bootstrap
from tpu_operator.payload import heartbeat as heartbeat_mod
from tpu_operator.payload import steptrace as steptrace_mod
from tpu_operator.util import lockdep

log = logging.getLogger(__name__)

# Operator env contract (injected when spec.mode is serve).
ENV_SERVE = "TPUJOB_SERVE"
ENV_RELOAD_POLL = "TPUJOB_SERVE_RELOAD_POLL"

# Idle poll when no request slot is active: the loop must not spin.
IDLE_SLEEP = 0.002

# Consecutive decode failures after which the service gives up: a step
# that fails persistently (bad mesh, poisoned device) would otherwise
# spin the loop forever against requests it can never complete — a
# permanent payload error (exit 1) hands the replica to the operator's
# per-pod restart machinery instead.
MAX_CONSECUTIVE_FAILURES = 8

# Default stagger between replica reloads (× replicaIndex): the fleet
# rolls through a reload instead of dropping every Service at once.
DEFAULT_RELOAD_STAGGER = 0.5


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--load", default="5:30",
                   help="requests/sec schedule, 'rps:seconds[,rps:seconds"
                        "...]' — piecewise-constant open-loop arrivals; "
                        "the service exits when the schedule ends "
                        "(0 duration segment = hold forever)")
    p.add_argument("--batch", type=int, default=4,
                   help="decode slots: concurrent requests per step")
    p.add_argument("--decode-tokens", type=int, default=8,
                   help="tokens generated per request")
    p.add_argument("--window", type=int, default=64,
                   help="context window the decode forward runs over")
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--kv-heads", type=int, default=2,
                   help="grouped-query attention K/V heads (the GQA "
                        "decode path; 0 = MHA)")
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--checkpoint-dir", default="",
                   help="weight source (default: $TPU_CHECKPOINT_DIR); "
                        "restored through the verified walk, hot-reloaded "
                        "when the remote store commits a newer snapshot")
    p.add_argument("--reload-poll", type=float,
                   default=float(os.environ.get(ENV_RELOAD_POLL) or 0) or 10.0,
                   help="seconds between remote-store newer-snapshot "
                        "polls (defaults from the operator-injected "
                        "$TPUJOB_SERVE_RELOAD_POLL)")
    p.add_argument("--reload-stagger", type=float,
                   default=DEFAULT_RELOAD_STAGGER,
                   help="seconds × replicaIndex to delay a reload so the "
                        "fleet rolls (0 = reload immediately)")
    return p.parse_args(argv)


# --- load generation ----------------------------------------------------------


class LoadSchedule:
    """Piecewise-constant requests/sec over time: ``[(rps, seconds), …]``.
    A zero-duration final segment holds its rate forever (a real service
    has no natural end; tests and the bench give finite schedules)."""

    def __init__(self, segments: List[Tuple[float, float]]):
        if not segments:
            raise ValueError("load schedule needs at least one segment")
        for rps, seconds in segments:
            if rps < 0 or seconds < 0:
                raise ValueError(
                    f"load segment ({rps}:{seconds}) must be non-negative")
        self.segments = list(segments)

    @classmethod
    def parse(cls, text: str) -> "LoadSchedule":
        segments = []
        for part in str(text).split(","):
            part = part.strip()
            if not part:
                continue
            rate, _, seconds = part.partition(":")
            segments.append((float(rate), float(seconds or 0)))
        return cls(segments)

    def rate_at(self, t: float) -> Optional[float]:
        """Requests/sec at elapsed time ``t``; None = schedule over."""
        at = 0.0
        for rps, seconds in self.segments:
            if seconds <= 0:  # hold forever
                return rps
            if t < at + seconds:
                return rps
            at += seconds
        return None

    def duration(self) -> Optional[float]:
        """Total schedule length, or None for a hold-forever schedule."""
        total = 0.0
        for _rps, seconds in self.segments:
            if seconds <= 0:
                return None
            total += seconds
        return total


class LoadGenerator:
    """Open-loop arrivals at the schedule's rate: deterministic fractional
    accumulation (rate × elapsed), so a 5 rps segment delivers exactly 5
    requests per second of wall time regardless of poll cadence."""

    def __init__(self, schedule: LoadSchedule):
        self.schedule = schedule
        self._t0: Optional[float] = None
        self._last: Optional[float] = None
        self._accum = 0.0
        self.total_arrivals = 0

    def due(self, now: float) -> Optional[int]:
        """Arrivals since the previous call; None once the schedule is
        over (drain what's in flight and exit)."""
        if self._t0 is None:
            self._t0 = self._last = now
            return 0
        rate = self.schedule.rate_at(now - self._t0)
        if rate is None:
            return None
        self._accum += max(0.0, now - self._last) * rate
        self._last = now
        n = int(self._accum)
        self._accum -= n
        self.total_arrivals += n
        return n


class LatencyWindow:
    """Per-request latency samples since the last drain (bounded), plus
    arrival accounting — the heartbeat's serving body is built from one
    drain per beat, so each window is disjoint (the steptrace digest
    discipline)."""

    CAP = 4096

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = lockdep.lock("LatencyWindow._lock")
        self._samples: List[float] = []  # guarded-by: _lock
        self._arrivals = 0  # guarded-by: _lock
        self._since = clock()  # guarded-by: _lock

    def arrived(self, n: int = 1) -> None:
        with self._lock:
            self._arrivals += n

    def record(self, seconds: float) -> None:
        with self._lock:
            if len(self._samples) < self.CAP:
                self._samples.append(float(seconds))

    def drain(self) -> Dict[str, float]:
        """{requestsPerSecond (offered), p50, p95, completed} over the
        window since the previous drain; resets the window."""
        now = self._clock()
        with self._lock:
            samples = sorted(self._samples)
            arrivals, since = self._arrivals, self._since
            self._samples, self._arrivals, self._since = [], 0, now
        elapsed = max(1e-9, now - since)
        out: Dict[str, float] = {
            "requestsPerSecond": arrivals / elapsed,
            "completed": float(len(samples)),
        }
        if samples:
            out["p50"] = samples[min(len(samples) - 1,
                                     int(0.50 * len(samples)))]
            out["p95"] = samples[min(len(samples) - 1,
                                     int(0.95 * len(samples)))]
        return out


# --- the decode engine --------------------------------------------------------


def build_decode(args, mesh=None):
    """(mesh, model, template_state, decode_fn, token_spec): the decode
    forward — the transformer payload's decoder on the flash-attention
    GQA path — jitted over the whole request matrix. ``template_state``
    is a full TrainState (optimizer state included) so trainer-written
    checkpoints restore through the unchanged verified walk; decode only
    ever reads ``params``."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpu_operator.payload import train
    from tpu_operator.payload import transformer

    mesh = mesh or train.make_mesh(axis_names=("data", "model"))
    shim = argparse.Namespace(
        vocab=args.vocab, dim=args.dim, heads=args.heads,
        kv_heads=args.kv_heads, layers=args.layers, seq_len=args.window,
        seq_parallel=1, tensor_parallel=1, split_qkv="auto",
        sp_mode="ring", sp_layout="contiguous", remat=False)
    model = transformer._build_model(shim, mesh)
    tx = optax.adam(1e-3)
    sample = jnp.zeros((args.batch, args.window), jnp.int32)
    state = train.create_train_state(model, jax.random.key(args.seed),
                                     sample, tx)
    shardings = train.state_shardings(mesh, state)
    state = train.place_state(mesh, state, shardings)

    from jax.sharding import NamedSharding, PartitionSpec as P

    # The request matrix shards over data only when the slot count
    # divides the axis; tiny batches (or test meshes wider than the
    # batch) replicate — decode correctness never depends on it.
    if args.batch % mesh.shape["data"] == 0:
        token_sharding = NamedSharding(mesh, P("data", None))
    else:
        token_sharding = NamedSharding(mesh, P(None, None))

    def decode(params, tokens):
        logits = model.apply({"params": params}, tokens)
        return jnp.argmax(logits[:, -1, :].astype(jnp.float32), axis=-1)

    decode_fn = jax.jit(decode,
                        in_shardings=(shardings.params, token_sharding),
                        out_shardings=None)
    return mesh, model, state, decode_fn, token_sharding


class ServeLoop:
    """One replica's decode service: request slots, the load generator,
    readiness + reload orchestration, and serving heartbeats.

    Single-threaded decode (the step loop owns the params); the reload
    WATCHER is the only other thread and it communicates through one
    flag — the loop performs the actual reload at a step boundary, so
    the decode forward never races a params swap."""

    def __init__(self, args, info: bootstrap.ProcessInfo,
                 heartbeat: Optional[Any] = "auto",
                 store: Optional[Any] = "auto",
                 recorder: Optional[Any] = "auto",
                 clock: Callable[[], float] = time.monotonic):
        import numpy as np

        self.args = args
        self.info = info
        self._clock = clock
        self._np = np
        if heartbeat == "auto":
            heartbeat = heartbeat_mod.from_env()
        self.heartbeat = heartbeat
        self.recorder = steptrace_mod.from_env() if recorder == "auto" \
            else recorder
        if store == "auto":
            from tpu_operator.payload import warmstore

            store = warmstore.store_from_env() \
                if os.environ.get(ENV_SERVE) else None
        self.store = store
        (self.mesh, self.model, self._state, self._decode,
         self._token_sharding) = build_decode(args)
        self.window = LatencyWindow(clock=clock)
        self.ready = False
        self.reloads = 0
        self.failed_steps = 0
        self._consecutive_failures = 0
        self.completed = 0
        self.steps = 0
        # Request slots: remaining-token budget (<=0 idle) + arrival time.
        self._budget = [0] * args.batch
        self._arrived = [0.0] * args.batch
        self._queue: List[float] = []  # arrival times awaiting a slot
        self._tokens = np.zeros((args.batch, args.window), np.int32)
        # Reload handshake between the decode loop (owner of the params)
        # and the store watcher thread: the loaded step and the pending
        # target share one lock — the watcher compares-and-arms, the loop
        # consumes at a step boundary.
        self._reload_lock = lockdep.lock("ServeLoop._reload_lock")
        self._loaded_step = 0  # guarded-by: _reload_lock
        self._reload_target: Optional[int] = None  # guarded-by: _reload_lock
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None

    @property
    def loaded_step(self) -> int:
        with self._reload_lock:
            return self._loaded_step

    def _set_loaded_step(self, step: int) -> None:
        with self._reload_lock:
            self._loaded_step = int(step)

    # -- weights ---------------------------------------------------------------

    def _restore_weights(self) -> int:
        """Restore the newest verified checkpoint into the template state
        (params swap; the decode fn takes params per call so no
        recompile). Returns the restored step (0 = fresh init weights)."""
        from tpu_operator.payload import checkpoint as checkpoint_mod

        directory = self.args.checkpoint_dir \
            or os.environ.get(checkpoint_mod.ENV_VAR, "")
        if not directory:
            return 0
        # A fresh Checkpointer per (re)load: serve replicas are READERS —
        # no uploader, no save-side state worth caching across reloads.
        ck = checkpoint_mod.Checkpointer(directory, save_every=1)
        try:
            state, step = ck.restore(self._state)
        finally:
            ck.close()
        self._state = state
        return int(step)

    def _prefetch_newer(self) -> None:
        """Materialize the newest healthy remote snapshot into the local
        checkpoint dir (where the verified walk finds it). Best-effort:
        a broken store degrades the reload to a no-op, never kills the
        service."""
        if self.store is None:
            return
        from tpu_operator.payload import checkpoint as checkpoint_mod

        directory = self.args.checkpoint_dir \
            or os.environ.get(checkpoint_mod.ENV_VAR, "")
        if not directory:
            return
        try:
            self.store.prefetch_checkpoint(directory)
        except Exception as e:  # noqa: BLE001 — reload is best-effort
            log.warning("serve: snapshot prefetch failed: %s", e)

    # -- readiness + heartbeats ------------------------------------------------

    def serving_wire(self) -> Dict[str, Any]:
        stats = self.window.drain()
        out: Dict[str, Any] = {
            "ready": bool(self.ready),
            "requestsPerSecond": round(stats["requestsPerSecond"], 3),
            "loadedStep": int(self.loaded_step),
            "reloads": int(self.reloads),
        }
        if "p50" in stats:
            out["p50LatencySeconds"] = round(stats["p50"], 6)
            out["p95LatencySeconds"] = round(stats["p95"], 6)
        return out

    def _post_beat(self, force: bool = False) -> None:
        hb = self.heartbeat
        if hb is None:
            return
        if force or hb.due(self.steps):
            hb.report(self.steps, serving=self.serving_wire(),
                      steptiming=(self.recorder.summary()
                                  if self.recorder is not None else None))

    def _set_ready(self, ready: bool) -> None:
        """Readiness transitions post a FORCED beat: the operator's
        Service gate must learn a reload started NOW, not at the next
        due interval."""
        if self.ready == ready:
            return
        self.ready = ready
        self._post_beat(force=True)

    # -- hot reload ------------------------------------------------------------

    def _watch_store(self) -> None:
        """Watcher thread: a newer VERIFIED remote snapshot (committed
        manifest — the PR-8 invariant) arms the reload flag; the decode
        loop executes it at a step boundary."""
        while not self._stop.wait(max(0.1, float(self.args.reload_poll))):
            try:
                newest = self.store.last_uploaded_step()
            except Exception as e:  # noqa: BLE001 — watch is best-effort
                log.warning("serve: store poll failed: %s", e)
                continue
            if newest is not None and newest > self.loaded_step:
                with self._reload_lock:
                    self._reload_target = int(newest)

    def _maybe_reload(self) -> bool:
        """Step-boundary reload: drop readiness (Service removed),
        stagger, prefetch + verified restore, swap params, re-post
        ready. Returns True when a reload ran."""
        with self._reload_lock:
            target = self._reload_target
            self._reload_target = None
        if target is None:
            return False
        log.info("serve: newer verified snapshot (step %d > loaded %d); "
                 "rolling reload", target, self.loaded_step)
        self._set_ready(False)
        stagger = float(self.args.reload_stagger) * self.info.replica_index
        if stagger > 0:
            # The roll: replica k waits k×stagger so the fleet never
            # loses every Service at once.
            self._stop.wait(stagger)
        self._prefetch_newer()
        try:
            step = self._restore_weights()
        except Exception:  # noqa: BLE001 — keep serving the old weights
            log.exception("serve: reload restore failed; continuing on "
                          "loaded step %d", self.loaded_step)
            self._set_ready(True)
            return False
        if step > self.loaded_step:
            self._set_loaded_step(step)
            self.reloads += 1
            log.info("serve: weights hot-reloaded at step %d "
                     "(reload %d, no restart)", step, self.reloads)
        self._set_ready(True)
        return True

    # -- the decode loop -------------------------------------------------------

    def _admit(self, n: int, now: float) -> None:
        """Enqueue ``n`` new arrivals, then fill free slots from the
        BACKLOG — which must happen even with zero new arrivals, or
        requests queued during an overload burst would starve once the
        arrival stream pauses (slots free up, nothing pulls the queue)."""
        if n:
            self.window.arrived(n)
            self._queue.extend([now] * n)
        for slot in range(self.args.batch):
            if not self._queue:
                return
            if self._budget[slot] <= 0:
                self._arrived[slot] = self._queue.pop(0)
                self._budget[slot] = int(self.args.decode_tokens)
                # A fresh request gets a seeded context (request id mixed
                # in so batches aren't degenerate); a real service would
                # place the prompt here.
                self._tokens[slot] = (self._np.arange(self.args.window)
                                      + self.steps + slot) % self.args.vocab

    def _decode_step(self) -> None:
        import jax

        rec = self.recorder
        if rec is not None:
            rec.begin(self.steps)
            rec.lap(steptrace_mod.DATA)
        try:
            next_tokens = self._decode(self._state.params,
                                       jax.device_put(
                                           self._tokens,
                                           self._token_sharding))
            next_tokens = self._np.asarray(
                jax.device_get(next_tokens)).astype(self._np.int32)
        except Exception:  # noqa: BLE001 — a failed step must be visible
            self.failed_steps += 1
            self._consecutive_failures += 1
            log.exception("serve: decode step failed")
            if rec is not None:
                rec.abandon()
            if self._consecutive_failures >= MAX_CONSECUTIVE_FAILURES:
                # Persistent failure: this replica can never complete its
                # requests — spinning against them forever would pin a
                # core and hide the breakage. Permanent exit; the per-pod
                # restart path recreates the replica.
                raise RuntimeError(
                    f"serve: {self._consecutive_failures} consecutive "
                    f"decode failures; giving up")
            return
        self._consecutive_failures = 0
        if rec is not None:
            rec.lap(steptrace_mod.COMPUTE)
        now = self._clock()
        for slot in range(self.args.batch):
            if self._budget[slot] <= 0:
                continue
            self._tokens[slot, :-1] = self._tokens[slot, 1:]
            self._tokens[slot, -1] = next_tokens[slot]
            self._budget[slot] -= 1
            if self._budget[slot] <= 0:
                self.completed += 1
                self.window.record(now - self._arrived[slot])
        if rec is not None:
            rec.lap(steptrace_mod.HOST)
            rec.commit()

    def run(self, duration: Optional[float] = None) -> Dict[str, Any]:
        """Serve until the load schedule ends (or ``duration`` caps it);
        returns a summary the bench asserts on."""
        schedule = LoadSchedule.parse(self.args.load)
        gen = LoadGenerator(schedule)
        self._set_loaded_step(self._restore_weights())
        # First decode compiled BEFORE readiness: a Service must never
        # route to a replica that would stall its first request on XLA —
        # and a replica whose warm-up step FAILED must not go ready
        # either (the loop below re-earns readiness on its first
        # successful decode instead of blackholing routed requests).
        self._decode_step()
        self.steps += 1
        self._set_ready(self._consecutive_failures == 0)
        if self.store is not None:
            self._watcher = threading.Thread(target=self._watch_store,
                                             daemon=True,
                                             name="serve-reload-watch")
            self._watcher.start()
        t0 = self._clock()
        try:
            while not self._stop.is_set():
                now = self._clock()
                if duration is not None and now - t0 >= duration:
                    break
                arrivals = gen.due(now)
                if (arrivals is None and not self._queue
                        and not any(b > 0 for b in self._budget)):
                    break  # schedule over, queue + in-flight drained
                # Fill slots from the backlog EVERY iteration (not only
                # on new arrivals): a burst queues past the slot count,
                # and the queued requests must drain as slots free even
                # after the arrival stream pauses or ends.
                self._admit(arrivals or 0, now)
                self._maybe_reload()
                if any(b > 0 for b in self._budget):
                    self._decode_step()
                    self.steps += 1
                    if not self.ready and self._consecutive_failures == 0:
                        # A replica whose warm-up (or a transient streak)
                        # failed re-earns readiness on its first
                        # successful decode.
                        self._set_ready(True)
                else:
                    time.sleep(IDLE_SLEEP)
                self._post_beat()
        finally:
            self._stop.set()
            self._set_ready(False)
            if self._watcher is not None:
                self._watcher.join(timeout=2.0)
        return {
            "steps": self.steps,
            "completed": self.completed,
            "arrivals": gen.total_arrivals,
            "failedSteps": self.failed_steps,
            "reloads": self.reloads,
            "loadedStep": self.loaded_step,
        }

    def stop(self) -> None:
        self._stop.set()


def run(info: bootstrap.ProcessInfo, args=None) -> Dict[str, Any]:
    args = args or parse_args([])
    loop = ServeLoop(args, info)
    summary = loop.run()
    log.info("serve: %d steps, %d/%d requests completed, %d reloads, "
             "%d failed steps", summary["steps"], summary["completed"],
             summary["arrivals"], summary["reloads"],
             summary["failedSteps"])
    return summary


def main() -> None:
    """Serve replicas are independent servers: no process group is formed
    (the operator injects JAX_NUM_PROCESSES=1 under mode: serve, so even
    bootstrap.initialize would be a single-process no-op) — the
    run_payload wrapper still owns the exit-code contract: SIGTERM
    (preemption of one replica) exits 143 → the per-pod restart path
    recreates exactly that replica."""
    args = parse_args()
    bootstrap.main_wrapper(lambda info: run(info, args))


if __name__ == "__main__":
    main()
