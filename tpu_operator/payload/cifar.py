"""Data-parallel CIFAR-10 ResNet — BASELINE config 3, the flagship payload.

The TPU-native counterpart of the reference's ``mxnet-cifar10-dist`` GPU
image (README.md:126-167): ResNet-20 (He et al. CIFAR variant) trained
data-parallel over every chip in the job's mesh, bf16 on the MXU, gradients
reduced over ICI by GSPMD. Run as the ``tpu`` container command::

    python -m tpu_operator.payload.cifar --steps 500 --batch 1024

``--model-parallel N`` additionally shards the head/wide convs over a
``model`` mesh axis (tensor parallelism) — not part of the reference's
capability set, but free under the same one-jit design.
"""

from __future__ import annotations

import argparse
import logging
import os

from tpu_operator.payload import bootstrap

log = logging.getLogger(__name__)


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=500)
    p.add_argument("--batch", type=int, default=1024, help="global batch size")
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--blocks", type=int, default=3,
                   help="blocks per stage (3 → ResNet-20)")
    p.add_argument("--widths", type=int, nargs=3, default=(16, 32, 64))
    p.add_argument("--model-parallel", type=int, default=1)
    p.add_argument("--data", default=os.environ.get("TPU_DATA_PATH", ""),
                   help=".npz dataset (images [N,32,32,3], labels [N]) "
                        "on a mounted volume; default synthetic "
                        "(or $TPU_DATA_PATH)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=50)
    p.add_argument("--checkpoint-dir", default="",
                   help="checkpoint/resume dir (default: $TPU_CHECKPOINT_DIR "
                        "as injected by the operator when spec.checkpointDir "
                        "is set)")
    p.add_argument("--checkpoint-every", type=int, default=100)
    p.add_argument("--profile-dir",
                   default=os.environ.get("TPU_PROFILE_DIR", ""),
                   help="jax.profiler trace dir (default: $TPU_PROFILE_DIR)")
    from tpu_operator.payload import autotune, compute

    autotune.add_prefetch_argument(p)
    # The shared compute lineage (payload/compute.py): --remat-policy,
    # --optimizer sgd|adam|adam8, --fused-loss, --scan-blocks, --aot.
    # Defaults reproduce the seed path (sgd + momentum, plain loss, no
    # remat); bench.py --flagship A/B-gates each option individually.
    compute.add_classifier_compute_flags(p)
    return p.parse_args(argv)


def build(args, mesh=None, num_slices: int = 1):
    """(mesh, model, state, train_step, batches) for the given config."""
    import jax
    import jax.numpy as jnp

    from tpu_operator.payload import compute
    from tpu_operator.payload import data as data_mod
    from tpu_operator.payload import models, train

    mesh = mesh or train.make_mesh(model_parallel=args.model_parallel,
                                   num_slices=num_slices)
    model = models.CifarResNet(blocks_per_stage=args.blocks,
                               widths=tuple(args.widths),
                               scan_blocks=getattr(args, "scan_blocks", False))
    tx = compute.make_optimizer(args, default="sgd")
    sample = jnp.zeros((args.batch, *data_mod.CIFAR_SHAPE), jnp.float32)
    state = train.create_train_state(model, jax.random.key(args.seed), sample, tx)
    shardings = train.state_shardings(mesh, state)
    state = train.place_state(mesh, state, shardings)
    step = train.make_classifier_train_step(
        model, tx, mesh, state, shardings,
        **compute.classifier_step_options(args))
    if getattr(args, "data", ""):
        batches = data_mod.npz_classification(
            args.data, args.seed, args.batch,
            num_classes=model.num_classes,
            image_shape=data_mod.CIFAR_SHAPE)
    else:
        batches = data_mod.synthetic_cifar(args.seed, args.batch)
    return mesh, model, state, step, batches


def run(info: bootstrap.ProcessInfo, args=None) -> dict:
    from tpu_operator.payload import autotune, checkpoint, train

    args = args or parse_args([])
    mesh, _model, state, step, batches = build(
        args, num_slices=info.num_slices)
    log.info("mesh: %s over %d devices; global batch %d",
             dict(zip(mesh.axis_names, mesh.devices.shape)),
             mesh.devices.size, args.batch)
    ckpt = checkpoint.from_env_or_args(args.checkpoint_dir,
                                       save_every=args.checkpoint_every)
    if ckpt is not None and ckpt.latest_step() is not None:
        log.info("attempt %d: resuming from %s (latest step: %d)",
                 info.attempt, ckpt.directory, ckpt.latest_step())
    try:
        state, metrics = train.train_loop(
            mesh, step, state, batches, args.steps,
            log_every=args.log_every,
            log_fn=lambda i, m: log.info(
                "step %d loss %.4f acc %.3f", i, m["loss"], m["accuracy"]),
            checkpointer=ckpt,
            profile_dir=args.profile_dir,
            prefetch=autotune.resolve_prefetch_depth(args.prefetch_depth),
        )
    finally:
        if ckpt is not None:
            ckpt.close()
    log.info("final: loss %.4f accuracy %.3f",
             metrics.get("loss", float("nan")),
             metrics.get("accuracy", float("nan")))
    return metrics


def main() -> None:
    args = parse_args()
    bootstrap.main_wrapper(lambda info: run(info, args))


if __name__ == "__main__":
    main()
