"""The sharded training loop: mesh construction, state, jitted train step.

TPU-first by construction (the design constraints the reference never had,
because its compute lived in user images):

- **One jit, global semantics.** The train step is a single ``jax.jit`` over
  global arrays with NamedSharding constraints; XLA/GSPMD inserts every
  collective (gradient psums over ``data``, TP collectives over ``model``).
  No hand-written pmap/allreduce anywhere.
- **Mesh = (data, model).** DP shards the batch over ``data``; optional TP
  shards wide params over ``model`` via models.param_partition_spec. A
  WORKER-replica job maps each process's local devices into one global mesh.
- **MXU-friendly numerics**: bf16 activations/weights-on-the-fly, f32 master
  params, f32 loss/optimizer state.
- **Donated state**: the train step donates its input state, so params and
  optimizer state update in place in HBM (no double-buffering spike).
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from typing import Any, Callable, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_operator.payload import autotune as autotune_mod
from tpu_operator.payload import bootstrap as bootstrap_mod
from tpu_operator.payload import data as data_mod
from tpu_operator.payload import heartbeat as heartbeat_mod
from tpu_operator.payload import models as models_mod
from tpu_operator.payload import profile as profile_mod
from tpu_operator.payload import startup as startup_mod
from tpu_operator.payload import steptrace as steptrace_mod

log = logging.getLogger(__name__)


class TrainState(flax.struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    batch_stats: Any
    opt_state: Any


def _mesh_device_layout(num_devices, devices, inner, inner_label,
                        num_slices):
    """Shared device selection for the mesh builders: slice, validate that
    the inner (per-op-collective) extent divides the device count — and
    fits within one slice for multi-slice jobs — and sort slice-major when
    the runtime exposes ``slice_index``."""
    devices = list(devices if devices is not None else jax.devices())
    if num_devices:
        devices = devices[:num_devices]
    n = len(devices)
    if n % inner != 0:
        raise ValueError(f"{n} devices not divisible by {inner_label}={inner}")
    if num_slices > 1:
        if n % num_slices != 0:
            raise ValueError(
                f"{n} devices not divisible by num_slices={num_slices}")
        per_slice = n // num_slices
        if per_slice % inner != 0:
            raise ValueError(
                f"{inner_label}={inner} does not fit within one slice "
                f"({per_slice} devices): inner-axis collectives must "
                f"stay on ICI")
        if all(hasattr(d, "slice_index") for d in devices):
            devices = sorted(devices, key=lambda d: (d.slice_index, d.id))
    return devices


def _guard_intra_slice(arr, num_slices, inner_label):
    """Every inner-axes block (arr row, flattened) must sit within one
    slice: a block silently spanning slices would put per-op collectives on
    DCN — the exact failure hybrid meshes exist to prevent. Only checkable
    when devices expose ``slice_index``."""
    flat_blocks = arr.reshape(arr.shape[0], -1)
    if num_slices > 1 and all(hasattr(d, "slice_index")
                              for d in flat_blocks.flat):
        for block in flat_blocks:
            if len({d.slice_index for d in block}) != 1:
                raise ValueError(
                    f"inner axes ({inner_label}) cross a slice boundary "
                    f"(num_slices={num_slices} vs device slice_index "
                    f"layout); per-op collectives must stay on ICI")


def make_mesh(num_devices: Optional[int] = None, model_parallel: int = 1,
              devices: Optional[list] = None,
              axis_names: Tuple[str, str] = ("data", "model"),
              num_slices: int = 1) -> Mesh:
    """Build a 2-axis mesh over the visible devices (default (data, model);
    the transformer payload reuses this with ("data", "seq")). On a real pod
    slice ``jax.devices()`` spans every process after
    jax.distributed.initialize; the mesh is global.

    ``num_slices > 1`` (multi-slice jobs, MEGASCALE_NUM_SLICES from the
    operator's env contract) makes the mesh DCN-aware: devices are grouped
    slice-major and the inner axis (model/seq/pipe/expert) is required to
    fit within one slice, so its collectives — the latency-sensitive ones,
    issued per matmul/attention/dispatch — ride ICI only, while the outer
    ``data`` axis spans slices and its once-per-step gradient psum is the
    only traffic that crosses DCN. This is the standard hybrid ICI×DCN
    sharding recipe; the slice boundary comes from each device's
    ``slice_index`` when the runtime exposes one (devices are sorted by it),
    else from the given device order (processes are already slice-major in
    the operator's TPU_WORKER_HOSTNAMES ordering)."""
    devices = _mesh_device_layout(num_devices, devices, model_parallel,
                                  axis_names[1], num_slices)
    n = len(devices)
    arr = np.array(devices).reshape(n // model_parallel, model_parallel)
    _guard_intra_slice(arr, num_slices, axis_names[1])
    return Mesh(arr, axis_names)


def quantized_aware(mesh: Mesh,
                    rule: Callable[[Tuple[str, ...], Any], P]
                    ) -> Callable[[Tuple[str, ...], Any], P]:
    """Wrap a path rule so int8 block-quantized adam moments
    (optimizers.Quantized: ``q`` [..., nb, BLOCK] / ``scale`` [..., nb]
    under the parameter's own path) shard like their parameter. The
    NamedTuple hop appends a ``.q``/``.scale`` path key and changes the
    rank, so name/rank-keyed rules (MoE expert sharding, Megatron TP,
    pipeline stage stacking) would silently fall through to replicate —
    at flagship MoE scale that forfeits the E-fold moment sharding the
    8-bit optimizer exists to afford. The wrapper asks the rule about a
    parameter-shaped proxy (same path minus the NamedTuple key, last dim
    the padded block span), then maps the answer onto the block layout:
    leading axes verbatim, the last axis' mesh assignment onto the
    ``nb`` axis when divisible (blocks tile the last axis, so sharding
    blocks IS sharding it), and BLOCK never sharded."""
    from tpu_operator.payload import optimizers as optimizers_mod

    def axis_size(axis) -> int:
        axes = axis if isinstance(axis, (tuple, list)) else (axis,)
        n = 1
        for a in axes:
            n *= mesh.shape[a]
        return n

    def wrapped(keys, leaf):
        if not (keys and keys[-1] in (".q", ".scale")):
            return rule(keys, leaf)
        is_q = keys[-1] == ".q"
        nb = leaf.shape[-2] if is_q else leaf.shape[-1]
        lead = leaf.shape[:-2] if is_q else leaf.shape[:-1]
        proxy = jax.ShapeDtypeStruct(
            (*lead, nb * optimizers_mod.BLOCK), jnp.float32)
        spec = tuple(rule(keys[:-1], proxy))
        spec = spec + (None,) * (proxy.ndim - len(spec))
        last = spec[-1]
        if last is not None and nb % axis_size(last) != 0:
            last = None
        if is_q:
            return P(*spec[:-1], last, None)
        return P(*spec[:-1], last)

    return wrapped


def shardings_from_rule(mesh: Mesh, state: TrainState,
                        rule: Callable[[Tuple[str, ...], Any], P]) -> TrainState:
    """TrainState of NamedShardings from one per-leaf rule
    ``rule(path_keys, leaf) -> PartitionSpec``, applied to params,
    batch_stats, and opt_state alike (the optimizer state embeds
    params-shaped moment leaves under the same layer names, so a path rule
    shards them identically to their params; scalar counters and stats fall
    through to the rule's replicate case; int8 block-quantized moments are
    adapted via :func:`quantized_aware`). ``step`` always replicates."""
    rule = quantized_aware(mesh, rule)

    def spec(tree: Any) -> Any:
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: NamedSharding(
                mesh,
                rule(tuple(getattr(p, "key", str(p)) for p in path), leaf),
            ),
            tree,
        )

    return TrainState(
        step=NamedSharding(mesh, P()),
        params=spec(state.params),
        batch_stats=spec(state.batch_stats),
        opt_state=spec(state.opt_state),
    )


def make_mesh3(num_devices: Optional[int] = None, seq_parallel: int = 1,
               model_parallel: int = 1, devices: Optional[list] = None,
               num_slices: int = 1,
               axis_names: Tuple[str, str, str] = ("data", "seq", "model")
               ) -> Mesh:
    """3-axis (data, seq, model) mesh for composed DP × SP × TP: TP is the
    innermost axis (its collectives fire per matmul — shortest ICI hops),
    the sequence ring sits around it, data-parallel outermost (and across
    DCN for multi-slice jobs, same rule and slice guard as make_mesh)."""
    inner = seq_parallel * model_parallel
    label = f"{axis_names[1]}×{axis_names[2]}"
    devices = _mesh_device_layout(num_devices, devices, inner, label,
                                  num_slices)
    n = len(devices)
    arr = np.array(devices).reshape(n // inner, seq_parallel, model_parallel)
    _guard_intra_slice(arr, num_slices, label)
    return Mesh(arr, axis_names)


def state_shardings(mesh: Mesh, state: TrainState) -> TrainState:
    """NamedShardings for the state: params follow the TP partition rules,
    everything else replicates (opt_state mirrors params' specs)."""
    return shardings_from_rule(mesh, state, models_mod.param_partition_spec)


def place_state(mesh: Mesh, state: TrainState,
                shardings: Optional[TrainState] = None) -> TrainState:
    """Commit the state onto its mesh shardings. Builds call this so the
    live state's shardings are the intended ones from step 0 — checkpoint
    restore targets the live state's shardings (checkpoint.py), and an
    uncommitted device-0 state would otherwise restore single-device and
    clash with the train step's in_shardings."""
    return jax.device_put(state, shardings or state_shardings(mesh, state))


def create_train_state(model: Any, rng: jax.Array, sample_input: jnp.ndarray,
                       tx: optax.GradientTransformation) -> TrainState:
    variables = model.init(rng, sample_input, train=True)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
    )


def cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))


def fused_cross_entropy(logits: jnp.ndarray,
                        labels: jnp.ndarray) -> jnp.ndarray:
    """:func:`cross_entropy` in the target-gather + logsumexp form the LM
    loss already uses (:func:`next_token_nll`): the f32 work is a row
    reduction XLA fuses into the cast, so no f32 [B, num_classes] log-prob
    tensor is materialized. Mathematically identical (``-logp[label] =
    lse(logits) - logits[label]``); summation order differs, so parity is
    to tolerance, not bit-exact — tests/test_flagship_compute.py pins it."""
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, labels[:, None], axis=1)[:, 0]
    return jnp.mean(lse - tgt)


def next_token_nll(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Mean next-token negative log-likelihood, f32 reduction (house
    numerics). Written as target-gather + logsumexp instead of a full
    ``log_softmax``: the f32 work is then a row *reduction* XLA fuses into
    the cast — no f32 [B, T, vocab] tensor is ever materialized, which at
    a 32k vocab is multiple GB of HBM the old form spent."""
    logits = logits[:, :-1]
    targets = tokens[:, 1:]
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None],
                              axis=-1)[..., 0].astype(jnp.float32)
    return jnp.mean(lse - tgt)


def next_token_nll_masked(logits: jnp.ndarray, targets: jnp.ndarray,
                          mask: jnp.ndarray) -> jnp.ndarray:
    """Next-token NLL with explicit per-slot targets and validity mask —
    the permuted-layout form of :func:`next_token_nll` (striped sequence
    layout: slot order ≠ position order, so the "shift by one" pairing is
    precomputed by the caller). Equal to the natural-order loss: both
    average ``lse - logit[target]`` over the same (position, next-token)
    pairs, just enumerated in a different order."""
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None],
                              axis=-1)[..., 0].astype(jnp.float32)
    # Broadcast the mask against the [B, T] per-slot grid and normalize by
    # the count of valid cells — correct for both a shared [T] mask (the
    # striped layout) and a per-example [B, T] one (padding-aware batches).
    mask = jnp.broadcast_to(mask.astype(jnp.float32), lse.shape)
    return jnp.sum((lse - tgt) * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def chunked_next_token_nll_masked(hidden: jnp.ndarray, w_head: jnp.ndarray,
                                  targets: jnp.ndarray, mask: jnp.ndarray,
                                  chunk: int) -> jnp.ndarray:
    """:func:`next_token_nll_masked` computed WITHOUT ever materializing the
    ``[B, T, vocab]`` logits: the lm_head matmul + loss run chunk-by-chunk
    over the sequence inside a ``lax.scan`` whose body is ``jax.checkpoint``-
    wrapped, so the backward also recomputes each chunk's logits instead of
    keeping a full-size cotangent resident. Peak logits footprint drops from
    O(B·T·V) to O(B·chunk·V) — at the 32k-context flagship (T = V = 32768,
    bf16) that is ~2 GiB of activation and ~2 GiB of cotangent back, the
    single biggest activation in the step. The per-chunk matmul stays MXU-
    sized (``[B·chunk, d] @ [d, V]``), so the split costs bandwidth-free
    FLOPs: one extra lm_head forward in the backward (the usual remat
    trade). Takes the trunk's final hidden states and the lm_head kernel
    explicitly (the head matmul must live inside the scan); the kernel is
    cast to the hidden dtype, matching ``nn.Dense(dtype=...)`` semantics.
    Summation order differs from the unchunked form (per-chunk partial
    sums), so equality holds to f32 reduction tolerance."""
    b, t, d = hidden.shape
    if chunk <= 0 or t % chunk != 0:
        raise ValueError(
            f"loss chunk {chunk} must be positive and divide T={t}")
    n = t // chunk
    mask = jnp.broadcast_to(mask.astype(jnp.float32), (b, t))
    xs = (hidden.reshape(b, n, chunk, d).swapaxes(0, 1),
          targets.reshape(b, n, chunk).swapaxes(0, 1),
          mask.reshape(b, n, chunk).swapaxes(0, 1))

    def body(acc, xs_i):
        xc, tc, mc = xs_i
        # Cast INSIDE the body: w_head stays the (f32) scan constant, so
        # the scan transpose sums the per-chunk head cotangents in f32 —
        # hoisting the cast would accumulate dL/dw in bf16, with error
        # growing in the chunk count (measured 3.3x the dense path's at
        # 64 chunks). The per-chunk cast is noise next to the matmul.
        logits = xc @ w_head.astype(xc.dtype)
        lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32),
                                          axis=-1)
        tgt = jnp.take_along_axis(logits, tc[..., None],
                                  axis=-1)[..., 0].astype(jnp.float32)
        return acc + jnp.sum((lse - tgt) * mc), None

    total, _ = jax.lax.scan(jax.checkpoint(body),
                            jnp.zeros((), jnp.float32), xs)
    return total / jnp.maximum(jnp.sum(mask), 1.0)


def chunked_next_token_nll(hidden: jnp.ndarray, w_head: jnp.ndarray,
                           tokens: jnp.ndarray, chunk: int) -> jnp.ndarray:
    """Natural-order wrapper over :func:`chunked_next_token_nll_masked`:
    target of position i is token i+1, last position masked out — the
    chunked equal of :func:`next_token_nll` (same (position, next-token)
    pairs, chunked enumeration)."""
    b, t = tokens.shape
    targets = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros((b, 1), tokens.dtype)], axis=1)
    mask = jnp.arange(t) < t - 1
    return chunked_next_token_nll_masked(hidden, w_head, targets, mask,
                                         chunk)


def leading_axis_shardings(mesh: Mesh, state: TrainState, axis: str,
                           match: Callable[[Tuple[str, ...]], bool]) -> TrainState:
    """Shardings for payloads with stacked parameter groups: leaves whose
    path keys satisfy ``match`` shard their leading dim over ``axis`` (the
    params-shaped adam moments share the paths, so they match identically);
    everything else replicates. Used by pipeline (stages → pipe) and MoE
    (expert stacks → expert)."""

    def rule(keys, leaf):
        if match(keys) and getattr(leaf, "ndim", 0) >= 1:
            return P(axis, *(None,) * (leaf.ndim - 1))
        return P()

    return shardings_from_rule(mesh, state, rule)


def fsdp_shardings(mesh: Mesh, state: TrainState, axis: str = "data",
                   min_size: int = 1024) -> TrainState:
    """ZeRO/FSDP-style shardings: every large param leaf (and its
    params-shaped adam moments) shards dim 0 over ``axis`` — normally the
    data axis, so each DP rank owns 1/N of the params and optimizer state.
    Under jit, GSPMD all-gathers a layer's weights just-in-time for its
    matmul and reduce-scatters its gradients — per-device param+opt memory
    drops to O(1/N) with no hand-written gather/scatter. Leaves whose dim 0
    does not divide the axis (or smaller than ``min_size`` elements, where
    collective latency would dominate) replicate."""
    axis_size = mesh.shape[axis]

    def rule(_keys, leaf):
        shape = getattr(leaf, "shape", ())
        size = getattr(leaf, "size", 0)
        if (len(shape) >= 1 and size >= min_size
                and shape[0] % axis_size == 0):
            return P(axis, *(None,) * (len(shape) - 1))
        return P()

    return shardings_from_rule(mesh, state, rule)


def make_loss_train_step(loss_fn: Callable, tx: optax.GradientTransformation,
                         mesh: Mesh, state: TrainState,
                         shardings: Optional[TrainState] = None,
                         batch_spec: P = P("data"),
                         grad_accum: int = 1) -> Callable:
    """The shared LM/loss step: ``loss_fn(params, batch) -> (loss, metrics)``
    differentiated, adam-updated, jitted with donated state. The LM payloads
    (transformer, pipeline, MoE) build their steps on this with
    payload-specific loss_fns and batch specs.

    ``grad_accum=K`` splits the batch's leading dim into K sequential
    microbatches inside the jit (``lax.scan``), averaging their gradients
    before the single optimizer update — the activation-memory knob for
    batch sizes whose activations exceed HBM. Numerically equal to the
    K=1 step up to summation order (every loss_fn here is a mean)."""
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
    shardings = shardings or state_shardings(mesh, state)

    def grads_and_metrics(params, batch):
        if grad_accum == 1:
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            return grads, metrics
        b = batch.shape[0]
        if b % grad_accum != 0:
            raise ValueError(
                f"batch {b} not divisible by grad_accum={grad_accum}")
        micro = batch.reshape(grad_accum, b // grad_accum, *batch.shape[1:])
        # keep each microbatch sharded exactly like a full batch
        micro = jax.lax.with_sharding_constraint(
            micro, NamedSharding(mesh, P(None, *batch_spec)))

        def body(g_acc, mb):
            (_loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb)
            return jax.tree_util.tree_map(jnp.add, g_acc, grads), metrics

        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        g_sum, metrics_stack = jax.lax.scan(body, zeros, micro)
        grads = jax.tree_util.tree_map(lambda g: g / grad_accum, g_sum)
        metrics = jax.tree_util.tree_map(
            lambda m: jnp.mean(m, axis=0), metrics_stack)
        return grads, metrics

    return make_grads_train_step(grads_and_metrics, tx, mesh, state,
                                 shardings, batch_spec=batch_spec)


def make_grads_train_step(grads_and_metrics: Callable,
                          tx: optax.GradientTransformation, mesh: Mesh,
                          state: TrainState,
                          shardings: Optional[TrainState] = None,
                          batch_spec: P = P("data")) -> Callable:
    """The shared adam-update/donated-jit tail of every step builder:
    ``grads_and_metrics(params, batch) -> (grads, metrics)`` however the
    caller computes them — jax.value_and_grad (make_loss_train_step) or
    hand-accumulated manual vjp (the pipeline 1F1B schedule)."""
    shardings = shardings or state_shardings(mesh, state)
    batch_shard = NamedSharding(mesh, batch_spec)

    def step(state: TrainState, batch: jnp.ndarray) -> Tuple[TrainState, dict]:
        grads, metrics = grads_and_metrics(state.params, batch)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_state = TrainState(
            step=state.step + 1,
            params=optax.apply_updates(state.params, updates),
            batch_stats=state.batch_stats,
            opt_state=new_opt,
        )
        return new_state, metrics

    return jax.jit(
        step,
        in_shardings=(shardings, batch_shard),
        out_shardings=(shardings, None),
        donate_argnums=(0,),
    )


def make_classifier_train_step(model: Any, tx: optax.GradientTransformation,
                               mesh: Mesh, state: TrainState,
                               shardings: Optional[TrainState] = None,
                               remat_policy: str = "full",
                               fused_loss: bool = False) -> Callable:
    """Compile the classification train step with explicit shardings.

    ``remat_policy`` != "full" wraps the model forward in ``jax.checkpoint``
    with :func:`models.remat_policy`'s policy — step-level remat rather than
    flax lifted ``nn.remat`` because the classifier forward mutates
    batch_stats, which step-level checkpointing handles as an explicit
    output without touching the param tree (checkpoint-compatible; the
    optimized path must restore the seed path's checkpoints). ``fused_loss``
    swaps :func:`cross_entropy` for :func:`fused_cross_entropy`. Defaults
    reproduce the seed path bit-for-bit."""
    shardings = shardings or state_shardings(mesh, state)
    batch_shard = data_mod.batch_sharding(mesh)
    label_shard = NamedSharding(mesh, P("data"))
    loss_of = fused_cross_entropy if fused_loss else cross_entropy

    def forward(params, batch_stats, images):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            images, train=True, mutable=["batch_stats"],
        )
        return logits, mutated["batch_stats"]

    if remat_policy != "full":
        from tpu_operator.payload import models as models_mod

        forward = jax.checkpoint(
            forward, policy=models_mod.remat_policy(remat_policy))

    def step(state: TrainState, images: jnp.ndarray,
             labels: jnp.ndarray) -> Tuple[TrainState, dict]:
        def loss_fn(params):
            logits, new_stats = forward(params, state.batch_stats, images)
            return loss_of(logits, labels), (logits, new_stats)

        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        accuracy = jnp.mean((jnp.argmax(logits, axis=1) == labels).astype(jnp.float32))
        new_state = TrainState(
            step=state.step + 1, params=new_params,
            batch_stats=new_stats, opt_state=new_opt,
        )
        return new_state, {"loss": loss, "accuracy": accuracy}

    return jax.jit(
        step,
        in_shardings=(shardings, batch_shard, label_shard),
        out_shardings=(shardings, None),
        donate_argnums=(0,),
    )


def make_regression_train_step(model: Any, tx: optax.GradientTransformation,
                               mesh: Mesh, state: TrainState,
                               shardings: Optional[TrainState] = None) -> Callable:
    shardings = shardings or state_shardings(mesh, state)
    x_shard = data_mod.batch_sharding(mesh)

    def step(state: TrainState, x: jnp.ndarray,
             y: jnp.ndarray) -> Tuple[TrainState, dict]:
        def loss_fn(params):
            pred = model.apply({"params": params}, x, train=True)
            return jnp.mean((pred - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_state = TrainState(
            step=state.step + 1,
            params=optax.apply_updates(state.params, updates),
            batch_stats=state.batch_stats,
            opt_state=new_opt,
        )
        return new_state, {"loss": loss}

    return jax.jit(
        step,
        in_shardings=(shardings, x_shard, x_shard),
        out_shardings=(shardings, None),
        donate_argnums=(0,),
    )


def _infer_tokens_per_batch(batch_args: tuple) -> int:
    """Tokens per global batch when the batch is LM-shaped — a single [B, T]
    integer array (transformer/pipeline/MoE payloads) — else 0. Lets the
    auto-wired heartbeat report tokens/sec without every payload plumbing
    its batch geometry through."""
    if len(batch_args) != 1:
        return 0
    arr = batch_args[0]
    shape = getattr(arr, "shape", ())
    dtype = getattr(arr, "dtype", None)
    if len(shape) == 2 and dtype is not None and \
            jnp.issubdtype(dtype, jnp.integer):
        return int(shape[0] * shape[1])
    return 0


def _abstractify(x: Any) -> Any:
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                    sharding=getattr(x, "sharding", None))
    return x


def _detach_restored(state: TrainState) -> TrainState:
    """Copy orbax-restored leaves into fresh backend buffers before the
    (donating) train step consumes them. Restored arrays can be backed by
    the restore machinery's own allocations; donating those into a
    persistent-cache-deserialized executable corrupts the heap on the
    jaxlib CPU build this environment pins (glibc abort on the second
    step, reproduced with restore + cache hit + donation and with any one
    of the three removed it disappears). The copy is bandwidth-cheap next
    to the restore's host I/O and runs once per attempt. Non-addressable
    (multi-host) leaves pass through untouched — copying them would need
    an identity program per sharding, and the corruption has only been
    observed on the single-process CPU path."""
    return jax.tree_util.tree_map(
        lambda x: jnp.array(x)
        if isinstance(x, jax.Array) and x.is_fully_addressable else x,
        state)


def aot_compile_step(train_step: Callable, state: TrainState,
                     batch_args: tuple) -> Optional[Callable]:
    """AOT-compile a jitted train step for the live state's shapes and one
    batch's shapes via ``lower(...).compile()`` — the compile then runs off
    the critical path (the overlapped prologue calls this on a worker
    thread while checkpoint restore does host I/O), and the returned
    executable is invoked directly, skipping trace-time on the first step.
    Returns None when the step has no ``lower`` (not a jit'd callable)."""
    lower = getattr(train_step, "lower", None)
    if lower is None:
        return None
    abstract_state = jax.tree_util.tree_map(_abstractify, state)
    abstract_batch = tuple(_abstractify(a) for a in batch_args)
    return lower(abstract_state, *abstract_batch).compile()


def _overlapped_prologue(train_step: Callable, state: TrainState, batches,
                         checkpointer, tracker: startup_mod.StartupTracker
                         ) -> tuple:
    """The warm-restart fast path's attempt prologue: checkpoint restore
    (host I/O + a little device placement) and the AOT compile of the train
    step (compiler-bound, or a persistent-cache deserialize on a warm
    restart) run **concurrently** instead of serially — restore lands into
    the already-compiled step. Returns (state, start, stream, compiled).

    Semantics are identical to the serial prologue by construction:

    - batch 0 is peeked only to give the AOT lowering its shapes; the
      returned stream re-chains it in order, so a fresh start trains on it
      and a resume discards it exactly as the serial fast-forward would;
    - restore keeps PR 4's verified-restore + gang-consistent semantics
      untouched — it is the same ``checkpointer.restore`` call, whose
      collectives stay on this (the main) thread; only the XLA compile
      moves to a worker;
    - any compile failure falls back to ordinary jit dispatch (first step
      pays trace+compile, as before) — the fast path never adds a way for
      an attempt to fail.
    """
    it = iter(batches)
    try:
        first = next(it)
    except StopIteration:
        first = None
    result: dict = {}
    # Snapshot the state binding BEFORE the thread starts: the main thread
    # rebinds ``state`` mid-restore (and again in _detach_restored), and a
    # closure reads its variable at call time — the lowering must see the
    # init state's leaves deterministically, never a racy mix.
    compile_state = state

    def compile_worker() -> None:
        listening = startup_mod.ensure_cache_listener()
        before = startup_mod.cache_hit_count()
        try:
            with tracker.stage(startup_mod.COMPILE):
                compiled = aot_compile_step(train_step, compile_state, first)
        except Exception as e:  # noqa: BLE001 — fall back to jit dispatch
            log.warning("AOT compile of the train step failed; first step "
                        "will trace+compile as before: %s", e)
            return
        if compiled is None:
            return
        # Warm vs cold via JAX's own monitoring events: a persistent-cache
        # hit during the compile window means the executable (or the bulk
        # of this attempt's programs) was deserialized, not rebuilt.
        if listening:
            tracker.cache_hit = startup_mod.cache_hit_count() > before
        result["compiled"] = compiled

    worker = None
    if first is not None:
        worker = threading.Thread(target=compile_worker, daemon=True,
                                  name="aot-compile")
        worker.start()
    start = 0
    try:
        if checkpointer is not None:
            with tracker.stage(startup_mod.RESTORE):
                state, start = checkpointer.restore(state)
            if start > 0:
                state = _detach_restored(state)
    finally:
        # Join on every exit — a restore failure propagating with the
        # compile thread mid-flight would race it against teardown.
        if worker is not None:
            worker.join()
    stream = itertools.chain([first], it) if first is not None else it
    for _ in range(start):
        next(stream)
    return state, start, stream, result.get("compiled")


def _dump_steptrace(recorder: Optional[steptrace_mod.StepRecorder],
                    checkpointer) -> None:
    """Retryable-exit postmortem: dump the flight recorder's ring buffer
    next to the checkpoint dir and, when the remote warm-start store is
    wired, ship the artifact through the existing write-behind worker (the
    caller's ``checkpointer.close()`` drains it) — so a postmortem of a
    preempted/stalled attempt sees the last N steps' phase timings even
    when the node itself is gone. Strictly best-effort on every branch."""
    if recorder is None:
        return
    recorder.abandon()
    ckpt_dir = getattr(checkpointer, "directory", "") \
        or os.environ.get("TPU_CHECKPOINT_DIR", "")
    path = steptrace_mod.postmortem_dump(recorder, ckpt_dir)
    if path is None:
        return
    uploader = getattr(checkpointer, "uploader", None)
    if uploader is not None and hasattr(uploader, "enqueue_artifact"):
        uploader.enqueue_artifact(path)


def _finish_profile(capture: profile_mod.ProfileCapture,
                    recorder: Optional[steptrace_mod.StepRecorder],
                    checkpointer, heartbeat) -> None:
    """Close a completed on-demand capture: write the artifact, ship it
    through the write-behind ``artifacts/`` path (same route as the
    steptrace postmortem), and attach the result to the heartbeat so the
    controller folds ``status.profile`` to Captured. Best-effort on every
    branch — a profile must never take down the step loop."""
    try:
        path, result = capture.finish(recorder)
    except Exception:  # noqa: BLE001 — capture teardown is observability only
        log.exception("profile %s: finish failed", capture.id)
        return
    if path:
        uploader = getattr(checkpointer, "uploader", None)
        if uploader is not None and hasattr(uploader, "enqueue_artifact"):
            uploader.enqueue_artifact(path)
            result["artifactKey"] = "artifacts/" + os.path.basename(path)
    attach = getattr(heartbeat, "attach_profile_result", None)
    if attach is not None:
        attach(result)
    log.info("profile %s: captured %d step(s)%s", capture.id,
             result.get("capturedSteps", 0),
             " -> " + result["artifactKey"] if "artifactKey" in result
             else "")


def _startup_heartbeat_ticker(tracker: startup_mod.StartupTracker,
                              heartbeat, stop: threading.Event) -> None:
    """Pre-first-step liveness: until the first step lands there are no
    step heartbeats, so a long compile or restore on a big payload is
    indistinguishable from a hang — the stall watchdog (PR 2) would
    restart the group into a loop that never escapes compilation. Posting
    the in-flight ``startupStage`` on the heartbeat cadence keeps the
    watchdog's baseline fresh while startup makes progress. The cadence
    is ``heartbeat.interval_of`` — the one shared definition (the
    reporter's due() interval, this ticker, and the autotune runtime's
    host-budget pacing can never disagree; the old per-tick
    ``getattr(..., 10.0)`` re-derivation only matched DEFAULT_INTERVAL
    by coincidence)."""
    while not stop.wait(max(0.01, heartbeat_mod.interval_of(heartbeat))):
        stage = tracker.current_stage()
        if stage is not None:
            heartbeat.report_startup(stage)


def train_loop(mesh: Mesh, train_step: Callable, state: TrainState,
               batches, steps: int,
               log_every: int = 0,
               log_fn: Callable[[int, dict], None] = None,
               checkpointer=None, spec=None,
               profile_dir: str = "",
               profile_range: Tuple[int, int] = (10, 20),
               prefetch: int = 2,
               heartbeat="auto", startup=None,
               overlap: bool = True,
               steptrace="auto",
               dataplane="auto") -> Tuple[TrainState, dict]:
    """Drive the loop to ``steps`` total steps; returns (state, last_metrics).
    Host↔device traffic is one batch in, one scalar dict out per logging
    interval — and the batch transfers run ``prefetch`` deep ahead of the
    step (data.device_prefetch), so host batch generation and H2D bytes
    overlap behind device compute instead of sitting on the critical path.
    ``spec`` overrides the batch PartitionSpec (default P("data");
    the LM payload passes P("data", "seq")).

    With a ``checkpointer`` (payload/checkpoint.py), the loop first restores
    the newest *verified* checkpoint (corrupt/torn steps are quarantined and
    walked past; multi-process jobs agree on the step via allgather-min) —
    so a whole-group restart (TPUJOB_ATTEMPT > 0) resumes where the previous
    attempt left off instead of step 0 — then
    saves on the checkpointer's interval policy plus once at the end. The
    checkpointer stays owned by the caller, who must ``close()`` it (flushes
    the async save) when done with it.
    ``steps`` is the *target total*, not an increment: a job restarted at
    step 400 of 500 runs 100 more, on the *same* batches 400..499 it would
    have seen uninterrupted: the seed-deterministic stream is fast-forwarded
    past the ``start`` batches the previous attempt already consumed.

    ``profile_dir`` (payload ``--profile-dir`` / operator-injectable
    ``TPU_PROFILE_DIR``) captures a ``jax.profiler`` device trace of steps
    ``profile_range`` *relative to this run's first step* — so a resumed
    attempt still profiles post-compile steady state, not its compile step —
    viewable in TensorBoard/XProf. The payload-side half of the reference's
    tracing subsystem (SURVEY.md §5; control-plane half is util/tracing.py).

    ``heartbeat`` posts step telemetry to the operator's status server
    (payload/heartbeat.py): ``"auto"`` (default) builds a reporter from the
    operator's env contract — a no-op unless TPUJOB_STATUS_URL is injected
    and this is process 0 — or pass a HeartbeatReporter / None explicitly.
    The post is rate-limited inside the reporter and fetches metrics only
    when actually due, so it stays off the steady-state step path.

    ``overlap`` (default on) runs the attempt prologue's independent costs
    concurrently — checkpoint restore, AOT compile of the train step, and
    the first batches' host generation + H2D prefetch — instead of
    serially (the warm-restart fast path; see ``_overlapped_prologue``).
    ``startup`` is an injectable :class:`startup.StartupTracker`; the
    default fresh tracker times each stage, the breakdown is posted on the
    first heartbeat after the first step (→ ``status.startup``), and
    pre-first-step liveness beats carry the in-flight ``startupStage`` so
    a long compile never reads as a stall.

    ``steptrace`` is the data-plane flight recorder
    (payload/steptrace.py): ``"auto"`` (default) builds one from the env
    contract (on unless TPUJOB_STEPTRACE_ENABLED=0), or pass a
    StepRecorder / None explicitly. The step path pays timestamps only;
    phase digests ride due heartbeats as ``stepTiming`` and the ring
    buffer dumps as a postmortem artifact on a retryable exit. The
    COMPUTE fence is deferred one step (see the ``fence`` comment below)
    so dispatch pipelining survives — bench.py --steptrace enforces the
    <1% overhead budget.

    ``dataplane`` is the self-tuning data plane
    (payload/autotune.py): ``"auto"`` (default) builds a runtime from
    the env contract — inert (the static ``prefetch`` depth, zero new
    cost) unless the operator injected ``TPUJOB_DATAPLANE_*`` for
    ``spec.dataPlane`` — or pass a DataPlaneRuntime / None explicitly.
    An active runtime runs the host batch generation on a background
    pipeline thread; with autotune enabled it also hill-climbs the live
    prefetch depth, moves heartbeat/log work off the step thread when
    HOST dominates, and stretches checkpoint cadence within its bound —
    converging toward minimal non-COMPUTE residue, backing off on
    regression (bench.py --dataplane enforces the budgets).
    """
    if heartbeat == "auto":
        heartbeat = heartbeat_mod.from_env()
    recorder = steptrace_mod.from_env() if steptrace == "auto" else steptrace
    if dataplane == "auto":
        runtime = autotune_mod.from_env(prefetch=prefetch)
    elif dataplane is None:
        runtime = autotune_mod.DataPlaneRuntime.static(prefetch)
    else:
        runtime = dataplane
    # processes gates the checkpoint-cadence knob: a gang's save is a
    # collective, so only a single-process job may stretch the
    # maybe_save gate unilaterally (see DataPlaneRuntime.attach).
    runtime.attach(recorder=recorder, heartbeat=heartbeat,
                   checkpointer=checkpointer,
                   processes=jax.process_count())
    tracker = startup if startup is not None else startup_mod.new_tracker()
    ticker_stop = threading.Event()
    # Startup-liveness beats are process 0's job (the watchdog baseline is
    # per JOB, not per process): a cadence-only reporter skips the ticker
    # entirely — on a 64-process gang, 63 startupStage posts per interval
    # the operator would discard anyway.
    if heartbeat is not None and not getattr(heartbeat, "cadence_only",
                                             False):
        threading.Thread(target=_startup_heartbeat_ticker,
                         args=(tracker, heartbeat, ticker_stop),
                         daemon=True, name="startup-heartbeat").start()
    start = 0
    step_fn = train_step
    try:
        if overlap:
            state, start, batches, compiled = _overlapped_prologue(
                train_step, state, batches, checkpointer, tracker)
            if compiled is not None:
                step_fn = compiled
        elif checkpointer is not None:
            with tracker.stage(startup_mod.RESTORE):
                state, start = checkpointer.restore(state)
            if start > 0:
                state = _detach_restored(state)
            for _ in range(start):
                next(batches)
    except BaseException:
        ticker_stop.set()
        raise
    # Prefetch wraps the stream only after the resume fast-forward above,
    # so a restarted attempt still sees exactly the batches it would have.
    # The fill's H2D transfers are async, so they overlap whatever compile
    # work the first step still has to do. The data-plane runtime resolves
    # the depth (0=auto convention; negative fails loudly in
    # device_prefetch) and, when active, supplies the live control and the
    # background host pipeline.
    dev_batches = data_mod.device_prefetch(mesh, batches, spec=spec,
                                           depth=runtime.depth,
                                           control=runtime.control,
                                           pipeline=runtime.pipeline)
    pending_startup: Optional[dict] = None
    metrics = {}
    tracing = profiled = False
    trace_from, trace_to = start + profile_range[0], start + profile_range[1]
    if profile_dir and trace_from >= steps:
        log.warning(
            "profile window [%d, %d) lies beyond the run's last step %d; "
            "no trace will be captured", trace_from, trace_to, steps)

    if jax.process_count() > 1:
        # Coordinated drain: SIGTERM lands on *one* pod (preemption) and a
        # drain directive lands on process 0 only, but an orbax save is a
        # group collective, so every process must agree on the boundary
        # step. Each step, every process contributes its local drain exit
        # code (0 = not draining) to a tiny allgather; all processes
        # evaluate the same gathered array at the same loop index, so they
        # reach consensus at the same i, group-save one consistent
        # checkpoint, and exit with the same code. ``max`` both detects
        # any drain and picks the winning flavor: EXIT_PLANNED (160) >
        # EXIT_RETRYABLE (143), so a directive-driven drain is billed
        # planned even when a sibling was independently SIGTERMed — the
        # same precedence the operator's classifier applies. Cost: one
        # scalar collective per step — noise next to a training step.
        from jax.experimental import multihost_utils

        def agreed_drain_code() -> int:
            code = np.int32(bootstrap_mod.drain_exit_code()
                            if bootstrap_mod.draining() else 0)
            return int(multihost_utils.process_allgather(code).max())
    else:
        def agreed_drain_code() -> int:
            return (bootstrap_mod.drain_exit_code()
                    if bootstrap_mod.draining() else 0)

    bootstrap_mod.enter_step_loop()  # SIGTERM now defers to a step boundary
    # Flight-recorder COMPUTE fence, one step deep: after dispatching step
    # i, block on step i-1's metrics (never the donated state). Fencing
    # the CURRENT step would serialize host dispatch against device
    # compute and cost real throughput (measured ~1-3% at bench shapes);
    # deferred by one step, the dispatch of i overlaps i-1's tail and the
    # lap still measures the honest device-bound share of the step wall
    # time. metrics is not donated, so the held reference stays valid.
    # ``ready`` is the newest metrics the fence has COMPLETED: while the
    # recorder runs, logs and heartbeats read it instead of the current
    # step's metrics — a same-step device_get on the telemetry path is a
    # full compute stall billed to the HOST lap, which inflated process
    # 0's local time into a FALSE straggler flag on large-step jobs (one
    # beat per digest window, and a <20-step window's nearest-rank p95 IS
    # its max). One step of telemetry lag, zero self-measurement.
    fence = ready = None
    # On-demand deep profile (one at a time): armed when a heartbeat ACK
    # delivers a directive, ticked once per committed step below.
    profile_capture: Optional[profile_mod.ProfileCapture] = None
    try:
        for i in range(start, steps):
            if recorder is not None:
                recorder.begin(i)
            drain_code = agreed_drain_code()
            if drain_code:
                # Drain: persist the i completed steps and exit retryable —
                # the restarted attempt resumes exactly here. The caller's
                # finally close() flushes the async write. In multi-process
                # jobs every peer (signaled or not) reaches this branch at
                # the same i (consensus above), saves collectively, and
                # exits with the same agreed code — EXIT_RETRYABLE for a
                # signal drain, EXIT_PLANNED for an operator directive —
                # so the operator restarts the whole group and bills the
                # restart to the right ledger kind. The save is guarded:
                # an I/O failure during the drain must not escape as a
                # permanent exit (1) — the restart simply resumes from the
                # last verified save.
                if checkpointer is not None and i > start:
                    try:
                        checkpointer.save(i, state)
                        log.info("drain: checkpointed step %d, "
                                 "exiting %d", i, drain_code)
                    except Exception:  # noqa: BLE001 — drain code regardless
                        log.exception(
                            "drain: checkpoint save of step %d failed; "
                            "exiting %d anyway (resume falls back "
                            "to the last verified step)", i, drain_code)
                else:
                    log.info("drain: exiting %d at step %d", drain_code, i)
                raise SystemExit(drain_code)
            if (profile_dir and not tracing and not profiled
                    and i >= trace_from):
                jax.profiler.start_trace(profile_dir)
                tracing = True
            batch_args = next(dev_batches)
            if recorder is not None:
                recorder.lap(steptrace_mod.DATA)
            if heartbeat is not None and i == start \
                    and getattr(heartbeat, "tokens_per_batch", 0) == 0:
                heartbeat.tokens_per_batch = _infer_tokens_per_batch(batch_args)
            if i == start:
                # Time the first step to completion (one extra fence, paid
                # once per attempt): with the AOT fast path it is pure
                # execution; without, it carries the residual trace+compile
                # — either way it is the last leg of TTFS.
                with tracker.stage(startup_mod.FIRST_STEP):
                    try:
                        state, metrics = step_fn(state, *batch_args)
                    except (TypeError, ValueError):
                        if step_fn is train_step:
                            raise
                        # The AOT executable can reject inputs the jit
                        # path would accept — e.g. a step jitted WITHOUT
                        # explicit in_shardings lowers from the host
                        # batch's (absent) sharding and then refuses the
                        # device-placed one. Only argument-validation
                        # errors (TypeError/ValueError) are retried: they
                        # fire before execution or donation, so the state
                        # is intact. Runtime failures (XlaRuntimeError,
                        # OOM) may already have consumed the donated
                        # buffers and must propagate as the real error.
                        log.warning(
                            "AOT-compiled step rejected its inputs; "
                            "falling back to jit dispatch", exc_info=True)
                        step_fn = train_step
                        state, metrics = step_fn(state, *batch_args)
                    jax.device_get(metrics)
                ticker_stop.set()
                pending_startup = tracker.breakdown()
                if recorder is not None:
                    # First step: dispatch, residual compile, and the
                    # device_get fence are one indivisible TTFS leg —
                    # recorded whole as COMPUTE.
                    recorder.lap(steptrace_mod.COMPUTE)
                    fence = ready = metrics
            else:
                state, metrics = step_fn(state, *batch_args)
                if recorder is not None:
                    recorder.lap(steptrace_mod.DISPATCH)
                    if fence is not None:
                        jax.block_until_ready(fence)
                        ready = fence
                    recorder.lap(steptrace_mod.COMPUTE)
                    fence = metrics
            if tracing and (i + 1) >= trace_to:
                jax.device_get(metrics)  # drain async work into the trace
                jax.profiler.stop_trace()
                tracing, profiled = False, True
                if recorder is not None:
                    # The profiler-stop drain fenced a whole step's
                    # compute; billed to HOST (one-off bookkeeping), it
                    # must not masquerade as a checkpoint stall in the
                    # phase digest.
                    recorder.lap(steptrace_mod.HOST)
            if checkpointer is not None:
                checkpointer.maybe_save(i + 1, state)
                if recorder is not None:
                    recorder.lap(steptrace_mod.CHECKPOINT)
            # Telemetry (logs + heartbeats) reads the newest FENCED
            # metrics while the recorder runs: already computed, so the
            # device_get is a scalar copy, not a compute stall — one step
            # of lag instead of a self-measured phantom HOST phase.
            telemetry = metrics if recorder is None or ready is None \
                else ready
            if log_every and log_fn and (i + 1) % log_every == 0:
                # The device_get of fenced metrics is a scalar copy and
                # stays on the step thread; formatting + emission move to
                # the async host worker when the data plane enabled it.
                runtime.submit_host(log_fn, i + 1,
                                    jax.device_get(telemetry))
            # The first step's report is forced (not just when due): it
            # carries the startup breakdown the operator folds into
            # status.startup; thereafter the breakdown rides along on due
            # beats until one post succeeds.
            if heartbeat is not None and (heartbeat.due(i + 1)
                                          or (i == start
                                              and pending_startup)):
                # The phase digest drains the recorder's window only on a
                # due beat (aggregation stays off the steady step path); a
                # failed post drops that window's digest — the ring buffer
                # still holds the raw steps for the postmortem. A
                # cadence-only reporter (non-zero process) skips the
                # device_get and checkpoint stats outright: it strips
                # loss/checkpoint from the body anyway, and the device_get
                # is a SAME-step fence — exactly the pipeline stall the
                # recorder's deferred COMPUTE fence exists to avoid.
                cadence = getattr(heartbeat, "cadence_only", False)
                if heartbeat.report(
                        i + 1,
                        None if cadence else jax.device_get(telemetry),
                        checkpoint=(checkpointer.stats()
                                    if checkpointer is not None
                                    and not cadence else None),
                        startup=pending_startup,
                        steptiming=(recorder.summary()
                                    if recorder is not None else None),
                        dataplane=runtime.wire()):
                    pending_startup = None
            if recorder is not None:
                recorder.lap(steptrace_mod.HOST)
                recorder.commit()
            if heartbeat is not None:
                # Ticked AFTER commit so the flight recorder's row for
                # this step is in the ring when a full window merges it.
                take = getattr(heartbeat, "take_profile_directive", None)
                directive = take() if take is not None else None
                if directive and profile_capture is None:
                    profile_capture = profile_mod.ProfileCapture(
                        directive,
                        base_dir=(getattr(checkpointer, "directory", "")
                                  or os.environ.get(
                                      "TPU_CHECKPOINT_DIR", "")),
                        # The loop's own --profile window owns the jax
                        # profiler while armed or active; the on-demand
                        # capture then ships raw laps only.
                        allow_jax_trace=(not tracing
                                         and (not profile_dir or profiled)))
                    profile_capture.start(i + 1)
                if (profile_capture is not None
                        and profile_capture.tick(i + 1)):
                    _finish_profile(profile_capture, recorder,
                                    checkpointer, heartbeat)
                    profile_capture = None
                # Cooperative-drain directive (process 0 only, rode the
                # ACK): arm the planned-drain latch — the consensus
                # allgather spreads it to every peer at the next step
                # boundary, where the gang saves and exits EXIT_PLANNED —
                # and attach the adoption ACK so the operator stops
                # resending. If the gang exits before the ACK posts, the
                # PLANNED classification itself completes the directive.
                take_drain = getattr(heartbeat, "take_drain_directive",
                                     None)
                drain_dir = take_drain() if take_drain is not None else None
                if drain_dir and drain_dir.get("id"):
                    log.info("drain directive %s (%s): draining at next "
                             "step boundary", drain_dir.get("id"),
                             drain_dir.get("reason", ""))
                    bootstrap_mod.request_planned_drain()
                    attach = getattr(heartbeat, "attach_drain_ack", None)
                    if attach is not None:
                        attach({"id": str(drain_dir["id"]),
                                "step": i + 1})
    except SystemExit as e:
        # Retryable exits (preemption drain, save-failure escalation) are
        # exactly when a postmortem wants the last N steps' phase timings:
        # dump the flight recorder next to the checkpoint dir (and ship it
        # via the write-behind store worker) before the exit propagates.
        # Direct equality, no int() coercion: SystemExit.code may legally
        # be any object (sys.exit("message")) and must pass through
        # untouched.
        if getattr(e, "code", None) in (bootstrap_mod.EXIT_RETRYABLE,
                                        bootstrap_mod.EXIT_PLANNED):
            _dump_steptrace(recorder, checkpointer)
        raise
    finally:
        ticker_stop.set()
        bootstrap_mod.exit_step_loop()
        # Deterministic data-plane teardown: close the prefetch generator
        # (stops the host pipeline thread, if any) and drain the async
        # host worker's queued telemetry (bounded — a wedged poster can't
        # park the exit).
        dev_batches.close()
        runtime.close()
        if profile_capture is not None:
            # A preemption mid-capture must not leave the jax profiler
            # started; the partial window is dropped (the directive is
            # one-shot — the user re-requests against the new attempt).
            profile_capture.abandon()
        if tracing:
            # Close the trace on EVERY exit path — normal completion with the
            # window open, SIGTERM drain (SystemExit above), or a step error —
            # so the captured window is flushed instead of lost/corrupt.
            try:
                jax.device_get(metrics)  # drain async work into the trace
            except Exception:  # noqa: BLE001 — device poisoned; still close
                pass
            jax.profiler.stop_trace()
    if checkpointer is not None and steps > start:
        # The final save is load-bearing: a run must not report DONE with
        # its end state silently unpersisted (an interval-save failure is
        # tolerable — the next interval retries — but there is no next
        # interval here). Flush forces verification; if the final step
        # still is not durable, exit retryable so the restarted attempt
        # resumes from the last verified step and re-earns a durable
        # finish instead of the trained weights being quietly lost.
        checkpointer.save(steps, state)
        checkpointer.flush()
        if checkpointer.last_verified_step() != steps:
            log.error(
                "final checkpoint of step %d is not durable (last verified "
                "step: %s); exiting retryable so the restart re-earns it",
                steps, checkpointer.last_verified_step())
            _dump_steptrace(recorder, checkpointer)
            raise SystemExit(bootstrap_mod.EXIT_RETRYABLE)
    return state, (jax.device_get(metrics) if metrics else {})


def throughput(mesh: Mesh, train_step: Callable, state: TrainState, batches,
               steps: int, warmup: int = 3, spec: P = None,
               prefetch: int = 2) -> Tuple[TrainState, float]:
    """steps/sec over `steps` timed iterations (post-warmup), fed through
    the SAME pipelined input path the shipped loop uses —
    ``data.device_prefetch`` (depth ``prefetch``) — rather than a
    bench-only per-step ``put_global_batch``: the measured number then
    includes host batch generation and H2D transfer overlapped behind
    compute exactly as production runs them (pre-staged device batches
    pass through untouched, so HBM-cycled benches are unchanged).
    The fences are ``device_get`` of the last metrics — a value fetch
    completes only after the whole dependent step chain has executed,
    which holds on every backend (``block_until_ready`` was observed
    returning early on the tunneled axon TPU platform and must not be
    trusted for timing)."""
    dev_batches = data_mod.device_prefetch(mesh, batches, spec=spec,
                                           depth=max(0, prefetch))
    for _ in range(warmup):
        state, metrics = train_step(state, *next(dev_batches))
    jax.device_get(metrics["loss"])
    start = time.perf_counter()
    for _ in range(steps):
        state, metrics = train_step(state, *next(dev_batches))
    jax.device_get(metrics["loss"])
    return state, steps / (time.perf_counter() - start)
