"""Warm-restart startup instrumentation: the per-attempt phase tracker.

PR 2 made restarts frequent by design (preemption budgets, backoff) and
PR 4 made them durable (verified checkpoint resume) — which moves the
goodput bottleneck under churn to **time-to-first-step (TTFS)**: every
attempt pays DNS wait → jax.distributed rendezvous → checkpoint restore →
XLA compilation → first step before a single useful FLOP. This module is
the measurement half of the warm-restart fast path (train.py's overlapped
prologue and bootstrap's persistent compilation cache are the mechanism):

- :class:`StartupTracker` times each startup stage (RENDEZVOUS / RESTORE /
  COMPILE / FIRST_STEP); stages may overlap (restore and AOT compile run
  concurrently in the fast path), so each is timed independently and
  ``current_stage`` reports the innermost in-flight one for the
  pre-first-step liveness heartbeats.
- The resulting ``breakdown()`` dict is the wire format the heartbeat
  carries (``startup: {rendezvousSeconds, restoreSeconds, compileSeconds,
  firstStepSeconds, cacheHit}``) into ``status.startup`` and the
  ``job_startup_seconds{stage}`` histograms.
- ``cache_hit_count`` is the persistent-compilation-cache hit signal,
  fed by JAX's own monitoring events.

Stdlib-only on purpose: the controller (statusserver heartbeat validation,
schema enums) imports the stage names from here, and this module must not
drag jax into the control plane.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Dict, List, Optional

from tpu_operator.util import lockdep

# Startup stages, in nominal order. COMPILE and RESTORE overlap in the
# fast path; PREFETCH (the remote warm-start store download) overlaps
# RENDEZVOUS — its recorded duration is only the tail that outlived the
# rendezvous wait, i.e. what the store actually kept on the critical
# path; FIRST_STEP is the first optimizer step after the prologue (its
# duration includes any residual compile the AOT path didn't cover).
RENDEZVOUS = "RENDEZVOUS"
PREFETCH = "PREFETCH"
RESTORE = "RESTORE"
COMPILE = "COMPILE"
FIRST_STEP = "FIRST_STEP"

STAGES = (RENDEZVOUS, PREFETCH, RESTORE, COMPILE, FIRST_STEP)

# Heartbeat/status field name per stage (the ``status.startup`` keys).
STAGE_FIELDS = {
    RENDEZVOUS: "rendezvousSeconds",
    PREFETCH: "prefetchSeconds",
    RESTORE: "restoreSeconds",
    COMPILE: "compileSeconds",
    FIRST_STEP: "firstStepSeconds",
}

# Rendezvous happens in bootstrap.initialize, before any tracker exists
# (the payload's train_loop builds one much later) — recorded at module
# level and seeded into every new tracker of this process. The store
# prefetch runs in the same window, so it is recorded the same way.
# One lock guards all of this module's mutable globals: the writers are
# the main thread (bootstrap), the overlapped-prologue compile worker
# (via JAX's monitoring callback), and the heartbeat thread reading the
# breakdown — the escape analyzer flagged the unlocked mix.
_state_lock = lockdep.lock("startup._state_lock")
_rendezvous_seconds: Optional[float] = None  # guarded-by: _state_lock
_prefetch_seconds: Optional[float] = None  # guarded-by: _state_lock
_prefetch_hit: Optional[bool] = None  # guarded-by: _state_lock
# The persistent compilation cache dir bootstrap enabled ("" = cold).
_cache_dir: str = ""  # guarded-by: _state_lock


def record_rendezvous(seconds: float) -> None:
    global _rendezvous_seconds
    with _state_lock:
        _rendezvous_seconds = float(seconds)


def record_prefetch(seconds: float, hit: Optional[bool]) -> None:
    """Record the warm-start store prefetch: ``seconds`` is the tail the
    download kept on the critical path AFTER the rendezvous it overlapped
    (0.0 = fully hidden), ``hit`` whether it delivered anything (a
    checkpoint step or cache entries); None = store not configured."""
    global _prefetch_seconds, _prefetch_hit
    with _state_lock:
        _prefetch_seconds = float(seconds)
        _prefetch_hit = None if hit is None else bool(hit)


def reset_prefetch() -> None:
    """Test hook: clear the module-level prefetch record."""
    global _prefetch_seconds, _prefetch_hit
    with _state_lock:
        _prefetch_seconds = None
        _prefetch_hit = None


def set_cache_dir(path: str) -> None:
    global _cache_dir
    with _state_lock:
        _cache_dir = str(path or "")


def cache_dir() -> str:
    with _state_lock:
        return _cache_dir


# Persistent-cache hit counting via jax.monitoring (the same event stream
# jax's own telemetry uses). Registered lazily from the payload side —
# importing this module must never import jax. The counter is bumped by
# the monitoring callback — which fires on whichever thread compiles,
# including the overlapped prologue's AOT worker — and read by the
# heartbeat thread: an unlocked += there was a classic lost-update race
# (surfaced by the escape analyzer's first run).
_cache_hits = 0  # guarded-by: _state_lock
_listener_registered = False  # guarded-by: _state_lock


def ensure_cache_listener() -> bool:
    """Idempotently subscribe to JAX's compilation-cache events; returns
    False when the monitoring API is unavailable (config drift).

    Claim-then-register: the registered flag flips under the lock BEFORE
    the registration (and rolls back on failure), so two concurrent
    callers can never both register and double-count every cache hit —
    while the foreign jax.monitoring call itself runs outside the lock
    (``_state_lock`` is a leaf per the lock-order policy)."""
    global _listener_registered
    with _state_lock:
        if _listener_registered:
            return True
        _listener_registered = True
    try:
        from jax import monitoring

        def _on_event(event: str, **_kw: Any) -> None:
            global _cache_hits
            if event == "/jax/compilation_cache/cache_hits":
                with _state_lock:
                    _cache_hits += 1

        monitoring.register_event_listener(_on_event)
        return True
    except Exception:  # noqa: BLE001 — best-effort telemetry
        with _state_lock:
            _listener_registered = False  # un-claim: a later call retries
        return False


def cache_hit_count() -> int:
    """Persistent compilation-cache hits observed so far this process
    (0 until :func:`ensure_cache_listener` ran and a compile hit)."""
    with _state_lock:
        return _cache_hits


class StartupTracker:
    """Times the startup stages of one attempt. Thread-safe: the fast path
    runs RESTORE (main thread) and COMPILE (worker thread) concurrently."""

    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self._lock = lockdep.lock("StartupTracker._lock")
        self._active: List[str] = []  # innermost last
        self.durations: Dict[str, float] = {}
        self.cache_hit: Optional[bool] = None
        # Absolute clock() stamp of first-step completion (TTFS fences).
        self.first_step_done_at: Optional[float] = None
        with _state_lock:
            self.prefetch_hit: Optional[bool] = _prefetch_hit
            if _rendezvous_seconds is not None:
                self.durations[RENDEZVOUS] = _rendezvous_seconds
            if _prefetch_seconds is not None:
                self.durations[PREFETCH] = _prefetch_seconds

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = self._clock()
        with self._lock:
            self._active.append(name)
        try:
            yield
        finally:
            dt = self._clock() - t0
            with self._lock:
                if name in self._active:
                    self._active.remove(name)
                # Keep the max across re-entries (a retried restore walk
                # re-enters the stage; the attempt paid the longest one).
                self.durations[name] = max(self.durations.get(name, 0.0), dt)
                if name == FIRST_STEP:
                    self.first_step_done_at = self._clock()

    def current_stage(self) -> Optional[str]:
        """The innermost in-flight stage — what a pre-first-step liveness
        heartbeat reports as ``startupStage``."""
        with self._lock:
            return self._active[-1] if self._active else None

    def breakdown(self) -> Dict[str, Any]:
        """The wire-format startup breakdown (only stages actually timed)."""
        with self._lock:
            out: Dict[str, Any] = {
                STAGE_FIELDS[name]: round(self.durations[name], 6)
                for name in STAGES if name in self.durations
            }
            if self.cache_hit is not None:
                out["cacheHit"] = bool(self.cache_hit)
            if self.prefetch_hit is not None:
                out["prefetchHit"] = bool(self.prefetch_hit)
        return out


def new_tracker(clock: Callable[[], float] = time.perf_counter
                ) -> StartupTracker:
    """Fresh per-attempt tracker, pre-seeded with this process's
    rendezvous time (bootstrap.initialize records it)."""
    return StartupTracker(clock=clock)
