"""Pallas TPU flash-attention block kernel — the hot op of long-context jobs.

The streaming-softmax merge of one visiting K/V block into a resident query
block is where ring attention (ring_attention.py) spends its FLOPs. The
plain-XLA path materializes the [B, H, Tq, Tk] score tensor in HBM between
ops; this kernel keeps everything for one (batch, head, q-block) grid cell
in VMEM — scores never leave the chip — and tiles the K dimension with an
in-kernel loop, exactly the flash-attention recurrence (public technique;
Dao et al. 2022, and the blockwise form of Liu et al.'s ring attention):

    m' = max(m, rowmax(S))           S = (Q K^T) * scale, masked
    l' = l * e^{m-m'} + rowsum(e^{S-m'})
    o' = o * e^{m-m'} + e^{S-m'} V

Layouts are MXU-native: [B, H, T, D] with D on lanes; Q@K^T and P@V are
``dot_general`` contractions hitting the systolic array; masks are computed
from ``broadcasted_iota`` (2D, as TPU requires). Global sequence offsets
arrive as scalar-prefetch values so one compiled kernel serves every ring
step (the offsets are traced, not baked into the grid).

Grouped-query attention is kernel-native (Ainslie et al. 2023, public
technique): K/V may carry ``kv_heads < heads`` with
``group = heads // kv_heads`` query heads per K/V head. The grid's head
dimension runs over *KV* heads and the Q/O/L/M BlockSpecs carry a
``group``-deep head block that the kernel flattens to a
``[group*blk_q, D]`` panel — one bigger MXU matmul per tile, K/V blocks
fetched once per group instead of once per query head, and dK/dV
accumulated directly at KV size. No ``jnp.repeat`` anywhere: the repeated
K/V tensor (and its gradient) that a broadcast-based GQA materializes in
HBM — the 4x K/V bandwidth and memory cost at kv4/16 — never exists.
Q heads map to K/V heads contiguously (query head h reads KV head
h // group), matching the `jnp.repeat(k, group, axis=2)` oracle.

Differentiation — fully fused, both directions:

- :func:`flash_attention` (the single-shard path every payload calls) is a
  whole-attention ``jax.custom_vjp``: the forward saves only (q, k, v, out,
  L) where ``L = m + log l`` is the per-row logsumexp, and the backward runs
  two Pallas kernels (`_bwd_dq_kernel`, `_bwd_dkv_kernel`) implementing the
  standard flash-attention backward recurrence (Dao et al. 2022): recompute
  the score tile in VMEM from Q/K and L, form ``dS = P * (dP - D)`` with
  ``D = rowsum(dO * O)``, and accumulate dQ / dK / dV — the [T, T] score
  and probability tensors never exist in HBM in either direction. (The
  pre-round-2 backward differentiated the jnp merge, which materialized
  the f32 [B, H, T, T] scores — 4.3 GB at B=16 H=16 T=2048, an HBM OOM
  and the dominant bandwidth cost of training steps.)
- :func:`merge_kv_block` (the ring building block) keeps its per-merge
  custom VJP for standalone use; ring_attention.py now differentiates at
  the ring level instead (a backward ring over the same two kernels via
  :func:`attention_block_grads`), so the carry-threaded merge backward is
  off the training hot path.

On non-TPU backends the kernels run in interpret mode (tests) or fall back
to the plain-jnp reference math.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

Carry = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]  # o, l, m


def _pick_block(t: int, target: int = 512, floor: int = 128) -> int:
    """Largest power-of-two divisor of ``t`` up to ``target`` (whole span
    when ``t`` has no such divisor — tiny test shapes). ``floor`` drops to
    64 for large GQA groups, whose flattened panels multiply every q-row
    by the group factor. ``target`` is rounded down to a power of two
    first: the budget formulas divide by the group factor, and a
    non-power-of-two group (e.g. 12 heads / 4 KV heads = group 3) would
    otherwise make the halving loop skip every actual divisor of ``t``
    and fall through to the whole span — the exact VMEM blowup this
    helper exists to cap."""
    b = 1 << (max(1, target).bit_length() - 1)
    while b >= floor:
        if t % b == 0:
            return b
        b //= 2
    return t


def _panel_blocks(tq: int, tk: int, group: int, q_budget: int,
                  area: int, k_cap: int, q_cap: int = 512
                  ) -> Tuple[int, int]:
    """Shared (blk_q, blk_k) selection for all three kernel families:
    blk_q targets ``q_budget // group`` flattened rows (capped at
    ``q_cap``), then blk_k fills the f32 score-panel area budget ``area``
    up to ``k_cap``. The three callers differ only in budgets — one
    definition so a resweep cannot desynchronize them."""
    floor = 64 if group > 8 else 128
    blk_q = _pick_block(tq, target=max(floor, min(q_cap, q_budget // group)),
                        floor=floor)
    flat = group * blk_q
    blk_k = _pick_block(tk, target=max(128, min(k_cap, area // flat)))
    return blk_q, blk_k


def _blocks_override(env: str, tq: int, tk: int) -> Optional[Tuple[int, int]]:
    """Sweep hook shared by the block pickers: ``env`` = "blk_q,blk_k"
    overrides the heuristic. Read at trace time (like
    TPU_OPERATOR_PALLAS) — a resweep runs one fresh process per
    candidate. A non-dividing override RAISES instead of falling through:
    the hook's only consumer is sweeps, and silently running the
    heuristic blocks would record a time under the wrong label —
    corrupting exactly the measurements the default budgets are derived
    from."""
    import os

    override = os.environ.get(env, "")
    if override:
        bq, bk = (int(x) for x in override.split(","))
        if tq % bq != 0 or tk % bk != 0:
            raise ValueError(
                f"{env}={override} does not divide (tq={tq}, tk={tk})")
        return bq, bk
    return None


def _fwd_blocks(tq: int, tk: int, group: int) -> Tuple[int, int]:
    """Fused forward kernel blocks: flattened-panel area capped at
    1024x1024 f32 (4 MB). Swept at steady state on v5e at the flagship
    attention shape (B8 T2048 D128, causal; long timing windows — short
    windows are dispatch-latency-bound on the tunnel and invert the
    ranking): MHA (512,1024) 4.02 ms beats (512,512) 4.14 and (256,512)
    5.26; GQA kv4 (256,1024) 2.77 ms beats (256,512) 3.17 and (512,512)
    3.19. (512,1024) at group 4 (8 MB panel) fails to compile — the area
    cap is the compile-feasibility boundary, not taste. Round-5 resweep:
    MHA prefers the full (1024,1024) panel — 40.3 vs 43.7 ms at T32768,
    2.93 vs 3.02 at T2048 — so the q-cap is 1024 (only group 1 reaches
    it; GQA shapes keep their round-4 optima, and (1024,*) at group 4
    does not compile). ``TPU_OPERATOR_FWD_BLOCKS=q,k`` overrides
    (sweep hook)."""
    override = _blocks_override("TPU_OPERATOR_FWD_BLOCKS", tq, tk)
    if override:
        return override
    return _panel_blocks(tq, tk, group, q_budget=1024,
                         area=1024 * 1024, k_cap=1024, q_cap=1024)


def _merge_blocks(tq: int, tk: int, group: int) -> Tuple[int, int]:
    """Ring *merge* kernel blocks. On top of the score panel this kernel
    streams six f32 o/l/m carry blocks (in and out) and so sits much
    closer to the 16 MB VMEM scope than the fused forward — a 2048-row
    panel measured 1.75 MB over the cap on v5e at the round-3 budget.
    Keeps the round-3 1024x512 panel area; the fused forward's doubled
    budget was swept without these carry streams and does not transfer."""
    return _panel_blocks(tq, tk, group, q_budget=1024,
                         area=1024 * 512, k_cap=512)


def _bwd_blocks(tq: int, tk: int, group: int) -> Tuple[int, int]:
    """Backward kernel blocks: three [group*blk_q, blk_k] f32 panels
    (P, dP, dS) live at once — half the forward's q rows. Swept at steady
    state with the FULL backward — grad wrt (q, k, v), both kernels live:
    the round-4 sweep differentiated wrt q only, which let XLA dead-code-
    eliminate the dK/dV kernel entirely and tuned blocks for half the
    backward. Round-5 full-grad sweep (median of 3 long windows): GQA kv4
    (512,512) wins at BOTH T2048 (7.85 ms vs 8.55 at the old 128,1024)
    and T32768 (139.2 vs 147.2); MHA keeps (512,1024) (9.87/152.2 ms —
    its 12 MB group-4 panel equivalent (512,1024) does not compile at
    group 4). The q_budget 2048 / area 1024x1024 pair lands exactly
    those per group. ``TPU_OPERATOR_BWD_BLOCKS=q,k`` overrides both
    (sweep hook; read at trace time, like TPU_OPERATOR_PALLAS)."""
    override = _blocks_override("TPU_OPERATOR_BWD_BLOCKS", tq, tk)
    if override:
        return override
    return _panel_blocks(tq, tk, group, q_budget=2048,
                         area=1024 * 1024, k_cap=1024)


def _group_of(q: jnp.ndarray, k: jnp.ndarray) -> int:
    """Query heads per K/V head, from [B, H, T, D] blocks. 1 = MHA."""
    hq, hkv = q.shape[1], k.shape[1]
    if hkv <= 0 or hq % hkv != 0:
        raise ValueError(
            f"query heads {hq} must be a multiple of K/V heads {hkv}")
    return hq // hkv


def _kernel_feasible(t: int) -> bool:
    """Whether a sequence span tiles into VMEM-sized blocks: a 128-multiple
    (proper tiling) or small enough that the whole span is one block. Odd
    long lengths (e.g. 4000) would otherwise become a whole-span block whose
    score tile busts VMEM — those fall back to the jnp path."""
    return t % 128 == 0 or t <= 512


def init_carry(batch: int, heads: int, tq: int, dim: int) -> Carry:
    """Zero accumulators for a fresh streaming softmax ([B,H,Tq,D] f32 out,
    [B,H,Tq,1] row-sum / row-max). ``heads`` is *query* heads — the carry
    is per query row regardless of K/V grouping."""
    return (
        jnp.zeros((batch, heads, tq, dim), jnp.float32),
        jnp.zeros((batch, heads, tq, 1), jnp.float32),
        jnp.full((batch, heads, tq, 1), NEG_INF, jnp.float32),
    )


def finalize(carry: Carry, dtype) -> jnp.ndarray:
    """carry → attention output [B,H,Tq,D]; fully-masked rows yield 0.

    A row that never saw an unmasked key keeps m = NEG_INF (its p values
    were exp(NEG_INF - NEG_INF) = 1, so l alone cannot detect it); the
    m-based guard is what makes the all-masked case return 0, not mean(V).
    """
    o, l, m = carry
    valid = m > NEG_INF / 2
    out = jnp.where(valid, o / jnp.maximum(l, 1e-30), 0.0)
    return out.astype(dtype)


# --- reference merge (backward path + non-TPU fallback) -----------------------

def _stride_of(offsets: jnp.ndarray) -> jnp.ndarray:
    """Position stride from an offsets array: [q_off, k_off] means
    contiguous (stride 1); [q_off, k_off, stride] supports striped
    sequence layouts (ring_attention stripe mode, Brandon et al. 2023),
    where slot i holds global position off + stride*i."""
    if offsets.shape[0] >= 3:
        return offsets[2]
    return jnp.int32(1)


def _normalize_offsets(offsets: jnp.ndarray) -> jnp.ndarray:
    """int32 [q_off, k_off, stride] — pads the contiguous two-element form
    with stride 1 so the kernels (which scalar-prefetch index [2]) see one
    layout."""
    offsets = offsets.astype(jnp.int32)
    if offsets.shape[0] == 2:
        offsets = jnp.concatenate([offsets, jnp.ones((1,), jnp.int32)])
    return offsets


def _merge_ref(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
               o: jnp.ndarray, l: jnp.ndarray, m: jnp.ndarray,
               offsets: jnp.ndarray, causal: bool) -> Carry:
    """The same recurrence in plain jnp on [B,H,T,D] blocks (K/V may be at
    kv_heads). Positions are int32 end to end — float32 cannot represent
    sequence indices past 2^24, which is squarely inside the long-context
    regime this serves."""
    b, hq, tq, d = q.shape
    hkv, tk = k.shape[1], k.shape[2]
    group = _group_of(q, k)
    scale = d ** -0.5
    qg = q.reshape(b, hkv, group, tq, d).astype(jnp.float32)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k.astype(jnp.float32)) * scale
    if causal:
        stride = _stride_of(offsets)
        q_pos = offsets[0] + stride * jnp.arange(tq, dtype=jnp.int32)
        k_pos = offsets[1] + stride * jnp.arange(tk, dtype=jnp.int32)
        s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, NEG_INF)
    s = s.reshape(b, hq, tq, tk)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhgqk,bhkd->bhgqd",
                    p.reshape(b, hkv, group, tq, tk),
                    v.astype(jnp.float32)).reshape(b, hq, tq, d)
    o_new = o * alpha + pv
    return o_new, l_new, m_new


# --- the kernel ---------------------------------------------------------------

def _causal_mask(s, q_lo, k_lo, stride, blk_q: int, group: int):
    """Mask a flattened [group*blk_q, blk_k] score panel: row r is query
    slot r % blk_q (every group repeats the same q-block), column c is key
    slot c; global positions are off + stride*slot."""
    rows, blk_k = s.shape
    row = lax.broadcasted_iota(jnp.int32, (rows, blk_k), 0)
    q_slot = row if group == 1 else lax.rem(row, blk_q)
    q_pos = q_lo + stride * q_slot
    k_pos = k_lo + stride * lax.broadcasted_iota(jnp.int32, (rows, blk_k), 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _merge_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, l_ref, m_ref,
                  o_out, l_out, m_out, *, causal: bool, scale: float,
                  group: int):
    """One (batch, kv-head, q-block, k-tile) grid cell. K tiling lives in
    the grid — only one [blk_k, D] K/V tile is VMEM-resident at a time, so
    the kernel compiles at arbitrary per-shard sequence lengths. The
    (o, l, m) accumulators ride the output blocks, whose index map is
    constant in the k dimension: Pallas keeps them VMEM-resident across all
    k-tiles of a q-block (the innermost grid dim), and the carry from the
    previous ring step seeds them at ik == 0. The q/accumulator blocks are
    ``group`` heads deep (all query heads of this KV head), flattened to one
    [group*blk_q, D] panel so the whole group shares a single K/V fetch and
    a single MXU contraction."""
    blk_q = q_ref.shape[2]
    blk_k = k_ref.shape[2]
    rows = group * blk_q
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _seed():
        o_out[...] = o_ref[...]
        l_out[...] = l_ref[...]
        m_out[...] = m_ref[...]

    # int32 positions: float32 loses integer resolution past 2^24, well
    # inside the long-context regime. Slot i holds global position
    # off + stride*i (stride 1 = contiguous; > 1 = striped layout).
    stride = offs_ref[2]
    q_lo = offs_ref[0] + stride * (iq * blk_q)
    k_lo = offs_ref[1] + stride * (ik * blk_k)

    # Causal skip: a k-tile entirely in this q-block's future contributes
    # nothing — skip its matmuls (≈2× effective throughput for causal).
    @pl.when(jnp.logical_or(not causal,
                            q_lo + stride * (blk_q - 1) >= k_lo))
    def _merge():
        # Matmuls on the inputs' native dtype (bf16 → full-rate MXU) with
        # f32 accumulation; f32 inputs keep full-precision matmuls.
        q = q_ref[0].reshape(rows, -1)
        o = o_out[0].reshape(rows, -1)                   # [rows, D] f32
        l = l_out[0].reshape(rows, 1)
        m = m_out[0].reshape(rows, 1)
        k_blk = k_ref[0, 0]                              # [blk_k, D]
        # S = Q K^T on the MXU (contract D, keep f32 accumulation).
        s = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, q_lo, k_lo, stride, blk_q, group)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        v_blk = v_ref[0, 0]
        o_new = o * alpha + lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_out[0] = o_new.reshape(group, blk_q, -1)
        l_out[0] = l_new.reshape(group, blk_q, 1)
        m_out[0] = m_new.reshape(group, blk_q, 1)


def _merge_pallas(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                  o: jnp.ndarray, l: jnp.ndarray, m: jnp.ndarray,
                  offsets: jnp.ndarray, causal: bool,
                  interpret: bool) -> Carry:
    b, hq, tq, d = q.shape
    hkv, tk = k.shape[1], k.shape[2]
    group = _group_of(q, k)
    blk_q, blk_k = _merge_blocks(tq, tk, group)
    scale = d ** -0.5

    def qo_map(ib, ih, iq, ik, offs):
        return (ib, ih, iq, 0)

    def kv_map(ib, ih, iq, ik, offs):
        return (ib, ih, ik, 0)

    q_spec = pl.BlockSpec((1, group, blk_q, d), qo_map)
    kv_spec = pl.BlockSpec((1, 1, blk_k, d), kv_map)
    acc_spec = pl.BlockSpec((1, group, blk_q, d), qo_map)
    vec_spec = pl.BlockSpec((1, group, blk_q, 1), qo_map)

    kernel = functools.partial(_merge_kernel, causal=causal, scale=scale,
                               group=group)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            # k-tiles innermost: the accumulator output blocks revisit the
            # same index across them and stay VMEM-resident.
            grid=(b, hkv, tq // blk_q, tk // blk_k),
            in_specs=[q_spec, kv_spec, kv_spec, acc_spec, vec_spec, vec_spec],
            out_specs=[acc_spec, vec_spec, vec_spec],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(o.shape, o.dtype),
            jax.ShapeDtypeStruct(l.shape, l.dtype),
            jax.ShapeDtypeStruct(m.shape, m.dtype),
        ],
        interpret=interpret,
    )(offsets, q, k, v, o, l, m)


# --- differentiable wrapper ---------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _merge(causal: bool, interpret: bool, q, k, v, o, l, m, offsets) -> Carry:
    return _merge_pallas(q, k, v, o, l, m, offsets, causal, interpret)


def _merge_fwd(causal, interpret, q, k, v, o, l, m, offsets):
    out = _merge_pallas(q, k, v, o, l, m, offsets, causal, interpret)
    return out, (q, k, v, o, l, m, offsets)


def _merge_bwd(causal, _interpret, residuals, g):
    import numpy as np

    q, k, v, o, l, m, offsets = residuals
    _out, vjp = jax.vjp(
        lambda q_, k_, v_, o_, l_, m_: _merge_ref(q_, k_, v_, o_, l_, m_,
                                                  offsets, causal),
        q, k, v, o, l, m,
    )
    dq, dk, dv, do, dl, dm = vjp(g)
    # int32 positions carry no gradient: the float0 cotangent is JAX's
    # "symbolic zero for integer primals".
    d_offs = np.zeros(offsets.shape, jax.dtypes.float0)
    return dq, dk, dv, do, dl, dm, d_offs


_merge.defvjp(_merge_fwd, _merge_bwd)


def use_pallas_default() -> bool:
    """Kernel on real TPUs; jnp fallback elsewhere (tests opt in to the
    interpreter explicitly). ``TPU_OPERATOR_PALLAS`` overrides both ways:
    ``force``/``1`` selects the kernels even off-TPU (interpret mode —
    how the dryrun and the sharded-parity tests put the kernel path under
    GSPMD/shard_map partitioning on the CPU mesh), ``off``/``0`` forces
    the jnp path even on TPU. Read at trace time: set it before building
    a payload, not between steps of an already-jitted one."""
    import os

    mode = os.environ.get("TPU_OPERATOR_PALLAS", "").lower()
    if mode in ("1", "true", "force"):
        return True
    if mode in ("0", "false", "off"):
        return False
    return jax.default_backend() == "tpu"


# --- fused single-shard forward -----------------------------------------------
#
# The ring merge above streams its (o, l, m) carry through HBM because ring
# steps are separate kernel launches — that is the price of the ring API.
# The single-shard forward (what every non-ring payload calls, including the
# flagship) has no such constraint, and paying it anyway measured 14 TFLOPS
# effective at the flagship attention shape: six extra f32 block streams
# (o/l/m in and out), a separate finalize pass over f32 [B,H,T,D], and a
# VMEM high-water within ~2 MB of the 16 MB scope cap that defeated DMA
# double-buffering. This kernel is the standard fused form instead: the
# accumulators live in VMEM *scratch* across the k-grid, nothing but q/k/v
# is read, and the only writes are the final bf16 output block and the f32
# logsumexp residual at the last k-tile. Matmuls take the inputs' native
# dtype (bf16 rides the MXU at full rate; f32 accumulation via
# preferred_element_type) — f32 inputs keep full-precision matmuls so the
# interpret-mode tests stay bit-comparable to the jnp oracle. Measured at
# the flagship attention shape (B8 T2048 H16 KV4 D128, causal, bf16,
# steady state): 4.89 ms (carry-stream path, already with native-dtype
# matmuls) → 2.77 ms fused; in the flagship train step, 45.1k → 48.2k
# tokens/sec together with the backward's native-dtype matmuls.


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, L_ref, acc_scr, l_scr, m_scr, *,
                causal: bool, scale: float, group: int, nk: int):
    """One (batch, kv-head, q-block, k-tile) cell of the fused forward.
    Streaming-softmax state rides VMEM scratch (persistent across the
    innermost k dimension), is reset at ik == 0, and collapses to the
    normalized output + logsumexp at ik == nk - 1."""
    blk_q = q_ref.shape[2]
    rows = group * blk_q
    blk_k = k_ref.shape[2]
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    q_lo = iq * blk_q
    k_lo = ik * blk_k

    @pl.when(ik == 0)
    def _reset():
        acc_scr[...] = jnp.zeros_like(acc_scr)
        l_scr[...] = jnp.zeros_like(l_scr)
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)

    # Causal skip: tiles entirely in the future contribute nothing. Their
    # K/V DMA is also elided — see the clamped index map in the caller.
    @pl.when(jnp.logical_or(not causal, q_lo + blk_q - 1 >= k_lo))
    def _tile():
        q = q_ref[0].reshape(rows, -1)
        s = lax.dot_general(q, k_ref[0, 0], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, q_lo, k_lo, jnp.int32(1), blk_q, group)
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        acc_scr[...] = acc_scr[...] * alpha + lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[...] = m_new

    @pl.when(ik == nk - 1)
    def _emit():
        m = m_scr[...]
        l = l_scr[...]
        valid = m > NEG_INF / 2
        out = jnp.where(valid, acc_scr[...] / jnp.maximum(l, 1e-30), 0.0)
        o_ref[0] = out.reshape(group, blk_q, -1).astype(o_ref.dtype)
        L_ref[0] = jnp.where(
            valid, m + jnp.log(jnp.maximum(l, 1e-30)), 0.0
        ).reshape(group, blk_q, 1)


def _flash_fwd_pallas(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      causal: bool, interpret: bool):
    """(out [B,H,T,D] in q.dtype, L [B,H,T,1] f32) via the fused kernel."""
    b, hq, tq, d = q.shape
    hkv, tk = k.shape[1], k.shape[2]
    group = _group_of(q, k)
    blk_q, blk_k = _fwd_blocks(tq, tk, group)
    nk = tk // blk_k
    scale = d ** -0.5

    def qo_map(ib, ih, iq, ik):
        return (ib, ih, iq, 0)

    if causal:
        def kv_map(ib, ih, iq, ik):
            # Tiles the causal guard skips clamp to the last contributing
            # k-tile of this q-block — a revisit of an already-resident
            # block, so the skipped tile costs no DMA either.
            last = lax.div((iq + 1) * blk_q - 1, blk_k)
            return (ib, ih, jnp.minimum(ik, last), 0)
    else:
        def kv_map(ib, ih, iq, ik):
            return (ib, ih, ik, 0)

    q_spec = pl.BlockSpec((1, group, blk_q, d), qo_map)
    kv_spec = pl.BlockSpec((1, 1, blk_k, d), kv_map)
    o_spec = pl.BlockSpec((1, group, blk_q, d), qo_map)
    L_spec = pl.BlockSpec((1, group, blk_q, 1), qo_map)
    rows = group * blk_q

    return pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, scale=scale,
                          group=group, nk=nk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(b, hkv, tq // blk_q, nk),
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=[o_spec, L_spec],
            scratch_shapes=[
                pltpu.VMEM((rows, d), jnp.float32),
                pltpu.VMEM((rows, 1), jnp.float32),
                pltpu.VMEM((rows, 1), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, tq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# --- fused backward ------------------------------------------------------------
#
# The flash backward needs, per (q-block, k-block) tile pair, only the VMEM
# recomputation of that tile's scores:  S = scale QK^T,  P = exp(S - L),
# dV += P^T dO,  dP = dO V^T,  dS = P (dP - D),  dQ += scale dS K,
# dK += scale dS^T Q,  with L the forward's row logsumexp and
# D = rowsum(dO * O) precomputed per row. Two kernels split the work by
# which accumulator can stay VMEM-resident: dQ tiles accumulate over k
# (k innermost in the grid), dK/dV tiles over q (q innermost). Under GQA
# the q-side blocks are group-deep and flattened exactly as in the forward;
# dK/dV accumulate the whole group's contribution in one P^T/dS^T matmul,
# landing at KV size with no post-hoc reduction.


def _logsumexp_rows(l: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """Per-row logsumexp from the streaming carry, [B,H,T,1] f32. Rows that
    never saw an unmasked key (m still NEG_INF) get L = 0: their backward
    tiles then compute P = exp(NEG_INF - 0) = 0 instead of NaN."""
    return jnp.where(m > NEG_INF / 2,
                     m + jnp.log(jnp.maximum(l, 1e-30)), 0.0)


def _bwd_tile_p_ds(q_ref, k_ref, v_ref, g_ref, L_ref, o_ref, q_lo, k_lo,
                   stride, causal: bool, scale: float, group: int,
                   fused_d: bool):
    """The shared per-tile backward recurrence: recompute this tile's
    probabilities from Q/K and the forward's logsumexp, then
    dS = P (dP - D). ``fused_d``: D = rowsum(dO * O) is computed
    IN-KERNEL from the forward output block — O streams through the same
    q-indexed BlockSpec D used to, and the separate XLA pass that
    materialized D (one full read of dO and O per invocation) is gone;
    the rowsum is VPU noise (rows x D MACs) next to the tile matmuls.
    With ``fused_d=False``, ``o_ref`` is the precomputed [.., blk_q, 1]
    D block instead — the backward ring's path, which reuses one D
    across every ring step rather than re-streaming the full [B,H,T,D]
    output each step. Both backward kernels build their accumulations
    from this one definition so the recurrence cannot desynchronize
    between dQ and dK/dV. q/g/L/O arrive group-deep and leave flattened
    to [group*blk_q, ·] panels. Matmuls run on the inputs' native dtype
    with f32 accumulation — bf16 training inputs take the full-rate MXU
    path; f32 (test) inputs keep full-precision matmuls."""
    blk_q = q_ref.shape[2]
    rows = group * blk_q
    q = q_ref[0].reshape(rows, -1)
    k_blk = k_ref[0, 0]
    v_blk = v_ref[0, 0]
    g = g_ref[0].reshape(rows, -1)
    if fused_d:
        o = o_ref[0].reshape(rows, -1)
        d_row = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True)
    else:
        d_row = o_ref[0].reshape(rows, 1)
    s = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask(s, q_lo, k_lo, stride, blk_q, group)
    p = jnp.exp(s - L_ref[0].reshape(rows, 1))            # [rows, blk_k]
    dp = lax.dot_general(g, v_blk, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    ds = p * (dp - d_row)
    return q, k_blk, g, p, ds


def _bwd_dq_kernel(offs_ref, q_ref, k_ref, v_ref, g_ref, L_ref, o_ref,
                   dq_out, acc_scr, *, causal: bool, scale: float,
                   group: int, nk: int, fused_d: bool):
    """dQ for one (batch, kv-head, q-block) — k-tiles innermost; the
    accumulator lives in f32 VMEM scratch and the output block is written
    once, at the last k-tile, cast to the requested gradient dtype (bf16
    in training): the f32 [B, H, T, D] HBM round-trip plus the separate
    downstream cast of the old output-block accumulation never happen."""
    blk_q = q_ref.shape[2]
    blk_k = k_ref.shape[2]
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    stride = offs_ref[2]
    q_lo = offs_ref[0] + stride * (iq * blk_q)
    k_lo = offs_ref[1] + stride * (ik * blk_k)

    @pl.when(ik == 0)
    def _zero():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(jnp.logical_or(not causal,
                            q_lo + stride * (blk_q - 1) >= k_lo))
    def _acc():
        _q, k_blk, _g, _p, ds = _bwd_tile_p_ds(
            q_ref, k_ref, v_ref, g_ref, L_ref, o_ref, q_lo, k_lo, stride,
            causal, scale, group, fused_d)
        acc_scr[...] += scale * lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _emit():
        dq_out[0] = acc_scr[...].reshape(group, blk_q, -1).astype(
            dq_out.dtype)


def _bwd_dkv_kernel(offs_ref, q_ref, k_ref, v_ref, g_ref, L_ref, o_ref,
                    dk_out, dv_out, dk_scr, dv_scr, *, causal: bool,
                    scale: float, group: int, nq: int, fused_d: bool):
    """dK/dV for one (batch, kv-head, k-block) — q-tiles innermost; both
    accumulators ride f32 VMEM scratch and emit once at the last q-tile
    (cast to the gradient dtype), like the dq kernel. The flattened
    [group*blk_q, blk_k] P/dS panels contract over their row dim, so each
    matmul already sums the whole query-head group into the KV-sized
    output."""
    blk_q = q_ref.shape[2]
    blk_k = k_ref.shape[2]
    ik = pl.program_id(2)
    iq = pl.program_id(3)
    stride = offs_ref[2]
    q_lo = offs_ref[0] + stride * (iq * blk_q)
    k_lo = offs_ref[1] + stride * (ik * blk_k)

    @pl.when(iq == 0)
    def _zero():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(jnp.logical_or(not causal,
                            q_lo + stride * (blk_q - 1) >= k_lo))
    def _acc():
        q, _k, g, p, ds = _bwd_tile_p_ds(
            q_ref, k_ref, v_ref, g_ref, L_ref, o_ref, q_lo, k_lo, stride,
            causal, scale, group, fused_d)
        # dV += P^T dO (rows contract: sums over q-slots and the group)
        dv_scr[...] += lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dK += dS^T Q
        dk_scr[...] += scale * lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _emit():
        dk_out[0, 0] = dk_scr[...].astype(dk_out.dtype)
        dv_out[0, 0] = dv_scr[...].astype(dv_out.dtype)


def _bwd_pallas(q, k, v, g, L, d_or_o, offsets, causal: bool,
                interpret: bool, grad_dtype, fused_d: bool):
    b, hq, tq, d = q.shape
    hkv, tk = k.shape[1], k.shape[2]
    group = _group_of(q, k)
    blk_q, blk_k = _bwd_blocks(tq, tk, group)
    scale = d ** -0.5
    d_width = d if fused_d else 1  # O blocks when fused, D rows when not

    def q_map(ib, ih, iq, ik, offs):
        return (ib, ih, iq, 0)

    nq, nk = tq // blk_q, tk // blk_k
    rows = group * blk_q

    if causal:
        def k_map(ib, ih, iq, ik, offs):
            # Causally-skipped k-tiles (entirely in this q-block's future)
            # clamp to the last contributing tile — an already-resident
            # revisit, so the skipped tile costs no K/V DMA (the same
            # trick as the fused forward). Global positions: slot i of q
            # is offs[0] + stride·i, of k offs[1] + stride·i; tile ik
            # contributes iff k_lo(ik) <= q_hi(iq), i.e.
            # ik <= floor((stride·((iq+1)·blk_q − 1) − diff)/(stride·blk_k))
            # with diff = offs[1] − offs[0] (floor_divide handles either
            # sign exactly).
            diff = offs[1] - offs[0]
            last = jnp.floor_divide(
                offs[2] * ((iq + 1) * blk_q - 1) - diff,
                offs[2] * blk_k)
            return (ib, ih, jnp.clip(jnp.minimum(ik, last), 0, nk - 1), 0)
    else:
        def k_map(ib, ih, iq, ik, offs):
            return (ib, ih, ik, 0)

    q_spec = pl.BlockSpec((1, group, blk_q, d), q_map)
    kv_spec = pl.BlockSpec((1, 1, blk_k, d), k_map)
    row_spec = pl.BlockSpec((1, group, blk_q, 1), q_map)

    do_spec = pl.BlockSpec((1, group, blk_q, d_width), q_map)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale,
                          group=group, nk=nk, fused_d=fused_d),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, hkv, tq // blk_q, tk // blk_k),
            in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, do_spec],
            out_specs=[q_spec],
            scratch_shapes=[pltpu.VMEM((rows, d), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct(q.shape, grad_dtype)],
        interpret=interpret,
    )(offsets, q, k, v, g, L, d_or_o)[0]

    # dkv grid transposes the block roles: k-blocks outer, q-tiles inner.
    if causal:
        def qT_map(ib, ih, ik, iq, offs):
            # Mirror clamp: q-tiles entirely before this k-block's past
            # (q_hi < k_lo) contribute nothing — clamp up to the first
            # contributing tile, iq >= ceil((diff + stride·(ik·blk_k −
            # blk_q + 1)) / (stride·blk_q)).
            diff = offs[1] - offs[0]
            num = diff + offs[2] * (ik * blk_k - blk_q + 1)
            den = offs[2] * blk_q
            first = jnp.floor_divide(num + den - 1, den)
            return (ib, ih, jnp.clip(jnp.maximum(iq, first), 0, nq - 1), 0)
    else:
        def qT_map(ib, ih, ik, iq, offs):
            return (ib, ih, iq, 0)

    def kT_map(ib, ih, ik, iq, offs):
        return (ib, ih, ik, 0)

    qT_spec = pl.BlockSpec((1, group, blk_q, d), qT_map)
    kvT_spec = pl.BlockSpec((1, 1, blk_k, d), kT_map)
    rowT_spec = pl.BlockSpec((1, group, blk_q, 1), qT_map)

    doT_spec = pl.BlockSpec((1, group, blk_q, d_width), qT_map)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale,
                          group=group, nq=nq, fused_d=fused_d),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, hkv, tk // blk_k, tq // blk_q),
            in_specs=[qT_spec, kvT_spec, kvT_spec, qT_spec, rowT_spec,
                      doT_spec],
            out_specs=[kvT_spec, kvT_spec],
            scratch_shapes=[pltpu.VMEM((blk_k, d), jnp.float32),
                            pltpu.VMEM((blk_k, d), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct(k.shape, grad_dtype),
                   jax.ShapeDtypeStruct(v.shape, grad_dtype)],
        interpret=interpret,
    )(offsets, q, k, v, g, L, d_or_o)
    return dq, dk, dv


def _bwd_ref(q, k, v, g, L, D, offsets, causal: bool):
    """The same tile math in plain jnp (CPU fallback / infeasible shapes);
    materializes this block pair's scores, which is fine at test sizes."""
    b, hq, tq, d = q.shape
    hkv, tk = k.shape[1], k.shape[2]
    group = _group_of(q, k)
    scale = d ** -0.5
    qg = q.reshape(b, hkv, group, tq, d).astype(jnp.float32)
    gg = g.reshape(b, hkv, group, tq, d).astype(jnp.float32)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k.astype(jnp.float32)) * scale
    if causal:
        stride = _stride_of(offsets)
        q_pos = offsets[0] + stride * jnp.arange(tq, dtype=jnp.int32)
        k_pos = offsets[1] + stride * jnp.arange(tk, dtype=jnp.int32)
        s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, NEG_INF)
    p = jnp.exp(s - L.reshape(b, hkv, group, tq, 1))
    dv = jnp.einsum("bhgqk,bhgqd->bhkd", p, gg)
    dp = jnp.einsum("bhgqd,bhkd->bhgqk", gg, v.astype(jnp.float32))
    ds = p * (dp - D.reshape(b, hkv, group, tq, 1))
    dq = scale * jnp.einsum("bhgqk,bhkd->bhgqd", ds, k.astype(jnp.float32))
    dk = scale * jnp.einsum("bhgqk,bhgqd->bhkd", ds, qg)
    return dq.reshape(b, hq, tq, d), dk, dv


def attention_block_grads(q, k, v, g, L, out, offsets, *,
                          causal: bool = True,
                          use_pallas: Optional[bool] = None,
                          grad_dtype=jnp.float32, D=None):
    """(dq, dk, dv) contributions of one K/V block to the gradients,
    given the *global* row logsumexp ``L`` and the forward output ``out``
    — the building block of both the single-shard fused backward and the
    backward ring (ring_attention.py). By default ``D = rowsum(dO * O)``
    is fused into the kernels (computed per tile from the streamed dO/O
    blocks), so no separate pass materializes it. Callers that invoke
    this repeatedly with the SAME dO/O (the backward ring — one call per
    ring step) pass a precomputed ``D`` instead: the kernels then stream
    the [B, H, T, 1] D rows rather than re-reading the full [B, H, T, D]
    output every step. Blocks are [B, H, T, D]; K/V may carry fewer
    (grouped) heads, and dk/dv come back at that KV size. ``grad_dtype``:
    f32 (default) for callers that accumulate contributions (the ring);
    the single-shard path passes the input dtype so the kernels emit
    bf16 directly from their f32 VMEM accumulators — no f32 HBM
    round-trip + downstream cast."""
    offsets = _normalize_offsets(offsets)
    if use_pallas is None:
        use_pallas = use_pallas_default()
    if use_pallas and not (_kernel_feasible(q.shape[2])
                           and _kernel_feasible(k.shape[2])):
        use_pallas = False
    if not use_pallas:
        if D is None:
            D = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1, keepdims=True)
        dq, dk, dv = _bwd_ref(q, k, v, g, L, D, offsets, causal)
        return (dq.astype(grad_dtype), dk.astype(grad_dtype),
                dv.astype(grad_dtype))
    interpret = jax.default_backend() != "tpu"
    if D is not None:
        return _bwd_pallas(q, k, v, g, L, D, offsets, causal, interpret,
                           grad_dtype, fused_d=False)
    return _bwd_pallas(q, k, v, g, L, out, offsets, causal, interpret,
                       grad_dtype, fused_d=True)


def _attn_impl(causal, use_pallas, q, k, v):
    if use_pallas:
        interpret = jax.default_backend() != "tpu"
        return _flash_fwd_pallas(q, k, v, causal, interpret)
    b, h, t, d = q.shape
    carry = init_carry(b, h, t, d)
    offsets = _normalize_offsets(jnp.zeros((2,), jnp.int32))
    o, l, m = _merge_ref(q, k, v, *carry, offsets, causal)
    return finalize((o, l, m), q.dtype), _logsumexp_rows(l, m)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _attn(causal: bool, use_pallas: bool, q, k, v):
    out, _L = _attn_impl(causal, use_pallas, q, k, v)
    return out


def _attn_fwd(causal, use_pallas, q, k, v):
    out, L = _attn_impl(causal, use_pallas, q, k, v)
    # Named for remat policies: under jax.checkpoint, "dots"-style policies
    # do not save custom-call outputs, so the whole forward kernel re-runs
    # inside the backward — measured at ~1/3 of the flagship's attention
    # time (docs/benchmarks.md attribution). Naming the two backward
    # residuals lets a save_only_these_names policy (transformer
    # --remat-policy dots_attn) keep them resident: O(B·T·H·D) bf16 + the
    # [B,H,T,1] logsumexp per layer, in exchange for skipping the
    # recompute pass.
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_attn_out")
    L = checkpoint_name(L, "flash_attn_lse")
    return out, (q, k, v, out, L)


def _attn_bwd(causal, use_pallas, residuals, g):
    q, k, v, out, L = residuals
    # grad_dtype = the input dtype: the kernels cast their f32 VMEM
    # accumulators on emission, so bf16 training grads never round-trip
    # HBM as f32. (Same value as the old downstream .astype — the
    # accumulation itself stays f32 either way.)
    return attention_block_grads(
        q, k, v, g, L, out, jnp.zeros((2,), jnp.int32),
        causal=causal, use_pallas=use_pallas, grad_dtype=q.dtype)


_attn.defvjp(_attn_fwd, _attn_bwd)


def merge_kv_block(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   carry: Carry, offsets: jnp.ndarray, *, causal: bool = True,
                   use_pallas: Optional[bool] = None) -> Carry:
    """Fold K/V block ``k``/``v`` (global position ``offsets[1]``) into the
    streaming softmax over resident queries ``q`` (position ``offsets[0]``).

    All blocks are [B, H, T, D]; K/V may carry grouped (fewer) heads — the
    carry stays at query-head size. ``offsets`` is [q_off, k_off]
    (contiguous) or [q_off, k_off, stride] (striped layout) int32, so one
    compiled kernel serves every ring step. Differentiable (custom VJP).
    ``use_pallas=None`` auto-selects: the kernel on real TPUs, the jnp path
    elsewhere (``True`` forces the kernel — interpret mode off-TPU, which is
    orders of magnitude slower than jnp and meant for tests only).
    """
    o, l, m = carry
    offsets = _normalize_offsets(offsets)
    if use_pallas is None:
        use_pallas = use_pallas_default()
    if use_pallas and not (_kernel_feasible(q.shape[2])
                           and _kernel_feasible(k.shape[2])):
        use_pallas = False
    if not use_pallas:
        return _merge_ref(q, k, v, o, l, m, offsets, causal)
    interpret = jax.default_backend() != "tpu"
    return _merge(causal, interpret, q, k, v, o, l, m, offsets)


# --- cached decode ------------------------------------------------------------
#
# The serve payload's incremental decode (payload/kvcache.py) attends ONE new
# token per slot against that slot's cached K/V. The shape is nothing like
# training attention: Tq is 1 (or a handful at speculative widths), the key
# span is the cache's padded capacity, and the only mask is a per-ROW valid
# length — row b's keys beyond lengths[b] are cache garbage (stale pages from
# a released request, zero-init) that must contribute *exactly* zero. The
# masked score is NEG_INF, so p = exp(NEG_INF - m) underflows to 0.0 in f32
# and 0 * finite-garbage = 0 — which is what makes a paged gather bit-equal
# to a dense cache (tests/test_kvcache.py asserts it). No backward: decode is
# inference-only, so there is no custom_vjp and no logsumexp residual.


def _decode_ref(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                lengths: jnp.ndarray) -> jnp.ndarray:
    """Length-masked attention in plain jnp, [B, Tq, H, D] query layout
    against [B, S, KVH, D] cache. Query slot j of row b sits at global
    position lengths[b] - Tq + j; keys are valid iff their position is
    both < lengths[b] and <= the query's own position (causal within the
    Tq tail). Single-pass max-subtracted softmax — masked lanes are
    exactly zero (module note above)."""
    b, tq, hq, d = q.shape
    tk, hkv = k.shape[1], k.shape[2]
    group = _group_of(jnp.einsum("bqhd->bhqd", q),
                      jnp.einsum("bkhd->bhkd", k))
    scale = d ** -0.5
    qg = jnp.einsum("bqhd->bhqd", q.astype(jnp.float32)).reshape(
        b, hkv, group, tq, d)
    kf = jnp.einsum("bkhd->bhkd", k.astype(jnp.float32))
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, kf) * scale
    q_pos = lengths.astype(jnp.int32)[:, None] - tq \
        + jnp.arange(tq, dtype=jnp.int32)[None, :]            # [B, Tq]
    k_pos = jnp.arange(tk, dtype=jnp.int32)                   # [S]
    valid = k_pos[None, None, :] <= q_pos[:, :, None]         # [B, Tq, S]
    s = jnp.where(valid[:, None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhgqk,bhkd->bhgqd", p,
                   jnp.einsum("bkhd->bhkd", v.astype(jnp.float32)))
    alive = m > NEG_INF / 2
    o = jnp.where(alive, o / jnp.maximum(l, 1e-30), 0.0)
    return jnp.einsum("bhqd->bqhd", o.reshape(b, hq, tq, d)).astype(q.dtype)


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, acc_scr, l_scr,
                   m_scr, *, scale: float, group: int, tq: int, nk: int,
                   blk_k: int):
    """One (batch, kv-head, k-tile) cell of the cached-decode forward.
    The whole Tq-deep query panel (group heads flattened, like the
    training kernels) stays resident; per-row valid lengths arrive as a
    scalar-prefetch array indexed by the batch grid dim, so one compiled
    kernel serves every occupancy mix. Tiles entirely beyond the row's
    length are skipped (and their K/V DMA elided by the clamped index
    map in the caller)."""
    ib = pl.program_id(0)
    ik = pl.program_id(2)
    length = len_ref[ib]
    rows = group * tq

    @pl.when(ik == 0)
    def _reset():
        acc_scr[...] = jnp.zeros_like(acc_scr)
        l_scr[...] = jnp.zeros_like(l_scr)
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)

    @pl.when(ik * blk_k < length)
    def _tile():
        qp = q_ref[0].reshape(rows, -1)
        s = lax.dot_general(qp, k_ref[0, 0], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        row = lax.broadcasted_iota(jnp.int32, (rows, blk_k), 0)
        # Row r of the flattened panel is query slot r % tq (each group
        # repeats the q panel), at global position length - tq + slot.
        q_pos = length - tq + (lax.rem(row, tq) if tq > 1
                               else jnp.zeros_like(row))
        k_pos = ik * blk_k + lax.broadcasted_iota(jnp.int32, (rows, blk_k), 1)
        s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        acc_scr[...] = acc_scr[...] * alpha + lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[...] = m_new

    @pl.when(ik == nk - 1)
    def _emit():
        m = m_scr[...]
        l = l_scr[...]
        alive = m > NEG_INF / 2
        out = jnp.where(alive, acc_scr[...] / jnp.maximum(l, 1e-30), 0.0)
        o_ref[0] = out.reshape(group, tq, -1).astype(o_ref.dtype)


def _flash_decode_pallas(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                         lengths: jnp.ndarray, interpret: bool
                         ) -> jnp.ndarray:
    qt = jnp.einsum("bqhd->bhqd", q)
    kt = jnp.einsum("bkhd->bhkd", k)
    vt = jnp.einsum("bkhd->bhkd", v)
    b, hq, tq, d = qt.shape
    hkv, tk = kt.shape[1], kt.shape[2]
    group = _group_of(qt, kt)
    blk_k = _pick_block(tk, target=512)
    nk = tk // blk_k
    scale = d ** -0.5
    lengths = lengths.astype(jnp.int32)

    def qo_map(ib, ih, ik, lens):
        return (ib, ih, 0, 0)

    def kv_map(ib, ih, ik, lens):
        # Tiles beyond the row's valid length are skipped in-kernel;
        # clamping their index to the last contributing tile turns the
        # skip into a free revisit (no K/V DMA), so decode reads
        # O(length), not O(capacity).
        last = jnp.maximum(lax.div(lens[ib] - 1, blk_k), 0)
        return (ib, ih, jnp.minimum(ik, last), 0)

    q_spec = pl.BlockSpec((1, group, tq, d), qo_map)
    kv_spec = pl.BlockSpec((1, 1, blk_k, d), kv_map)
    rows = group * tq
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, group=group, tq=tq,
                          nk=nk, blk_k=blk_k),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, hkv, nk),
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=[pl.BlockSpec((1, group, tq, d), qo_map)],
            scratch_shapes=[
                pltpu.VMEM((rows, d), jnp.float32),
                pltpu.VMEM((rows, 1), jnp.float32),
                pltpu.VMEM((rows, 1), jnp.float32),
            ],
        ),
        out_shape=[jax.ShapeDtypeStruct((b, hq, tq, d), q.dtype)],
        interpret=interpret,
    )(lengths, qt, kt, vt)[0]
    return jnp.einsum("bhqd->bqhd", out)


def flash_decode(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                 lengths: jnp.ndarray, *,
                 use_pallas: Optional[bool] = None) -> jnp.ndarray:
    """Cached-decode attention: [B, Tq, H, D] new-token queries against a
    [B, S, KVH, D] K/V cache with per-row valid ``lengths`` (int32 [B]) —
    the serve payload's per-step hot op. Row b's query slot j sits at
    position ``lengths[b] - Tq + j`` and attends keys at positions
    < lengths[b] (its own K/V already written). K/V may carry grouped
    heads exactly as in :func:`flash_attention`. Inference-only: no
    backward, no residuals. ``use_pallas=None`` auto-selects the kernel
    on TPU and the jnp path elsewhere."""
    if use_pallas is None:
        use_pallas = use_pallas_default()
    if use_pallas and not _kernel_feasible(k.shape[1]):
        use_pallas = False
    if not use_pallas:
        return _decode_ref(q, k, v, lengths)
    interpret = jax.default_backend() != "tpu"
    return _flash_decode_pallas(q, k, v, lengths, interpret)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    causal: bool = True,
                    use_pallas: Optional[bool] = None) -> jnp.ndarray:
    """Single-device exact attention, [B, T, H, D] in/out — the fused
    counterpart of ring_attention.reference_attention. K/V may carry
    ``kv_heads`` < H (grouped-query attention, module docstring): the
    kernels index K/V heads by group, so the repeated-K/V tensor of a
    broadcast-based GQA never exists in HBM and dK/dV come back at KV
    size. Forward and backward both run as Pallas kernels: O(T) memory in
    either direction, so this is the path that makes 8k-32k contexts
    trainable on one chip."""
    qt = jnp.einsum("bqhd->bhqd", q)
    kt = jnp.einsum("bkhd->bhkd", k)
    vt = jnp.einsum("bkhd->bhkd", v)
    if use_pallas is None:
        use_pallas = use_pallas_default()
    if use_pallas and not (_kernel_feasible(qt.shape[2])
                           and _kernel_feasible(kt.shape[2])):
        use_pallas = False
    out = _attn(causal, use_pallas, qt, kt, vt)
    return jnp.einsum("bhqd->bqhd", out)
