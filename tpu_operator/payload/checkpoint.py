"""Durable checkpoint/resume for whole-group restart, built on orbax.

The reference operator had no checkpoint layer at all — persistence was the
user container's job via PodTemplate volumes (SURVEY.md §5; reference
README.md:168-180 mounts an azureFile share). That was tolerable for MXNet
parameter servers, where a single dead worker restarts alone and re-pulls
weights from the servers. A JAX multi-controller group has no such warm
store: any worker death triggers whole-group restart (trainer/policy.py),
so every attempt restarts from step 0 unless the payload itself persists
state. This module makes resume a first-class part of the payload contract:

- the operator injects ``TPU_CHECKPOINT_DIR`` when ``spec.checkpointDir``
  is set (trainer/replicas.py build_replica_env);
- payloads call :func:`from_env_or_args` to get a :class:`Checkpointer`
  (or ``None`` when unconfigured — checkpointing stays opt-in, exactly as
  in the reference's data-plane contract);
- ``train.train_loop`` restores the newest *verified* step on entry and
  saves every ``save_every`` steps plus once at the end.

Durability model (the CheckFreq/Gemini hardening arc: decouple save
failures from the step loop, treat checkpoint validity as a first-class
recovery input):

- **Verified saves.** After an async save commits, the checkpoint is
  validated — orbax's commit marker (a finalized, non-tmp step directory)
  plus a manifest sidecar recording every file's size and sha256 — and the
  *last verified step* is tracked separately from latest-on-disk. A save
  that never finalizes (kill -9, preemption mid-write) is never advertised
  as durable.
- **Restore fallback.** ``restore`` walks from the newest step backwards:
  a step that fails verification (or raises during restore) is
  *quarantined* — renamed to ``<step>.corrupt-N`` so orbax stops seeing it
  but the bytes survive for postmortem — and the walk continues to the
  newest older valid step, reaching step 0 only when nothing survives.
  Orphaned tmp directories from killed saves are swept aside on restore.
- **Save-failure tolerance.** An I/O error on an interval save (disk full,
  flaky volume) does not crash the step loop: it is counted, logged, and
  reported via the heartbeat; only ``fail_after`` *consecutive* failures
  escalate to a retryable exit (143) so the operator restarts the group
  onto (hopefully) healthier storage instead of the job dying permanently.
- **Gang-consistent resume.** In multi-process jobs the restore step is
  agreed via a tiny allgather-min of each process's newest locally-valid
  step (the same pattern as train_loop's drain latch), so shared-fs lag or
  per-pod checkpoint dirs can never make the group restore divergent
  state.
- **Reshard-restore.** The restore target is the LIVE state's shardings,
  never the saved ones: a checkpoint saved on mesh ``{data: 8}`` restores
  onto ``{data: 4}`` (and back up) inside the same verified walk —
  elastic gangs (``spec.elastic``) resize between attempts, and the
  remote warm-start store makes the donor snapshot reachable from
  whichever nodes the resized gang lands on. When orbax's sharded
  restore refuses the mesh change on bytes that verify intact, a host
  round-trip + ``device_put`` fallback re-lays the leaves out.

The counters (``save_failures``, ``restore_fallbacks``, last verified
step) flow out through the heartbeat (payload/heartbeat.py →
``status.checkpoint`` / ``job_checkpoint_*`` metrics), so the operator's
restart decisions and the human's ``tpujobctl describe`` both see which
step is actually durable.

TPU notes: saves go through orbax's async path (device→host copy happens
at save(); the filesystem write overlaps subsequent steps, keeping the MXU
busy), and the verification read-back + sha256 runs on a background thread
once the commit lands — the step loop never pays the hash; it only joins
the worker at the next save boundary (where orbax would block for the
previous write anyway) or on an explicit flush. Restore is sharding-aware —
each process reads only the shards it owns, so a resumed TP/DP-sharded
state never materialises unsharded on one host.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from tpu_operator.payload.bootstrap import EXIT_RETRYABLE
from tpu_operator.util import lockdep

log = logging.getLogger(__name__)

ENV_VAR = "TPU_CHECKPOINT_DIR"

# Manifest sidecar written into a step directory after its save verifies.
# Lives inside the step dir so orbax's max_to_keep GC and our quarantine
# rename both carry it along with the data it describes.
MANIFEST_NAME = "manifest.tpuop.json"

# Quarantined step dirs: ``<step>.corrupt-<n>``. Non-numeric, so orbax's
# step scan ignores them; the bytes stay on disk for postmortem.
QUARANTINE_SUFFIX = ".corrupt"

# Orphaned tmp dirs from a killed save are renamed aside with this suffix.
ORPHAN_SUFFIX = ".orphaned"

# Consecutive interval-save failures tolerated before escalating to a
# retryable exit (CheckFreq-style: transient I/O blips are skipped and
# counted; a persistently failing volume hands the problem to the
# operator's whole-group restart instead of silently training undurable).
DEFAULT_FAIL_AFTER = 3


def gang_agree_step(candidate: Optional[int]) -> Optional[int]:
    """Group consensus on the restore step: allgather-min of each process's
    newest locally-valid step (None → -1 sentinel). Single-process jobs
    return the candidate unchanged. Same tiny-collective pattern as the
    drain latch in train.train_loop — one scalar allgather, noise next to
    restore itself. The MIN is the only safe choice: every process can
    restore a step ≤ its own newest valid one, so the group lands on state
    all members actually hold (shared-fs propagation lag or per-pod dirs
    would otherwise leave the group restoring divergent steps — a silent
    training-state fork)."""
    import jax

    if jax.process_count() <= 1:
        return candidate
    import numpy as np
    from jax.experimental import multihost_utils

    local = np.int64(candidate if candidate is not None else -1)
    agreed = int(multihost_utils.process_allgather(local).min())
    return None if agreed < 0 else agreed


class CheckpointError(Exception):
    """A checkpoint operation failed (carried in logs/counters; only
    escalation raises out of the step loop)."""


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class Checkpointer:
    """Orbax CheckpointManager wrapper with verified saves, quarantine-and-
    fall-back restore, and save-failure tolerance, bound to one train state
    shape.

    Steps are the single source of truth: the saved pytree carries its own
    ``step`` leaf, and orbax names checkpoints by step, so resume needs no
    sidecar metadata beyond the integrity manifest.
    """

    def __init__(self, directory: str, save_every: int = 100,
                 max_to_keep: int = 3,
                 fail_after: int = DEFAULT_FAIL_AFTER,
                 agree_fn: Optional[Callable[[Optional[int]],
                                             Optional[int]]] = None,
                 uploader: Optional[Any] = None):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self.save_every = max(1, int(save_every))
        # Live cadence knob (payload/autotune.py): the effective interval
        # is ``save_every * cadence_multiplier``. Autotune may only
        # COARSEN cadence — the multiplier starts at 1 (exactly the
        # configured interval) and is bounded by the controller's cap —
        # so durability never silently tightens below what the payload
        # asked for, and a regression reverts the stretch. Read at save
        # boundaries on the step-loop thread. In a gang the save is a
        # COLLECTIVE, so a stretched gate must be gang-uniform: with
        # ``enable_gang_cadence()`` the multiplier becomes a PROPOSAL —
        # at each base-interval boundary every process contributes its
        # local value to the injectable ``agree_fn`` (allgather-min, the
        # restore-step pattern) and the gang-wide MINIMUM gates the save,
        # so a process whose controller hasn't stretched yet keeps
        # everyone saving (the conservative choice) and the barrier can
        # never mismatch. Without the flag (single-process, or a caller
        # that never attached autotune) the local value applies directly.
        self.cadence_multiplier = 1
        self._cadence_gang_agreed = False
        self.fail_after = max(1, int(fail_after))
        # Injectable for tests; default is the real allgather-min.
        self._agree = agree_fn or gang_agree_step
        # Durability bookkeeping, reported via stats()/the heartbeat.
        self.save_failures = 0              # total failed saves, this attempt
        self.consecutive_save_failures = 0  # escalation counter
        self.restore_fallbacks = 0          # quarantined steps during restore
        # Restores that needed the reshard fallback (saved mesh != live
        # mesh and the direct re-layout refused): elastic resize made
        # the gang a different size than the one that saved.
        self.reshard_restores = 0
        self._last_verified: Optional[int] = None  # newest verified commit
        self._pending: Optional[int] = None        # async save awaiting verify
        # Background verification: the read-back + sha256 of a committed
        # save runs on this worker so the step loop never pays the hash;
        # its (step, error-or-None) outcome is applied by _reap_verify on
        # the step-loop thread (where escalation is allowed to raise).
        self._verify_thread: Optional[threading.Thread] = None
        # The worker's (step, error) handoff: written on the verify
        # thread, swapped out on the step-loop thread. The thread-join
        # ordering made the unlocked version *mostly* safe, but the
        # non-blocking reap path read it concurrently with the worker's
        # store (escape-analyzer finding) — now explicitly guarded.
        self._verify_lock = lockdep.lock("Checkpointer._verify_lock")
        self._verify_outcome: Optional[Tuple[int, Optional[Exception]]] = None  # guarded-by: _verify_lock
        # Steps already condemned this process (quarantine attempted): never
        # reconsidered, so a failing rename cannot loop the restore walk.
        self._condemned: set = set()
        # Remote warm-start store write-behind
        # (store/writebehind.WriteBehindUploader, wired by
        # payload/warmstore.uploader_from_env for process 0): every
        # VERIFIED save is enqueued for async upload — durability is local
        # first, remote never blocks the step loop — and quarantined steps
        # are condemned remotely so a fresh-node prefetch can never prefer
        # a remote copy of a step the local walk rejected. Persistent
        # upload failures escalate exactly like local save failures
        # (checked at save boundaries on the step-loop thread).
        self._uploader = uploader
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                save_interval_steps=self.save_every,
                max_to_keep=max_to_keep,
                create=True,
            ),
        )

    # -- introspection ---------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        """Newest step on disk — NOT necessarily durable; restart decisions
        should prefer :meth:`last_verified_step`."""
        return self.manager.latest_step()

    def last_verified_step(self) -> Optional[int]:
        """Newest step whose commit was verified (marker + manifest) by this
        process — the step a restart is guaranteed to resume from."""
        return self._last_verified

    @property
    def uploader(self) -> Optional[Any]:
        """The write-behind store uploader (None when the remote store is
        unwired or this is not process 0) — exposed so exit paths can
        enqueue postmortem artifacts through the same worker."""
        return self._uploader

    def stats(self) -> Dict[str, int]:
        """Durability counters for the heartbeat body
        (→ ``status.checkpoint`` and the ``job_checkpoint_*`` metrics)."""
        out: Dict[str, int] = {
            "saveFailures": int(self.save_failures),
            "restoreFallbacks": int(self.restore_fallbacks),
        }
        if self._last_verified is not None:
            out["lastCheckpointStep"] = int(self._last_verified)
        if self._uploader is not None:
            # Remote-store counters ride the same heartbeat channel:
            # {uploadFailures, lastUploadedStep} → storeUploadFailures /
            # storeLastUploadedStep on the wire.
            out.update(self._uploader.stats())
        return out

    # -- save path -------------------------------------------------------------

    def maybe_save(self, step: int, state: Any) -> bool:
        """Save if the interval policy says so (orbax decides). Async: the
        write completes in the background; the *previous* pending save is
        verified here first — blocking only when a new save is due, where
        orbax would block for it anyway. I/O failures never propagate: they
        are counted and skipped, escalating to SystemExit(143) only after
        ``fail_after`` consecutive failures."""
        step = int(step)
        self._check_upload_escalation()
        mult = self._effective_cadence_multiplier(step)
        if mult > 1 and step % (self.save_every * mult) != 0:
            # Autotune stretched the cadence: only every mult'th interval
            # boundary saves (orbax's own policy still gates below, so a
            # stretch can never make saves MORE frequent than configured).
            self._finalize_pending(block=False)
            return False
        try:
            due = bool(self.manager.should_save(step))
        except Exception:  # noqa: BLE001 — conservative: try the save
            due = True
        if not due:
            self._finalize_pending(block=False)
            return False
        self._finalize_pending(block=True)
        return self._save(step, state, force=False)

    def enable_gang_cadence(self) -> None:
        """Make the cadence multiplier gang-agreed: from now on each
        base-interval boundary routes the local proposal through
        ``agree_fn`` (allgather-min) before gating the save. Called by
        the autotune runtime when it attaches a multi-process job's
        checkpointer — must be enabled on EVERY process of the gang
        (attach runs from the same injected env on all of them, so the
        collective's participation set is uniform by construction)."""
        self._cadence_gang_agreed = True

    def _effective_cadence_multiplier(self, step: int) -> int:
        """The multiplier that gates this boundary. Gang-agreed mode runs
        the agreement collective ONLY at base-interval boundaries
        (``step % save_every == 0`` — spec-driven, identical on every
        process, so all members join the allgather at the same steps
        regardless of their local proposals) and takes the gang MINIMUM:
        a disagreeing gang saves at the most conservative member's
        cadence instead of wedging the save barrier."""
        mult = max(1, int(self.cadence_multiplier))
        if not self._cadence_gang_agreed or step % self.save_every != 0:
            return mult
        try:
            agreed = self._agree(mult)
        except Exception:  # noqa: BLE001 — agreement is best-effort
            log.exception("gang cadence agreement failed; saving at the "
                          "configured interval")
            return 1
        return max(1, int(agreed)) if agreed is not None else 1

    def save(self, step: int, state: Any) -> bool:
        """Unconditional save (end-of-run final state, drain); no-op if that
        step was already written by the interval policy. The
        synchronize-first order matters: comparing only ``latest_step()``
        misses an async interval save of the same step still in flight and
        would issue a redundant force rewrite of state that is already
        committing — so the pending save is finalized (committed AND
        verified) before deciding, and a pending save that *failed* to
        commit is retried here rather than dedup'd away."""
        step = int(step)
        self._check_upload_escalation()
        self._finalize_pending(block=True)
        if self._last_verified == step or self.manager.latest_step() == step:
            return False
        return self._save(step, state, force=True)

    def flush(self) -> None:
        """Block until the in-flight async save (if any) has committed AND
        verified — after this, :meth:`last_verified_step` reflects it."""
        self._finalize_pending(block=True)

    def _save(self, step: int, state: Any, force: bool) -> bool:
        try:
            saved = bool(self.manager.save(
                step, args=self._ocp.args.StandardSave(state), force=force))
        except Exception as e:  # noqa: BLE001 — tolerance: skip, count, report
            self._record_save_failure(step, e)
            return False
        if saved:
            self._pending = step
        return saved

    def _finalize_pending(self, block: bool) -> None:
        """Drive the pending async save towards verified: once the commit
        lands, hand the read-back + sha256 to the background verify worker,
        and apply any finished worker's outcome (advance the last-verified
        step, or count the failure). ``block=True`` joins everything —
        after it returns, the pending save is either verified or counted
        as failed; ``block=False`` never waits."""
        self._reap_verify(block)
        if self._pending is None:
            return
        if not block:
            try:
                if self.manager.is_saving_in_progress():
                    return
            except Exception:  # noqa: BLE001 — treat as still in progress
                return
        step, self._pending = self._pending, None
        try:
            self.manager.wait_until_finished()
            check = getattr(self.manager, "check_for_errors", None)
            if check is not None:
                check()
        except Exception as e:  # noqa: BLE001 — async write failed
            self._record_save_failure(step, e)
            return
        self._verify_thread = threading.Thread(
            target=self._verify_worker, args=(step,),
            name="ckpt-verify", daemon=True)
        self._verify_thread.start()
        if block:
            self._reap_verify(block=True)

    def _verify_worker(self, step: int) -> None:
        """Background half of verification: commit-marker check, manifest
        hash + write. Only records the outcome — counters and escalation
        belong to the step-loop thread via _reap_verify."""
        try:
            ok, why = self._verify_commit(step)
            if not ok:
                raise CheckpointError(why)
            try:
                self._write_manifest(step)
            except Exception as e:  # noqa: BLE001 — manifest is best-effort
                # The commit itself is good; a failed manifest write only
                # downgrades this step to legacy (restore-attempt)
                # verification.
                log.warning("checkpoint step %d: manifest write failed: %s",
                            step, e)
            with self._verify_lock:
                self._verify_outcome = (step, None)
        except Exception as e:  # noqa: BLE001 — applied by _reap_verify
            with self._verify_lock:
                self._verify_outcome = (step, e)

    def _reap_verify(self, block: bool) -> None:
        """Apply the verify worker's outcome on the calling (step-loop)
        thread, so a fail_after escalation raises where SystemExit actually
        exits the process instead of dying with a daemon thread."""
        t = self._verify_thread
        if t is None:
            return
        if block:
            t.join()
        elif t.is_alive():
            return
        self._verify_thread = None
        with self._verify_lock:
            outcome, self._verify_outcome = self._verify_outcome, None
        if outcome is None:  # worker died before recording: count it
            self._record_save_failure(-1, CheckpointError(
                "verification worker died without an outcome"))
            return
        step, err = outcome
        if err is not None:
            self._record_save_failure(step, err)
            return
        self._last_verified = step
        self.consecutive_save_failures = 0
        log.info("checkpoint step %d verified in %s", step, self.directory)
        if self._uploader is not None:
            # Write-behind: only VERIFIED saves ship (the remote store
            # advertises durable steps, so it must never hold bytes the
            # local manifest discipline hasn't blessed). enqueue is a
            # lock-guarded dict update — the step loop never touches the
            # backend.
            self._uploader.enqueue(step, self._step_dir(step))

    def _record_save_failure(self, step: int, err: Exception) -> None:
        self.save_failures += 1
        self.consecutive_save_failures += 1
        log.warning(
            "checkpoint save of step %d failed (%d consecutive, %d total, "
            "last durable step %s): %s", step,
            self.consecutive_save_failures, self.save_failures,
            self._last_verified, err)
        if self.consecutive_save_failures >= self.fail_after:
            log.error(
                "checkpoint storage failing persistently (%d consecutive "
                "save failures); exiting retryable so the operator restarts "
                "the group", self.consecutive_save_failures)
            raise SystemExit(EXIT_RETRYABLE)

    def _check_upload_escalation(self) -> None:
        """Remote-upload health, polled at save boundaries on the
        step-loop thread (where SystemExit actually exits): a remote that
        has failed ``fail_after`` consecutive uploads is treated exactly
        like persistently failing local storage — exit retryable and let
        the operator re-place the group. Transient blips cost nothing
        (the uploader skips and retries on the next verified save)."""
        if self._uploader is not None and self._uploader.escalated():
            log.error(
                "remote warm-start store failing persistently (%d upload "
                "failures); exiting retryable so the operator restarts "
                "the group", self._uploader.upload_failures)
            raise SystemExit(EXIT_RETRYABLE)

    # -- verification / manifest -----------------------------------------------

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, str(step))

    def _verify_commit(self, step: int) -> Tuple[bool, str]:
        """Did the save of ``step`` commit? Orbax's marker is the atomic
        rename to a finalized (non-tmp) step directory."""
        path = self._step_dir(step)
        if not os.path.isdir(path):
            return False, "step directory missing after save"
        try:
            from orbax.checkpoint import utils as ocp_utils

            finalized = bool(ocp_utils.is_checkpoint_finalized(path))
        except Exception as e:  # noqa: BLE001 — probe itself failed
            # Indeterminate, NOT a failed commit: the probe breaking
            # (orbax API drift across versions, a transient stat error on
            # flaky storage) says nothing about the checkpoint — the step
            # dir exists at its final (post-rename) path and the manifest
            # checksums still guard integrity. Failing here would convert
            # every healthy save into the fail_after escalation loop.
            log.warning("commit-marker probe unavailable for step %d "
                        "(passing tentatively): %s", step, e)
            return True, "commit marker unprobeable"
        if not finalized:
            return False, "orbax commit marker missing (tmp checkpoint)"
        return True, ""

    def _write_manifest(self, step: int) -> None:
        """Record every committed file's size + sha256 in an atomically-
        replaced sidecar, so later verification can tell torn/corrupt bytes
        from a healthy checkpoint without attempting a full restore.
        Process 0 writes (single writer on a shared filesystem); per-pod
        checkpoint dirs simply fall back to legacy verification."""
        try:
            import jax

            if jax.process_count() > 1 and jax.process_index() != 0:
                return
        except Exception:  # noqa: BLE001 — no jax runtime: single process
            pass
        root = self._step_dir(step)
        files = []
        for dirpath, _dirnames, filenames in os.walk(root):
            for fn in sorted(filenames):
                if fn == MANIFEST_NAME or fn.endswith(".tmp"):
                    continue
                p = os.path.join(dirpath, fn)
                files.append({
                    "path": os.path.relpath(p, root),
                    "size": os.path.getsize(p),
                    "sha256": _sha256_file(p),
                })
        doc = {"step": int(step), "files": files}
        tmp = os.path.join(root, MANIFEST_NAME + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(root, MANIFEST_NAME))

    def _has_intact_manifest(self, step: int) -> bool:
        """True when the step carries a manifest AND its bytes still match
        it — i.e. a restore failure on this step cannot be blamed on torn
        or corrupt data. Legacy unmanifested steps return False (their
        bytes are unprovable, so restore failures keep the quarantine
        path)."""
        if not os.path.exists(
                os.path.join(self._step_dir(step), MANIFEST_NAME)):
            return False
        ok, _why = self._verify_step(step)
        return ok

    def _verify_step(self, step: int) -> Tuple[bool, str]:
        """Full integrity check of an on-disk step: commit marker, then the
        manifest (when present — a step without one, e.g. written before
        this subsystem existed, passes tentatively and relies on restore's
        own failure handling)."""
        ok, why = self._verify_commit(step)
        if not ok:
            return False, why
        mpath = os.path.join(self._step_dir(step), MANIFEST_NAME)
        if not os.path.exists(mpath):
            return True, "unmanifested (legacy) checkpoint"
        try:
            with open(mpath, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            return False, f"manifest unreadable: {e}"
        root = self._step_dir(step)
        for entry in doc.get("files", []):
            p = os.path.join(root, entry.get("path", ""))
            if not os.path.isfile(p):
                return False, f"missing file {entry.get('path')}"
            if os.path.getsize(p) != entry.get("size"):
                return False, (f"size mismatch on {entry.get('path')}: "
                               f"{os.path.getsize(p)} != {entry.get('size')}")
            if _sha256_file(p) != entry.get("sha256"):
                return False, f"checksum mismatch on {entry.get('path')}"
        return True, ""

    # -- restore path ----------------------------------------------------------

    def _quarantine(self, step: int, why: str) -> None:
        """Move a failed step aside under a non-numeric name: orbax stops
        seeing it, the walk-back continues, and the bytes survive for
        postmortem. Races with a peer process quarantining the same step on
        a shared filesystem resolve to whoever renames first."""
        self._condemned.add(int(step))
        if self._uploader is not None:
            # Condemn the REMOTE copy too (async, best-effort): a fresh
            # node's prefetch must never prefer a remote snapshot of a
            # step the local walk just proved bad. (Prefetch also skips
            # locally-quarantined steps independently, covering the
            # window before this mark lands.)
            self._uploader.mark_corrupt(int(step))
        src = self._step_dir(step)
        n = 0
        dst = f"{src}{QUARANTINE_SUFFIX}-{n}"
        while os.path.exists(dst):
            n += 1
            dst = f"{src}{QUARANTINE_SUFFIX}-{n}"
        try:
            os.rename(src, dst)
            log.error("quarantined checkpoint step %d -> %s (%s)",
                      step, os.path.basename(dst), why)
        except OSError as e:
            # A peer already moved it (or it vanished): same outcome.
            log.warning("quarantine of step %d raced/failed (%s); "
                        "continuing fallback: %s", step, why, e)
        try:
            self.manager.reload()
        except Exception as e:  # noqa: BLE001 — stale cache worst case
            log.warning("checkpoint manager reload after quarantine: %s", e)

    def _sweep_orphaned_tmp(self) -> None:
        """Rename aside tmp directories a killed save (kill -9, preemption
        mid-write) left behind, so they are visibly inert instead of
        silently ignored."""
        try:
            from orbax.checkpoint import utils as ocp_utils

            tmps = list(ocp_utils.tmp_checkpoints(self.directory))
        except Exception:  # noqa: BLE001 — best-effort hygiene
            return
        for name in tmps:
            src = os.path.join(self.directory, str(name))
            try:
                os.rename(src, src + ORPHAN_SUFFIX)
                log.warning("swept orphaned tmp checkpoint %s (killed save)",
                            name)
            except OSError:
                pass  # peer swept it / already gone

    def _newest_intact_step(self) -> Optional[int]:
        """Newest step passing full verification; anything newer that fails
        is quarantined and counted as a restore fallback."""
        try:
            steps = sorted(self.manager.all_steps(), reverse=True)
        except Exception as e:  # noqa: BLE001 — unreadable dir = nothing
            log.warning("listing checkpoint steps failed: %s", e)
            return None
        for step in steps:
            if int(step) in self._condemned:
                continue  # quarantine raced/failed earlier; never re-walk it
            ok, why = self._verify_step(step)
            if ok:
                return int(step)
            self.restore_fallbacks += 1
            self._quarantine(int(step), why)
        return None

    def _reshard_restore(self, step: int, state: Any
                         ) -> Tuple[Any, Optional[Exception]]:
        """Re-lay-out a saved checkpoint onto the LIVE state's shardings
        when the direct sharded restore refused: restore WITHOUT target
        shardings (host-side buffers), then ``device_put`` each leaf
        onto the live leaf's sharding. This is the elastic-gang resume
        path of last resort — a checkpoint saved on mesh ``{data: 8}``
        restoring onto ``{data: 4}`` (or back up) when orbax's own
        re-layout declines the mesh change. Costs one host round-trip
        of the state; correctness is unchanged (the bytes were already
        manifest-verified)."""
        import jax

        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
            if hasattr(x, "shape") else x,
            state,
        )
        try:
            raw = self.manager.restore(
                step, args=self._ocp.args.StandardRestore(abstract))

            def relay(saved: Any, live: Any) -> Any:
                sharding = getattr(live, "sharding", None)
                if sharding is not None and hasattr(saved, "shape"):
                    return jax.device_put(saved, sharding)
                return saved

            return jax.tree_util.tree_map(relay, raw, state), None
        except Exception as e:  # noqa: BLE001 — caller keeps the original
            return None, e

    def restore(self, state: Any) -> Tuple[Any, int]:
        """(state, start_step): the newest *valid* checkpoint agreed across
        the gang, restored onto the live state's shardings, or the input
        state untouched at step 0 when nothing survives.

        **Reshard-restore**: the restore target is always the LIVE
        state's shardings, never the saved ones — a checkpoint saved on
        mesh ``{data: 8}`` restores onto ``{data: 4}`` (and back up) by
        re-laying-out every saved leaf onto the live mesh (elastic gangs
        resize between attempts, and the remote warm-start store makes
        the donor snapshot reachable from whichever nodes the resized
        gang lands on). Orbax's sharded restore does the re-layout
        directly in the common case; when it refuses a mesh change on
        bytes that still verify intact, :meth:`_reshard_restore` falls
        back to a host round-trip + ``device_put``.

        The walk: verify newest → quarantine failures → gang-agree the min
        of everyone's newest valid step → restore it → gang-confirm the
        restore; a restore that still raises anywhere in the group
        (corruption the manifest missed, or a legacy unmanifested step)
        quarantines that step on the failing process(es) and the whole walk
        repeats *collectively*. The confirm round is what keeps the gang's
        collectives matched: without it, a process whose local restore
        failed would loop back into the allgather while its peers proceed
        into training collectives — a mismatched collective, i.e. a hang."""
        import jax

        self._sweep_orphaned_tmp()
        while True:
            candidate = self._newest_intact_step()
            agreed = self._agree(candidate)
            if agreed is None:
                # Collective min: every process sees the same None and
                # returns here together — no confirm round needed.
                if self.restore_fallbacks:
                    log.error(
                        "no valid checkpoint survives in %s (%d quarantined); "
                        "restarting from step 0", self.directory,
                        self.restore_fallbacks)
                return state, 0
            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype,
                    sharding=getattr(x, "sharding", None),
                ) if hasattr(x, "shape") else x,
                state,
            )
            restored, err = None, None
            try:
                restored = self.manager.restore(
                    agreed, args=self._ocp.args.StandardRestore(abstract))
            except Exception as e:  # noqa: BLE001 — gang-confirmed below
                err = e
            intact = (err is not None
                      and self._has_intact_manifest(int(agreed)))
            if err is not None and intact:
                # Intact bytes the sharded restore refused: the benign
                # cause is a saved-mesh/live-mesh mismatch (an elastic
                # resize between attempts). Try the reshard fallback
                # BEFORE the confirm collective, so the whole gang sees
                # one verdict for this step.
                restored, reshard_err = self._reshard_restore(int(agreed),
                                                              state)
                if reshard_err is None:
                    self.reshard_restores += 1
                    log.warning(
                        "restore of step %d resharded onto the live mesh "
                        "(direct sharded restore refused: %s)", agreed, err)
                    err = None
            # Every process reaches this second collective each iteration,
            # success or failure, so the rounds stay paired group-wide.
            confirmed = self._agree(agreed if err is None else None)
            if err is not None:
                if intact:
                    # The bytes re-verify against their manifest AND the
                    # reshard fallback failed too, so this is NOT
                    # corruption — a shape/dtype mismatch after a model
                    # change, orbax version drift, OOM. Quarantining
                    # would mangle every resumable checkpoint in turn and
                    # silently restart from step 0; surface it as the
                    # permanent, visible error it is instead.
                    log.error(
                        "restore of step %d failed but its bytes verify "
                        "intact — not corruption; refusing to quarantine",
                        agreed)
                    raise err
                self.restore_fallbacks += 1
                self._quarantine(int(agreed), f"restore failed: {err}")
                continue
            if confirmed != agreed:
                # A peer's restore of this step failed (it quarantined its
                # copy); discard ours and re-agree so the group lands on a
                # common older step instead of forking state.
                log.warning(
                    "restore of step %d succeeded locally but failed on a "
                    "peer; retrying the walk collectively", agreed)
                continue
            self._last_verified = int(agreed)
            if candidate is not None and agreed != candidate:
                log.warning(
                    "gang agreed on step %d (local newest valid was %d)",
                    agreed, candidate)
            if self.restore_fallbacks:
                log.warning(
                    "restored checkpoint step %d from %s after %d "
                    "fallback(s)", agreed, self.directory,
                    self.restore_fallbacks)
            else:
                log.info("restored checkpoint step %d from %s", agreed,
                         self.directory)
            return restored, int(agreed)

    # -- lifecycle -------------------------------------------------------------

    def close(self) -> None:
        """Flush and verify the in-flight save, then close. Best-effort:
        escalation (SystemExit) belongs to the step loop, not to teardown —
        a completed run must not be converted to a retryable exit by its
        final flush."""
        try:
            self._finalize_pending(block=True)
        except SystemExit:
            pass
        except Exception as e:  # noqa: BLE001
            log.warning("checkpoint flush on close failed: %s", e)
        if self._uploader is not None:
            # Bounded drain so the FINAL checkpoint usually lands remotely
            # (a fresh node restarted after completion warm-starts from
            # it); best-effort — a completed run is never converted to a
            # failure by its upload tail.
            try:
                self._uploader.close(flush=True)
            except Exception as e:  # noqa: BLE001
                log.warning("remote store flush on close failed: %s", e)
        try:
            self.manager.close()
        except Exception as e:  # noqa: BLE001
            log.warning("checkpoint manager close failed: %s", e)


def from_env_or_args(checkpoint_dir: str = "", save_every: int = 100,
                     max_to_keep: int = 3,
                     fail_after: int = DEFAULT_FAIL_AFTER,
                     env: Optional[dict] = None) -> Optional[Checkpointer]:
    """Build a Checkpointer from an explicit flag, falling back to the
    operator-injected TPU_CHECKPOINT_DIR; None when neither is set. When
    the operator also wired a remote warm-start store (TPUJOB_STORE_*),
    process 0 gets the write-behind uploader attached."""
    e = env if env is not None else os.environ
    directory = checkpoint_dir or e.get(ENV_VAR, "")
    if not directory:
        return None
    from tpu_operator.payload import warmstore

    return Checkpointer(directory, save_every=save_every,
                        max_to_keep=max_to_keep, fail_after=fail_after,
                        uploader=warmstore.uploader_from_env(e))
