"""Checkpoint/resume for whole-group restart, built on orbax.

The reference operator had no checkpoint layer at all — persistence was the
user container's job via PodTemplate volumes (SURVEY.md §5; reference
README.md:168-180 mounts an azureFile share). That was tolerable for MXNet
parameter servers, where a single dead worker restarts alone and re-pulls
weights from the servers. A JAX multi-controller group has no such warm
store: any worker death triggers whole-group restart (trainer/policy.py),
so every attempt restarts from step 0 unless the payload itself persists
state. This module makes resume a first-class part of the payload contract:

- the operator injects ``TPU_CHECKPOINT_DIR`` when ``spec.checkpointDir``
  is set (trainer/replicas.py build_replica_env);
- payloads call :func:`from_env_or_args` to get a :class:`Checkpointer`
  (or ``None`` when unconfigured — checkpointing stays opt-in, exactly as
  in the reference's data-plane contract);
- ``train.train_loop`` restores the latest step on entry and saves every
  ``save_every`` steps plus once at the end.

TPU notes: saves go through orbax's async path (device→host copy happens
at save(); the filesystem write overlaps subsequent steps, keeping the MXU
busy), and restore is sharding-aware — each process reads only the shards
it owns, so a resumed TP/DP-sharded state never materialises unsharded on
one host.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional, Tuple

log = logging.getLogger(__name__)

ENV_VAR = "TPU_CHECKPOINT_DIR"


class Checkpointer:
    """Thin orbax CheckpointManager wrapper bound to one train state shape.

    Steps are the single source of truth: the saved pytree carries its own
    ``step`` leaf, and orbax names checkpoints by step, so resume needs no
    sidecar metadata.
    """

    def __init__(self, directory: str, save_every: int = 100,
                 max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self.save_every = max(1, int(save_every))
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                save_interval_steps=self.save_every,
                max_to_keep=max_to_keep,
                create=True,
            ),
        )

    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def restore(self, state: Any) -> Tuple[Any, int]:
        """(state, start_step): the latest checkpoint restored onto the
        live state's shardings, or the input state untouched at step 0."""
        import jax

        latest = self.manager.latest_step()
        if latest is None:
            return state, 0
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=getattr(x, "sharding", None),
            ) if hasattr(x, "shape") else x,
            state,
        )
        restored = self.manager.restore(
            latest, args=self._ocp.args.StandardRestore(abstract))
        log.info("restored checkpoint step %d from %s", latest, self.directory)
        return restored, int(latest)

    def maybe_save(self, step: int, state: Any) -> bool:
        """Save if the interval policy says so (orbax decides). Async: the
        write completes in the background; wait_until_finished() blocks."""
        return bool(self.manager.save(int(step), args=self._ocp.args.StandardSave(state)))

    def save(self, step: int, state: Any) -> bool:
        """Unconditional save (end-of-run final state); no-op if that step
        was already written by the interval policy."""
        if self.manager.latest_step() == int(step):
            return False
        return bool(self.manager.save(
            int(step), args=self._ocp.args.StandardSave(state), force=True))

    def close(self) -> None:
        self.manager.wait_until_finished()
        self.manager.close()


def from_env_or_args(checkpoint_dir: str = "", save_every: int = 100,
                     max_to_keep: int = 3,
                     env: Optional[dict] = None) -> Optional[Checkpointer]:
    """Build a Checkpointer from an explicit flag, falling back to the
    operator-injected TPU_CHECKPOINT_DIR; None when neither is set."""
    e = env if env is not None else os.environ
    directory = checkpoint_dir or e.get(ENV_VAR, "")
    if not directory:
        return None
    return Checkpointer(directory, save_every=save_every,
                        max_to_keep=max_to_keep)
