"""Long-context transformer LM payload with sequence parallelism.

``python -m tpu_operator.payload.transformer`` — the third in-repo model
family (after linear and CIFAR ResNet), exercising the capability the
reference could only host, never express: long sequences sharded across the
process group the operator bootstraps.

The reference's data plane was opaque user images (README.md:66-96); its
operator had no notion of sequence length (SURVEY.md §5 "long-context:
absent"). Here long-context is first-class payload capability:

- mesh = (data, seq): batch shards over ``data``, the *sequence dimension*
  shards over ``seq``. Per-device activation memory is O(T / seq_shards).
- attention is exact ring attention (payload/ring_attention.py): K/V blocks
  rotate around the ``seq`` axis on neighbor ppermutes (ICI hops), queries
  stay resident, softmax streams in f32. On TPU the per-block merge (and
  the single-shard path) runs the fused Pallas flash-attention kernel
  (payload/flash_attention.py).
- everything else (LN, QKV/MLP matmuls, embeddings) is position-local, so
  it runs on sequence-sharded activations with zero communication; XLA
  inserts the gradient psums over both mesh axes.
- numerics follow the house style (models.py): bf16 matmul inputs on the
  MXU, f32 LayerNorm/softmax/loss, f32 master params.
"""

from __future__ import annotations

import argparse
import logging
import os
from typing import Optional

from tpu_operator.payload import bootstrap
from tpu_operator.payload import optimizers

log = logging.getLogger(__name__)


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--batch", type=int, default=8, help="global batch size")
    p.add_argument("--seq-len", type=int, default=2048, help="global sequence length")
    p.add_argument("--seq-parallel", type=int, default=1,
                   help="sequence-parallel shards (mesh seq axis size)")
    p.add_argument("--tensor-parallel", type=int, default=1,
                   help="Megatron-style TP shards (mesh model axis): q/k/v/"
                        "mlp_up column-parallel, attn_out/mlp_down row-"
                        "parallel; composes with --seq-parallel (3-axis "
                        "data x seq x model mesh, ring attention only)")
    p.add_argument("--split-qkv", choices=("auto", "on", "off"),
                   default="auto",
                   help="separate q/k/v projections (auto: on under "
                        "--tensor-parallel, so TP shards whole heads; "
                        "off fuses one [d,3d] GEMM — also the compat "
                        "switch for checkpoints saved with a fused "
                        "kernel, whose param tree differs)")
    p.add_argument("--sp-mode", choices=("ring", "ulysses"), default="ring",
                   help="sequence-parallel strategy: ring = ppermute K/V "
                        "rotation, O(T/P) memory; ulysses = head-scatter "
                        "all-to-all, needs heads %% seq shards == 0")
    p.add_argument("--sp-layout", choices=("contiguous", "striped"),
                   default="contiguous",
                   help="how the sequence dim shards under --sp-mode ring: "
                        "contiguous = shard r holds positions [rC, (r+1)C) "
                        "— under causal masking the last rank does ~2x the "
                        "mean attention work and sets ring wall-clock; "
                        "striped = shard r holds positions r, r+N, r+2N, … "
                        "(Striped Attention, Brandon et al. 2023) — every "
                        "rank's causal work is equal to within one tile. "
                        "The permutation is applied inside the jit (token "
                        "gather + position ids + shifted-target loss); "
                        "model params and semantics are identical")
    from tpu_operator.payload import compute

    # --remat / --remat-policy / --optimizer from the shared surface
    # (payload/compute.py) — one flag set across the LM family.
    compute.add_lm_compute_flags(
        p, remat_help="rematerialize each block on backward (jax.checkpoint"
                      "): activation memory O(layers) -> O(1) blocks, for "
                      "long-context configs that would not fit HBM")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="accumulate gradients over K sequential "
                        "microbatches inside the jit (activation-memory "
                        "knob; optimizer sees the full-batch gradient)")
    p.add_argument("--loss-chunk", type=int, default=0,
                   help="compute the lm_head matmul + loss in sequence "
                        "chunks of this many tokens (lax.scan with a "
                        "checkpointed body): the [B, T, vocab] logits "
                        "tensor — ~2 GiB at the 32k flagship, plus its "
                        "cotangent — is never materialized. 0 = off. "
                        "Must divide --seq-len; single-shard sequence "
                        "only (under --seq-parallel the logits are "
                        "already sequence-sharded)")
    p.add_argument("--fsdp", action="store_true",
                   help="ZeRO/FSDP param+optimizer sharding over the data "
                        "axis (train.fsdp_shardings): per-device state "
                        "memory O(1/N); GSPMD gathers weights just-in-time")
    p.add_argument("--adam-mu-dtype", choices=("f32", "bf16"), default="f32",
                   help="dtype of adam's first moment (optax mu_dtype): "
                        "bf16 halves its HBM (2 bytes/param back) at "
                        "negligible quality cost — the m accumulator is a "
                        "smoothed gradient, far less precision-sensitive "
                        "than v or the master params, which stay f32")
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--dim", type=int, default=256)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--kv-heads", type=int, default=0,
                   help="grouped-query attention: K/V heads (< --heads; "
                        "each serves heads/kv-heads query heads). 0 = MHA, "
                        "1 = MQA. Cuts K/V projection params + grads by "
                        "the group factor; under --tensor-parallel, "
                        "kv-heads must divide by the TP degree")
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=50)
    p.add_argument("--data", default=os.environ.get("TPU_DATA_PATH", ""),
                   help="mounted .npy token file (1-D int array): "
                        "memory-mapped real-data stream (data.token_file_lm)"
                        "; empty = synthetic recurrence")
    p.add_argument("--checkpoint-dir", default="",
                   help="checkpoint/resume dir (default: $TPU_CHECKPOINT_DIR)")
    p.add_argument("--checkpoint-every", type=int, default=100)
    p.add_argument("--profile-dir",
                   default=os.environ.get("TPU_PROFILE_DIR", ""),
                   help="jax.profiler trace dir (default: $TPU_PROFILE_DIR)")
    from tpu_operator.payload import autotune

    autotune.add_prefetch_argument(p)
    return p.parse_args(argv)


def make_lm_mesh(num_devices: Optional[int] = None, seq_parallel: int = 1,
                 devices: Optional[list] = None, num_slices: int = 1,
                 tensor_parallel: int = 1):
    """(data, seq) mesh: DP outer, sequence-parallel inner (neighboring
    devices share a ring edge, so K/V rotation stays on adjacent ICI links;
    multi-slice jobs keep the ring within a slice — train.make_mesh).
    With ``tensor_parallel > 1`` the inner axis is ``model`` instead;
    both > 1 composes DP × SP × TP on a 3-axis mesh (ring attention
    around TP-sharded heads)."""
    from tpu_operator.payload import train

    if tensor_parallel > 1 and seq_parallel > 1:
        # composed DP x SP x TP: 3-axis mesh, TP innermost
        return train.make_mesh3(num_devices, seq_parallel=seq_parallel,
                                model_parallel=tensor_parallel,
                                devices=devices, num_slices=num_slices)
    if tensor_parallel > 1:
        return train.make_mesh(num_devices, model_parallel=tensor_parallel,
                               devices=devices, axis_names=("data", "model"),
                               num_slices=num_slices)
    return train.make_mesh(num_devices, model_parallel=seq_parallel,
                           devices=devices, axis_names=("data", "seq"),
                           num_slices=num_slices)


def _build_model(args, mesh):
    import flax.linen as nn
    import jax.numpy as jnp

    from tpu_operator.payload import flash_attention as fa
    from tpu_operator.payload import ring_attention as ring

    seq_shards = mesh.shape.get("seq", 1)
    sp_mode = getattr(args, "sp_mode", "ring")
    striped = getattr(args, "sp_layout", "contiguous") == "striped"
    if striped and sp_mode != "ring":
        raise ValueError("--sp-layout striped requires --sp-mode ring")
    if striped and seq_shards <= 1:
        raise ValueError(
            "--sp-layout striped requires --seq-parallel > 1 (the layout "
            "exists to balance the ring)")

    def attend(q, k, v):
        if seq_shards > 1:
            if sp_mode == "ulysses":
                from tpu_operator.payload import ulysses

                return ulysses.ulysses_attention(q, k, v, mesh, causal=True)
            head_axis = "model" if mesh.shape.get("model", 1) > 1 else None
            return ring.ring_attention(q, k, v, mesh, causal=True,
                                       head_axis=head_axis, stripe=striped)
        if fa.use_pallas_default():
            return fa.flash_attention(q, k, v, causal=True)
        return ring.reference_attention(q, k, v, causal=True)

    from tpu_operator.payload import models

    tp = mesh.shape.get("model", 1)
    if tp > 1 and seq_shards > 1 and sp_mode == "ulysses":
        raise ValueError(
            "--sp-mode ulysses does not compose with --tensor-parallel "
            "(both shard the head dimension); use --sp-mode ring")
    split_qkv = models.resolve_split_qkv(getattr(args, "split_qkv", "auto"),
                                         tp, log)
    kv_heads = getattr(args, "kv_heads", 0)
    models.validate_heads_dims(args.heads, kv_heads, args.dim, tp)

    # Shared Block construction (compute.lm_block): nn.remat over
    # DecoderBlock with the --remat-policy policy when --remat is set —
    # the policy trade-offs are documented on lm_block itself.
    from tpu_operator.payload import compute

    Block = compute.lm_block(args)

    class TransformerLM(nn.Module):
        vocab: int
        dim: int
        heads: int
        layers: int
        max_seq: int

        @nn.compact
        def __call__(self, tokens, train: bool = True, positions=None,
                     return_hidden: bool = False):
            # ``positions``: per-slot global position ids (striped layout
            # feeds permuted tokens, so slot index != position); default
            # natural order. ``return_hidden`` skips the lm_head and
            # returns the post-ln_final hidden states — the chunked-loss
            # step (train.chunked_next_token_nll) applies the head itself,
            # chunk by chunk, so the full [B, T, vocab] logits never
            # materialize. lm_head params exist either way (init runs the
            # default path).
            _b, t = tokens.shape
            if positions is None:
                positions = jnp.arange(t)
            x = nn.Embed(self.vocab, self.dim, dtype=jnp.bfloat16,
                         name="tok_embed")(tokens)
            pos = nn.Embed(self.max_seq, self.dim, dtype=jnp.bfloat16,
                           name="pos_embed")(positions)
            x = x + pos[None]
            for i in range(self.layers):
                x = Block(self.dim, self.heads, attend,
                          split_qkv=split_qkv, kv_heads=kv_heads,
                          name=f"block{i}")(x)
            x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
            if return_hidden:
                return x.astype(jnp.bfloat16)
            return nn.Dense(self.vocab, use_bias=False, dtype=jnp.bfloat16,
                            name="lm_head")(x)

    return TransformerLM(vocab=args.vocab, dim=args.dim, heads=args.heads,
                         layers=args.layers, max_seq=args.seq_len)


def lm_token_spec(mesh):
    """Token batch PartitionSpec for whichever LM mesh layout is in
    play: sequence-sharded on (data, seq), batch-only otherwise."""
    from jax.sharding import PartitionSpec as P

    return P("data", "seq" if "seq" in mesh.shape else None)


def lm_tp_shardings(mesh, state):
    """Megatron-style TP rule over the ``model`` axis: qkv and mlp_up
    kernels column-parallel P(None, model), attn_out and mlp_down
    row-parallel P(model, None), whose products GSPMD psums — the
    classic pairing needing exactly one all-reduce per block per
    direction; lm_head column-parallel over vocab. TP builds split the
    qkv projection into per-projection Dense layers (DecoderBlock
    split_qkv) so each shard holds whole heads and attention is
    head-local; a *fused* [d, 3d] kernel would shard contiguous columns
    straddling the q/k/v thirds and pay a reshard per block. Everything else (LayerNorms, embeddings,
    adam scalars) replicates; params-shaped adam moments match by path.
    """
    from jax.sharding import PartitionSpec as P

    from tpu_operator.payload import train

    col = ("q", "k", "v", "qkv", "mlp_up", "lm_head")
    row = ("attn_out", "mlp_down")

    def rule(keys, leaf):
        if keys and keys[-1] == "kernel" and getattr(leaf, "ndim", 0) == 2:
            if any(k in col for k in keys):
                return P(None, "model")
            if any(k in row for k in keys):
                return P("model", None)
        return P()

    return train.shardings_from_rule(mesh, state, rule)


def make_lm_train_step(model, tx, mesh, state, shardings=None,
                       grad_accum: int = 1, sp_layout: str = "contiguous",
                       loss_chunk: int = 0):
    """Next-token cross-entropy step, jitted with (data, seq) shardings.

    ``sp_layout="striped"``: the step still takes *natural-order* token
    batches; inside the jit the tokens are gathered into the striped
    layout (a [B, T] int32 all-to-all across the seq axis — bytes-wise
    noise), the model runs with explicit position ids, and the loss pairs
    each slot with its true next token. Semantically identical to the
    contiguous step; only the ring's work balance changes.

    ``loss_chunk > 0``: the lm_head + loss run sequence-chunked
    (train.chunked_next_token_nll) so the [B, T, vocab] logits are never
    materialized — the long-context activation-memory lever. Requires an
    unsharded sequence axis: under sequence parallelism the logits are
    already T/P-sized per device and chunking a sharded T would reshard
    every scan slice."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_operator.payload import ring_attention as ring_mod
    from tpu_operator.payload import train

    if loss_chunk:
        if mesh.shape.get("seq", 1) > 1:
            raise ValueError(
                "--loss-chunk requires --seq-parallel 1: sequence "
                "parallelism already shards the logits over T")
        if sp_layout == "striped":
            raise ValueError(
                "--loss-chunk with --sp-layout striped is unsupported "
                "(striped requires --seq-parallel > 1)")
        if model.max_seq % loss_chunk != 0:
            raise ValueError(
                f"--loss-chunk {loss_chunk} must divide --seq-len "
                f"{model.max_seq}")

        def loss_fn(params, tokens):
            hidden = model.apply({"params": params}, tokens,
                                 return_hidden=True)
            loss = train.chunked_next_token_nll(
                hidden, params["lm_head"]["kernel"], tokens, loss_chunk)
            return loss, {"loss": loss}

        return train.make_loss_train_step(loss_fn, tx, mesh, state,
                                          shardings,
                                          batch_spec=lm_token_spec(mesh),
                                          grad_accum=grad_accum)

    if sp_layout == "striped":
        seq_shards = mesh.shape.get("seq", 1)
        perm_np, _inv = ring_mod.stripe_permutation(model.max_seq,
                                                    seq_shards)
        perm = jnp.asarray(perm_np, jnp.int32)
        spec = lm_token_spec(mesh)

        def loss_fn(params, tokens):
            t = tokens.shape[1]
            if t != perm.shape[0]:
                # jnp.take would silently *clip* out-of-range indices on a
                # shorter batch, training on corrupted pairs.
                raise ValueError(
                    f"striped layout was built for seq_len "
                    f"{perm.shape[0]}, got batch with T={t}")
            tok_s = jnp.take(tokens, perm, axis=1)
            tok_s = jax.lax.with_sharding_constraint(
                tok_s, NamedSharding(mesh, spec))
            logits = model.apply({"params": params}, tok_s, positions=perm)
            targets = jnp.take(tokens, (perm + 1) % t, axis=1)
            mask = perm < t - 1
            loss = train.next_token_nll_masked(logits, targets, mask)
            return loss, {"loss": loss}
    else:
        def loss_fn(params, tokens):
            loss = train.next_token_nll(
                model.apply({"params": params}, tokens), tokens)
            return loss, {"loss": loss}

    return train.make_loss_train_step(loss_fn, tx, mesh, state, shardings,
                                      batch_spec=lm_token_spec(mesh),
                                      grad_accum=grad_accum)


def build(args, mesh=None, num_slices: int = 1):
    """(mesh, model, state, train_step, batches) for the given config."""
    import jax
    import jax.numpy as jnp

    from tpu_operator.payload import data as data_mod
    from tpu_operator.payload import train

    mesh = mesh or make_lm_mesh(
        seq_parallel=args.seq_parallel, num_slices=num_slices,
        tensor_parallel=getattr(args, "tensor_parallel", 1))
    model = _build_model(args, mesh)
    tx = optimizers.from_args(args)
    sample = jnp.zeros((args.batch, args.seq_len), jnp.int32)
    state = train.create_train_state(model, jax.random.key(args.seed), sample, tx)
    if "model" in mesh.shape and mesh.shape["model"] > 1:
        if getattr(args, "fsdp", False):
            raise ValueError(
                "--fsdp and --tensor-parallel are exclusive in this "
                "payload: TP replicates over data, FSDP shards over it")
        shardings = lm_tp_shardings(mesh, state)
    elif getattr(args, "fsdp", False):
        shardings = train.fsdp_shardings(mesh, state)
    else:
        shardings = train.state_shardings(mesh, state)
    state = train.place_state(mesh, state, shardings)
    step = make_lm_train_step(model, tx, mesh, state, shardings,
                              grad_accum=getattr(args, "grad_accum", 1),
                              sp_layout=getattr(args, "sp_layout",
                                                "contiguous"),
                              loss_chunk=getattr(args, "loss_chunk", 0))
    batches = data_mod.lm_batches(args, mesh=mesh,
                                  spec=lm_token_spec(mesh))
    return mesh, model, state, step, batches


def run(info: bootstrap.ProcessInfo, args=None) -> dict:
    from jax.sharding import PartitionSpec as P

    from tpu_operator.payload import autotune, checkpoint, train

    args = args or parse_args([])
    mesh, _model, state, step, batches = build(
        args, num_slices=info.num_slices)
    log.info("mesh: %s over %d devices; batch %d seq %d",
             dict(zip(mesh.axis_names, mesh.devices.shape)),
             mesh.devices.size, args.batch, args.seq_len)
    ckpt = checkpoint.from_env_or_args(args.checkpoint_dir,
                                       save_every=args.checkpoint_every)
    if ckpt is not None and ckpt.latest_step() is not None:
        log.info("attempt %d: resuming from %s (latest step: %d)",
                 info.attempt, ckpt.directory, ckpt.latest_step())
    try:
        state, metrics = train.train_loop(
            mesh, step, state, batches, args.steps,
            log_every=args.log_every,
            log_fn=lambda i, m: log.info("step %d loss %.4f", i, m["loss"]),
            checkpointer=ckpt,
            profile_dir=args.profile_dir,
            spec=lm_token_spec(mesh),
            prefetch=autotune.resolve_prefetch_depth(args.prefetch_depth),
        )
    finally:
        if ckpt is not None:
            ckpt.close()
    log.info("final: loss %.4f", metrics.get("loss", float("nan")))
    return metrics


def main() -> None:
    args = parse_args()
    bootstrap.main_wrapper(lambda info: run(info, args))


if __name__ == "__main__":
    main()
