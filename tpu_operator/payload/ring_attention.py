"""Ring attention: exact sequence-parallel attention over a mesh axis.

Long-context jobs shard the sequence dimension across devices; attention is
the one op where every query must see every key. Ring attention (Liu et al.,
"Ring Attention with Blockwise Transformers", public technique) computes it
exactly without ever materializing the full sequence on one device: each
device keeps its query block resident and the K/V blocks rotate around the
mesh axis via ``lax.ppermute`` (one ICI hop per step, bandwidth-optimal on a
TPU torus), while a streaming flash-attention-style softmax accumulates the
output in float32.

The reference operator has no compute layer at all — sequence length is
invisible to it (SURVEY.md §5 "long-context: absent"). In the TPU-native
build, long-context is a first-class payload capability: this module is the
data-plane piece, and the operator's job stays what it always was —
bootstrapping the process group the mesh lives on.

Design notes (TPU-first):
- communication: ``ppermute`` neighbor exchange only — no all-gather of K/V,
  so per-device memory stays O(T/N) and the ring rides ICI links.
- compute: per-step scores are [B, H, Tq_local, Tk_local] — big dense
  matmuls that tile onto the MXU; bf16 inputs are fine, accumulation is f32.
- control flow: ``lax.scan`` with a static trip count (the axis size), so
  the whole ring unrolls into one XLA while-op, reverse-differentiable.
- numerics: running max is kept at a finite ``NEG_INF`` so fully-masked
  blocks (causal, future shards) contribute exp(0)*0 instead of NaN.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30  # finite stand-in for -inf: keeps exp() NaN-free when a
                 # query row has seen no keys yet


def reference_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        causal: bool = True) -> jnp.ndarray:
    """Vanilla full attention, [B, T, H, D] layout — the parity oracle."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _block_scores(q: jnp.ndarray, k: jnp.ndarray, scale: float,
                  q_offset: jnp.ndarray, kv_offset: jnp.ndarray,
                  causal: bool) -> jnp.ndarray:
    """Masked scores [B, H, Tq, Tk] for one (query-block, kv-block) pair.
    Offsets are the blocks' global sequence positions, so causal masking is
    correct regardless of which shard's K/V the ring currently holds."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = kv_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    return s


def _ring_attention_local(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          axis_name: str, causal: bool) -> jnp.ndarray:
    """The per-shard body (runs inside shard_map): q stays resident, k/v
    rotate; a streaming softmax merges each visiting block."""
    axis_size = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = d ** -0.5
    q_offset = idx * tq

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def merge(carry, k_blk, v_blk, kv_idx):
        """Fold one K/V block into the streaming-softmax accumulators."""
        o, l, m = carry
        s = _block_scores(q, k_blk, scale, q_offset, kv_idx * tk, causal)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
        return o, l, m_new

    # Resident block first, then rotate: exactly axis_size - 1 ppermute
    # hops, none wasted.
    acc = (
        jnp.zeros((b, h, tq, d), jnp.float32),
        jnp.zeros((b, h, tq), jnp.float32),
        jnp.full((b, h, tq), NEG_INF, jnp.float32),
    )
    acc = merge(acc, k, v, idx)

    def step(carry, i):
        o, l, m, k_cur, v_cur = carry
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        # After i forward rotations we hold the block that started on
        # shard (idx - i) mod axis_size.
        kv_idx = (idx - i) % axis_size
        o, l, m = merge((o, l, m), k_cur, v_cur, kv_idx)
        return (o, l, m, k_cur, v_cur), None

    (o, l, _m, _k, _v), _ = lax.scan(
        step, (*acc, k, v), jnp.arange(1, axis_size))
    out = jnp.where(l[..., None] > 0, o / jnp.maximum(l, 1e-30)[..., None], 0.0)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, *, seq_axis: str = "seq",
                   batch_axis: Optional[str] = "data",
                   causal: bool = True) -> jnp.ndarray:
    """Exact attention over globally [B, T, H, D] arrays whose T dimension is
    sharded on ``mesh`` axis ``seq_axis`` (and B on ``batch_axis``).

    Drop-in equal to :func:`reference_attention` (up to accumulation order);
    per-device memory O(T / seq_shards), communication = seq_shards - 1
    neighbor hops of the local K/V blocks.
    """
    spec = P(batch_axis, seq_axis, None, None)
    body = functools.partial(_ring_attention_local,
                             axis_name=seq_axis, causal=causal)
    fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)
