"""Ring attention: exact sequence-parallel attention over a mesh axis.

Long-context jobs shard the sequence dimension across devices; attention is
the one op where every query must see every key. Ring attention (Liu et al.,
"Ring Attention with Blockwise Transformers", public technique) computes it
exactly without ever materializing the full sequence on one device: each
device keeps its query block resident and the K/V blocks rotate around the
mesh axis via ``lax.ppermute`` (one ICI hop per step, bandwidth-optimal on a
TPU torus), while a streaming flash-attention-style softmax accumulates the
output in float32.

The reference operator has no compute layer at all — sequence length is
invisible to it (SURVEY.md §5 "long-context: absent"). In the TPU-native
build, long-context is a first-class payload capability: this module is the
data-plane piece, and the operator's job stays what it always was —
bootstrapping the process group the mesh lives on.

Design notes (TPU-first):
- communication: ``ppermute`` neighbor exchange only — no all-gather of K/V,
  so per-device memory stays O(T/N) and the ring rides ICI links.
- compute: the per-visiting-block merge is the flash-attention recurrence,
  fused into a single Pallas kernel on TPU (payload/flash_attention.py) so
  block scores never round-trip through HBM; the jnp fallback below is the
  same math and serves as the oracle + backward path.
- control flow: ``lax.scan`` with a static trip count (the axis size), so
  the whole ring unrolls into one XLA while-op, reverse-differentiable.
- numerics: running max is kept at a finite ``NEG_INF`` so fully-masked
  blocks (causal, future shards) contribute exp(0)*0 instead of NaN.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30  # finite stand-in for -inf: keeps exp() NaN-free when a
                 # query row has seen no keys yet


def reference_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        causal: bool = True) -> jnp.ndarray:
    """Vanilla full attention, [B, T, H, D] layout — the parity oracle.
    K/V with fewer (grouped) heads are broadcast to full head count here:
    the oracle *is* the repeat-based GQA definition the kernels must
    match, so materializing the repeat is the point, not a cost."""
    if k.shape[2] != q.shape[2]:
        hq, hkv = q.shape[2], k.shape[2]
        if hkv <= 0 or hq % hkv != 0:
            raise ValueError(
                f"query heads {hq} must be a multiple of K/V heads {hkv}")
        k = jnp.repeat(k, hq // hkv, axis=2)
        v = jnp.repeat(v, hq // hkv, axis=2)
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def stripe_permutation(t: int, shards: int):
    """(perm, inv) for the striped sequence layout (Striped Attention,
    Brandon et al. 2023): ``x[:, perm]`` lays tokens out so shard r's
    contiguous slice holds global positions r, r+N, r+2N, … Under causal
    masking every rank then owns an equal mix of early (cheap) and late
    (expensive) query positions, so ring wall-clock is set by the mean
    shard instead of the last one (contiguous layout: the final shard does
    ~2x the mean work and the first almost none)."""
    import numpy as np

    if t % shards:
        raise ValueError(f"seq {t} not divisible by {shards} shards")
    perm = np.arange(t).reshape(t // shards, shards).T.reshape(-1)
    inv = np.argsort(perm)
    return perm, inv


def _ring_offsets_fn(axis_name, tq, tk, stripe: bool):
    """(idx, kv_idx) → [q_offset, k_offset, stride] int32 for a shard's
    resident queries against the block that started life on shard kv_idx.
    Contiguous layout: shard r's slot c is global position r*C + c
    (stride 1). Striped: slot c is position r + N*c (stride N) — the
    kernels mask on off + stride*slot either way."""
    idx = lax.axis_index(axis_name)
    n = lax.psum(1, axis_name)
    if stripe:
        def offsets(kv_idx):
            return jnp.stack([idx.astype(jnp.int32),
                              jnp.asarray(kv_idx, jnp.int32),
                              jnp.int32(n)])
    else:
        q_offset = (idx * tq).astype(jnp.int32)

        def offsets(kv_idx):
            return jnp.stack([q_offset, (kv_idx * tk).astype(jnp.int32),
                              jnp.int32(1)])

    return idx, offsets


def _ring_fwd_scan(qt, kt, vt, axis_name, causal, use_pallas, stripe):
    """Forward ring: q resident, K/V rotate on neighbor ppermutes, each
    visit folded by the fused streaming-softmax merge. Returns the raw
    carry so callers can also extract the row logsumexp for the backward
    ring."""
    from tpu_operator.payload import flash_attention as fa

    axis_size = lax.psum(1, axis_name)
    b, h, tq, d = qt.shape
    tk = kt.shape[2]
    idx, offsets = _ring_offsets_fn(axis_name, tq, tk, stripe)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    # Resident block first, then rotate: exactly axis_size - 1 ppermute
    # hops, none wasted.
    carry = fa.init_carry(b, h, tq, d)
    carry = fa.merge_kv_block(qt, kt, vt, carry, offsets(idx),
                              causal=causal, use_pallas=use_pallas)

    def step(state, i):
        o, l, m, k_cur, v_cur = state
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        # After i forward rotations we hold the block that started on
        # shard (idx - i) mod axis_size.
        kv_idx = (idx - i) % axis_size
        o, l, m = fa.merge_kv_block(qt, k_cur, v_cur, (o, l, m),
                                    offsets(kv_idx), causal=causal,
                                    use_pallas=use_pallas)
        return (o, l, m, k_cur, v_cur), None

    (o, l, m, _k, _v), _ = lax.scan(
        step, (*carry, kt, vt), jnp.arange(1, axis_size))
    return o, l, m


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _ring_local_attn(axis_name: str, causal: bool, use_pallas: bool,
                     stripe: bool, qt, kt, vt):
    """Per-shard ring attention in [B,H,T,D] layout (runs inside shard_map),
    differentiated by a *backward ring* (defvjp below) instead of autodiff
    through the forward scan: the forward saves only (q, k, v, out, L) —
    O(T/N) per shard — and the backward rotates K/V (plus their gradient
    accumulators) around the ring again, computing each block pair's
    contribution with the fused flash-backward kernels
    (flash_attention.attention_block_grads). Neither direction materializes
    a score tensor in HBM, and backward communication stays neighbor-only
    ppermutes like the forward."""
    from tpu_operator.payload import flash_attention as fa

    o, l, m = _ring_fwd_scan(qt, kt, vt, axis_name, causal, use_pallas,
                             stripe)
    return fa.finalize((o, l, m), qt.dtype)


def _ring_local_fwd(axis_name, causal, use_pallas, stripe, qt, kt, vt):
    from tpu_operator.payload import flash_attention as fa

    o, l, m = _ring_fwd_scan(qt, kt, vt, axis_name, causal, use_pallas,
                             stripe)
    out = fa.finalize((o, l, m), qt.dtype)
    return out, (qt, kt, vt, out, fa._logsumexp_rows(l, m))


def _ring_local_bwd(axis_name, causal, use_pallas, stripe, residuals, g):
    from tpu_operator.payload import flash_attention as fa

    qt, kt, vt, out, L = residuals
    axis_size = lax.psum(1, axis_name)
    tq, tk = qt.shape[2], kt.shape[2]
    idx, offsets = _ring_offsets_fn(axis_name, tq, tk, stripe)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    # D precomputed ONCE per backward and reused across every ring step:
    # the fused-D kernel path would re-stream the full [B, H, T, D]
    # output through both kernels at each step, where these [B, H, T, 1]
    # rows ride a d=1 BlockSpec. Grads stay f32 — they accumulate across
    # ring steps below.
    D = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1, keepdims=True)

    def block_grads(k_cur, v_cur, kv_idx):
        return fa.attention_block_grads(qt, k_cur, v_cur, g, L, out,
                                        offsets(kv_idx), causal=causal,
                                        use_pallas=use_pallas, D=D)

    # Home block first (mirrors the forward), then rotate K/V together
    # with their f32 gradient accumulators so each block's dK/dV ride
    # along with it around the ring.
    dq, dk, dv = block_grads(kt, vt, idx)

    def step(state, i):
        dq, k_cur, v_cur, dk_cur, dv_cur = state
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        dk_cur = lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = lax.ppermute(dv_cur, axis_name, perm)
        kv_idx = (idx - i) % axis_size
        dq_b, dk_b, dv_b = block_grads(k_cur, v_cur, kv_idx)
        return (dq + dq_b, k_cur, v_cur, dk_cur + dk_b, dv_cur + dv_b), None

    (dq, _k, _v, dk, dv), _ = lax.scan(
        step, (dq, kt, vt, dk, dv), jnp.arange(1, axis_size))

    # After axis_size - 1 rotations a block (and its accumulated gradient)
    # sits one hop short of its home shard: one final ppermute closes the
    # ring.
    dk = lax.ppermute(dk, axis_name, perm)
    dv = lax.ppermute(dv, axis_name, perm)
    return dq.astype(qt.dtype), dk.astype(kt.dtype), dv.astype(vt.dtype)


_ring_local_attn.defvjp(_ring_local_fwd, _ring_local_bwd)


def _ring_attention_local(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          axis_name: str, causal: bool,
                          use_pallas: bool, stripe: bool) -> jnp.ndarray:
    """The per-shard body (runs inside shard_map): transpose to the kernel's
    [B,H,T,D] layout, run the ring (custom-VJP'd — see _ring_local_attn),
    transpose back."""
    qt = jnp.einsum("bqhd->bhqd", q)
    kt = jnp.einsum("bkhd->bhkd", k)
    vt = jnp.einsum("bkhd->bhkd", v)
    out = _ring_local_attn(axis_name, causal, use_pallas, stripe,
                           qt, kt, vt)
    return jnp.einsum("bhqd->bqhd", out)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, *, seq_axis: str = "seq",
                   batch_axis: Optional[str] = "data",
                   head_axis: Optional[str] = None,
                   causal: bool = True,
                   use_pallas: Optional[bool] = None,
                   stripe: bool = False) -> jnp.ndarray:
    """Exact attention over globally [B, T, H, D] arrays whose T dimension is
    sharded on ``mesh`` axis ``seq_axis`` (and B on ``batch_axis``).

    Drop-in equal to :func:`reference_attention` (up to accumulation order);
    per-device memory O(T / seq_shards), communication = seq_shards - 1
    neighbor hops of the local K/V blocks. ``use_pallas`` selects the fused
    flash-attention block kernel (default: on real TPUs; tests opt in to the
    interpreter on CPU).

    ``head_axis`` additionally shards the H dimension (composed SP × TP on
    a 3-axis mesh): the ring math is head-local, so each (seq, model) shard
    just runs the same recurrence on its slice of heads — no extra
    communication.

    ``stripe=True`` declares the T dimension is in the striped layout
    (:func:`stripe_permutation` — shard r's slice holds global positions
    r, r+N, …), which balances causal work across ring ranks: with
    contiguous shards the last rank does ~2x the mean work and sets the
    ring's wall-clock; striped, every rank's unmasked-tile count is equal
    to within one tile row. The caller owns laying out q/k/v (and
    interpreting the output) in that permutation — transformer.py's
    ``--sp-layout striped`` does this end to end."""
    if use_pallas is None:
        from tpu_operator.payload import flash_attention as fa

        use_pallas = fa.use_pallas_default()
    spec = P(batch_axis, seq_axis, head_axis, None)
    body = functools.partial(_ring_attention_local,
                             axis_name=seq_axis, causal=causal,
                             use_pallas=use_pallas, stripe=stripe)
    fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)
