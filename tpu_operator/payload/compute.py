"""Shared compute-path option surface for every payload.

Seventeen PRs of control-plane work left the headline payload on its seed
compute path while the LM payloads grew remat policies, the int8
block-quantized adam8 optimizer, fused losses, and AOT compilation through
the persistent cache. This module is the one place those options live, so
the flagship classifier (cifar.py), the smallest payload (linear.py), and
the LM family (transformer/moe/pipeline) opt into the SAME lineage through
the SAME flags:

===============  ========================  ===============================
option           flag                      payloads
===============  ========================  ===============================
remat policy     ``--remat-policy``        all (LMs gate on ``--remat``;
                                           classifier: != full engages
                                           step-level ``jax.checkpoint``)
optimizer        ``--optimizer``           all (LMs: adam/adam8;
                                           classifier adds sgd, the seed
                                           default)
fused loss       ``--fused-loss``          classifier (LM loss is already
                                           the fused lse-tgt form)
scan blocks      ``--scan-blocks``         classifier (one compiled block
                                           body per stage)
AOT via cache    ``--aot`` /               all run paths AOT through the
                 :func:`aot_compile_cached` overlapped prologue already;
                                           this surface adds it to direct
                                           step users (bench, tests)
===============  ========================  ===============================

Every default reproduces the seed path exactly — an unconfigured payload
trains the same program it always has. bench.py ``--flagship`` A/B-gates
each option individually against that seed path.

Import discipline: module import stays stdlib-only (the payload entry
modules import this at parse time, before bootstrap pins the platform);
jax/flax/optax load lazily inside functions.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Optional, Tuple

log = logging.getLogger(__name__)

CLASSIFIER_OPTIMIZERS = ("sgd", "adam", "adam8")


def add_lm_compute_flags(parser, remat_help: Optional[str] = None) -> None:
    """The LM payloads' shared compute flags: ``--remat`` (gate),
    ``--remat-policy``, ``--optimizer adam|adam8``. One call site per
    parser (transformer/moe/pipeline) instead of three hand-copied
    blocks; ``remat_help`` lets a payload keep its config-specific help
    text (the flags themselves are identical)."""
    from tpu_operator.payload import models, optimizers

    parser.add_argument(
        "--remat", action="store_true",
        help=remat_help or
        "rematerialize each block on backward (jax.checkpoint): "
        "activation memory O(layers) -> O(1) blocks")
    models.add_remat_policy_flag(parser)
    optimizers.add_optimizer_flag(parser)


def lm_block(args, base: Any = None) -> Any:
    """The LM payloads' shared Block construction: ``nn.remat`` over
    :class:`models.DecoderBlock` (or ``base``) with the ``--remat-policy``
    policy when ``--remat`` is set, the plain class otherwise.

    nn.remat is semantics-preserving: same params/outputs, backward
    recomputes the block instead of keeping its activations in HBM. The
    "dots" policy keeps each block's matmul outputs resident and
    recomputes only the cheap elementwise ops between them — the MFU
    sweet spot when the config fits. "dots_attn" additionally saves the
    flash-attention kernel's named residuals (output + row logsumexp):
    dots policies treat custom-calls as recomputable, so without the
    names the whole attention forward re-runs inside the backward."""
    import flax.linen as nn

    from tpu_operator.payload import models

    base = base or models.DecoderBlock
    if getattr(args, "remat", False):
        return nn.remat(base, policy=models.remat_policy(
            getattr(args, "remat_policy", "full")))
    return base


def add_classifier_compute_flags(parser) -> None:
    """The classifier payloads' compute flags (cifar.py; linear.py takes
    the optimizer subset). No ``--remat`` gate: ``--remat-policy full``
    (the default) IS the off position — the classifier's remat is
    step-level ``jax.checkpoint`` in train.make_classifier_train_step,
    not a lifted module transform, so there is no second knob to gate."""
    from tpu_operator.payload import models, optimizers

    models.add_remat_policy_flag(parser)
    optimizers.add_optimizer_flag(parser, choices=CLASSIFIER_OPTIMIZERS,
                                  default="sgd")
    parser.add_argument(
        "--fused-loss", action="store_true",
        help="compute cross-entropy as target-gather + logsumexp (the LM "
             "loss form): the f32 row reduction fuses into the cast, no "
             "f32 [B, classes] log-prob tensor is materialized; parity "
             "to tolerance (summation order differs)")
    parser.add_argument(
        "--scan-blocks", action="store_true",
        help="roll each stage's identical stride-1 blocks into one "
             "nn.scan'd body with stacked params: compile time stops "
             "scaling with depth. Changes the param tree — checkpoints "
             "do not resume across this flip")
    parser.add_argument(
        "--aot", action="store_true",
        help="AOT-compile the train step through the persistent "
             "compilation cache before step 0 (the run path already "
             "does this via the overlapped prologue; this forces it for "
             "direct step users and records compile seconds)")


def classifier_step_options(args) -> dict:
    """kwargs for train.make_classifier_train_step from parsed flags."""
    return {
        "remat_policy": getattr(args, "remat_policy", "full"),
        "fused_loss": bool(getattr(args, "fused_loss", False)),
    }


def make_optimizer(args, default: str = "sgd"):
    """The classifier payloads' optimizer construction site — one thin
    indirection over optimizers.from_args so cifar/linear and the LM
    builders resolve ``--optimizer`` through the same code."""
    from tpu_operator.payload import optimizers

    return optimizers.from_args(args, default=default)


def aot_compile_cached(train_step, state, batch_args: tuple,
                       env: Optional[dict] = None
                       ) -> Tuple[Optional[Any], float, bool]:
    """AOT-compile a jitted train step THROUGH the persistent compilation
    cache (ROADMAP 1c: "AOT-compile through the warm cache everywhere"):
    enable the cache (JAX_COMPILATION_CACHE_DIR / TPUJOB_CACHE_PATH, if
    configured), subscribe the hit listener, then ``lower(...).compile()``
    for the live shapes. Returns ``(compiled_or_None, compile_seconds,
    cache_hit)`` — compiled is None when the step has no ``lower``;
    cache_hit is True when the executable deserialized from the
    persistent cache instead of compiling (the warm-restart fast path).
    Callers report compile_seconds OUT of their timed windows so first-
    window jitter never absorbs a compile."""
    from tpu_operator.payload import bootstrap
    from tpu_operator.payload import startup as startup_mod
    from tpu_operator.payload import train

    env = env if env is not None else os.environ
    cache_dir = bootstrap.enable_compilation_cache(env)
    if cache_dir:
        # The run path enables the cache before the backend initializes;
        # direct step users (bench, tests) reach here after warmup
        # compiles, and jax memoizes the no-cache state at first compile
        # — a later jax_compilation_cache_dir update is silently ignored
        # until the cache module re-initializes. Best-effort: private
        # module, disk entries survive the reset.
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # noqa: BLE001 — cache stays best-effort
            log.debug("compilation-cache reset unavailable", exc_info=True)
    listener = startup_mod.ensure_cache_listener()
    before = startup_mod.cache_hit_count() if listener else 0
    t0 = time.perf_counter()
    compiled = train.aot_compile_step(train_step, state, batch_args)
    compile_seconds = time.perf_counter() - t0
    hit = bool(listener and cache_dir
               and startup_mod.cache_hit_count() > before)
    return compiled, compile_seconds, hit
