"""Deterministic on-device data pipeline.

Zero-egress environment: datasets are synthetic but *learnable* — images are
class prototypes plus noise, so loss curves actually descend and the
BASELINE loss-parity check (CPU run vs sharded run) is meaningful. The
pipeline is host-side numpy feeding device arrays sharded over the mesh's
``data`` axis; in a multi-process job each process materializes only its own
shard (``make_array_from_process_local_data``), exactly how a real
per-worker input pipeline feeds a TPU pod slice.
"""

from __future__ import annotations

from typing import Iterator, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CIFAR_SHAPE = (32, 32, 3)


def synthetic_cifar(seed: int, batch: int, num_classes: int = 10,
                    noise: float = 0.1) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Infinite stream of (images[batch,32,32,3] f32, labels[batch] i32).

    Class k's images cluster around a fixed random prototype, so a model can
    fit them; noise keeps the task non-trivial.
    """
    rng = np.random.default_rng(seed)
    prototypes = rng.normal(0.5, 0.25, size=(num_classes, *CIFAR_SHAPE)).astype(
        np.float32
    )
    while True:
        labels = rng.integers(0, num_classes, size=batch).astype(np.int32)
        images = prototypes[labels] + rng.normal(
            0.0, noise, size=(batch, *CIFAR_SHAPE)
        ).astype(np.float32)
        yield images, labels


def synthetic_linear(seed: int, batch: int, dim: int = 8,
                     noise: float = 0.01) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """y = X·w* + b* + ε for a fixed hidden (w*, b*) — the linear-regression
    task of the reference's mxnet-linear-dist image (README.md:66-96)."""
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(dim, 1)).astype(np.float32)
    b_true = np.float32(rng.normal())
    while True:
        x = rng.normal(size=(batch, dim)).astype(np.float32)
        y = x @ w_true + b_true + rng.normal(
            0.0, noise, size=(batch, 1)
        ).astype(np.float32)
        yield x, y


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batches shard over the ``data`` axis, replicated over ``model``."""
    return NamedSharding(mesh, P("data"))


def put_global_batch(mesh: Mesh, *arrays: np.ndarray):
    """Place host arrays as global device arrays sharded on ``data``.

    Single-process: a plain sharded device_put. Multi-process: each process
    holds only its local shard, and the returned jax.Arrays are global views
    (the pjit programming model for pod slices).
    """
    sharding = batch_sharding(mesh)
    out = []
    multiprocess = jax.process_count() > 1
    for arr in arrays:
        if multiprocess:
            out.append(jax.make_array_from_process_local_data(sharding, arr))
        else:
            out.append(jax.device_put(arr, sharding))
    return tuple(out)
