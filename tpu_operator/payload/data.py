"""Deterministic on-device data pipeline.

Zero-egress environment: datasets are synthetic but *learnable* — images are
class prototypes plus noise, so loss curves actually descend and the
BASELINE loss-parity check (CPU run vs sharded run) is meaningful. The
pipeline is host-side numpy feeding device arrays sharded over the mesh;
in a multi-process job every process generates the identical global batch
(seed-deterministic) and contributes its addressable slices
(``make_array_from_process_local_data`` with explicit ``global_shape``),
exactly how a real per-worker input pipeline feeds a TPU pod slice.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CIFAR_SHAPE = (32, 32, 3)


def synthetic_cifar(seed: int, batch: int, num_classes: int = 10,
                    noise: float = 0.1) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Infinite stream of (images[batch,32,32,3] f32, labels[batch] i32).

    Class k's images cluster around a fixed random prototype, so a model can
    fit them; noise keeps the task non-trivial.
    """
    rng = np.random.default_rng(seed)
    prototypes = rng.normal(0.5, 0.25, size=(num_classes, *CIFAR_SHAPE)).astype(
        np.float32
    )
    while True:
        labels = rng.integers(0, num_classes, size=batch).astype(np.int32)
        images = prototypes[labels] + rng.normal(
            0.0, noise, size=(batch, *CIFAR_SHAPE)
        ).astype(np.float32)
        yield images, labels


def npz_classification(path: str, seed: int, batch: int,
                       num_classes: int = 0, image_shape: Tuple[int, ...] = ()
                       ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Stream (images, labels) batches from a mounted ``.npz`` with arrays
    ``images [N,H,W,C]`` (integer dtypes scaled to [0, 1]) and ``labels
    [N]`` — the real-data counterpart of synthetic_cifar for deployments
    that mount a dataset volume (the reference's real-CIFAR images did the
    same inside its user containers, README.md:126-167). Seed-deterministic
    epoch shuffles, so every process of a multi-controller job draws the
    identical global stream.

    Validates eagerly (the model was traced on fixed shapes, and the
    jit-clamped take_along_axis in the loss would otherwise train silently
    wrong on out-of-range labels): pass ``num_classes``/``image_shape`` to
    fail fast on a mismatched dataset instead of mid-training.
    """
    with np.load(path) as z:
        raw = z["images"]
        labels = z["labels"].astype(np.int32)
    images = raw.astype(np.float32)
    if np.issubdtype(raw.dtype, np.integer):
        images = images / np.float32(255.0)
    n = len(images)
    if len(labels) != n:
        raise ValueError(
            f"dataset {path}: {n} images but {len(labels)} labels")
    if n < batch:
        raise ValueError(f"dataset {path} has {n} examples < batch {batch}")
    if image_shape and tuple(images.shape[1:]) != tuple(image_shape):
        raise ValueError(
            f"dataset {path} images are {images.shape[1:]}, model expects "
            f"{tuple(image_shape)}")
    if num_classes and (labels.min() < 0 or labels.max() >= num_classes):
        raise ValueError(
            f"dataset {path} labels span [{labels.min()}, {labels.max()}], "
            f"model has {num_classes} classes")

    def stream():
        rng = np.random.default_rng(seed)
        while True:
            perm = rng.permutation(n)
            for i in range(0, n - batch + 1, batch):
                idx = perm[i:i + batch]
                yield images[idx], labels[idx]

    return stream()


def synthetic_linear(seed: int, batch: int, dim: int = 8,
                     noise: float = 0.01) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """y = X·w* + b* + ε for a fixed hidden (w*, b*) — the linear-regression
    task of the reference's mxnet-linear-dist image (README.md:66-96)."""
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(dim, 1)).astype(np.float32)
    b_true = np.float32(rng.normal())
    while True:
        x = rng.normal(size=(batch, dim)).astype(np.float32)
        y = x @ w_true + b_true + rng.normal(
            0.0, noise, size=(batch, 1)
        ).astype(np.float32)
        yield x, y


def synthetic_lm(seed: int, batch: int, seq_len: int,
                 vocab: int = 256) -> Iterator[Tuple[np.ndarray]]:
    """Infinite stream of token sequences [batch, seq_len] i32 following a
    fixed affine recurrence x_{t+1} = (a·x_t + b) mod vocab with random
    starts — a deterministic next-token structure a small LM fits quickly,
    so long-context loss curves descend and parity checks are meaningful."""
    rng = np.random.default_rng(seed)
    # x → a·x + b mod vocab is a bijection iff gcd(a, vocab) == 1; pick the
    # first odd multiplier coprime to the caller's vocab.
    a, b = 5, 17
    while np.gcd(a, vocab) != 1:
        a += 2
    while True:
        seq = np.empty((batch, seq_len), np.int64)
        seq[:, 0] = rng.integers(0, vocab, size=batch)
        for t in range(1, seq_len):
            seq[:, t] = (a * seq[:, t - 1] + b) % vocab
        yield (seq.astype(np.int32),)


def local_batch_rows(mesh: Mesh, batch: int, seq_len: int,
                     spec: P = None) -> Optional[Tuple[int, int]]:
    """The contiguous [lo, hi) range of *global* batch rows this process
    contributes under ``batch_sharding(mesh, spec)`` — ``None`` when every
    row is needed (single process). Derived from the sharding's own
    device→index map, not from device-order assumptions, so it is exact
    for any (data, seq, …) layout. Multi-host input sharding: each host
    mmap-reads only the window rows it will actually contribute
    (token_file_lm ``local_rows``), instead of materializing the full
    global batch N× across the job."""
    if jax.process_count() <= 1:
        return None
    sharding = batch_sharding(mesh, spec)
    starts, stops = [], []
    for dev, idx in sharding.devices_indices_map((batch, seq_len)).items():
        if dev.process_index != jax.process_index():
            continue
        rows = idx[0]
        # NamedSharding over a Mesh only ever produces contiguous row
        # blocks per device; a strided slice would break the single-span
        # collapse below, so refuse it rather than silently over-reading.
        if rows.step not in (None, 1):
            raise ValueError(
                f"local_batch_rows: strided batch shard {rows} is not "
                f"supported (contiguous spans only)")
        starts.append(rows.start or 0)
        stops.append(rows.stop if rows.stop is not None else batch)
    if not starts:
        return (0, 0)
    lo, hi = min(starts), max(stops)
    # Merge before summing: replicated batch rows (a model/seq axis within
    # this process) report identical spans, and a raw sum would double-
    # count them and mask a real gap.
    merged, owned = [], 0
    for a, b in sorted(zip(starts, stops)):
        if merged and a <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], b)
        else:
            merged.append([a, b])
    owned = sum(b - a for a, b in merged)
    if owned < hi - lo:
        # Device order gave this process non-adjacent row blocks: the
        # collapsed span over-reads the gap rows. Correct (extras are
        # dropped by make_array_from_process_local_data) but the N-fold
        # read saving degrades — surface it instead of hiding it.
        import logging
        logging.getLogger(__name__).warning(
            "local_batch_rows: process %d owns %d rows but spans [%d, %d) "
            "(%d rows read); non-contiguous shard layout degrades the "
            "sharded-read saving", jax.process_index(), owned, lo, hi,
            hi - lo)
    return (lo, hi)


def token_file_lm(path: str, seed: int, batch: int, seq_len: int,
                  vocab: int = 0,
                  local_rows: Optional[Tuple[int, int]] = None,
                  ) -> Iterator[Tuple[np.ndarray]]:
    """Stream [batch, seq_len] i32 token batches from a mounted ``.npy``
    token file — the real-data counterpart of synthetic_lm, mirroring the
    CIFAR ``.npz`` discipline (npz_classification): mounted volume, eager
    validation, seed-deterministic order.

    The file is a 1-D integer token array, **memory-mapped** — a
    multi-GB corpus costs no resident RAM; each batch gathers only the
    windows it touches. Tokens chunk into non-overlapping ``seq_len``
    windows (remainder dropped); every epoch draws a fresh seeded
    permutation of windows, so the stream is an exact function of
    (path contents, seed) — which is what makes two properties hold:

    - every process of a multi-controller job draws the identical global
      batch and contributes its addressable slices (put_global_batch's
      contract, same as the synthetic generators);
    - checkpoint resume replays exactly: train_loop fast-forwards the
      stream past the ``start`` batches the previous attempt consumed, and
      determinism guarantees batches ``start..`` match what an
      uninterrupted run would have seen.

    ``vocab`` validates eagerly (min/max over the mapped array — a
    sequential scan, no materialization): out-of-range tokens would
    otherwise train silently wrong through the loss's clamped gather.

    ``local_rows=(lo, hi)`` (from :func:`local_batch_rows`) makes this
    process mmap-read and copy **only rows lo..hi** of each global batch
    — the rows it will contribute through ``put_global_batch``. The
    yielded array keeps the full [batch, seq_len] shape (rows outside
    the range are zeros, never consumed:
    ``make_array_from_process_local_data`` slices exactly the
    addressable portion), and the window permutation is drawn
    identically on every process, so the global batch sequence — and
    therefore checkpoint-resume fast-forward — is unchanged from the
    full-read path.
    """
    tokens = np.load(path, mmap_mode="r")
    if tokens.ndim != 1 or not np.issubdtype(tokens.dtype, np.integer):
        raise ValueError(
            f"token file {path}: expected a 1-D integer array, got "
            f"{tokens.dtype}{list(tokens.shape)}")
    n_windows = len(tokens) // seq_len
    if n_windows < batch:
        raise ValueError(
            f"token file {path}: {len(tokens)} tokens = {n_windows} "
            f"windows of {seq_len} < batch {batch}")
    if vocab:
        lo, hi = int(tokens.min()), int(tokens.max())
        if lo < 0 or hi >= vocab:
            raise ValueError(
                f"token file {path} spans [{lo}, {hi}], model vocab is "
                f"{vocab}")

    lo, hi = local_rows if local_rows is not None else (0, batch)

    def stream():
        rng = np.random.default_rng(seed)
        while True:
            perm = rng.permutation(n_windows)
            for i in range(0, n_windows - batch + 1, batch):
                idx = perm[i:i + batch]
                out = np.zeros((batch, seq_len), np.int32)
                for row in range(lo, hi):
                    w = idx[row]
                    out[row] = tokens[w * seq_len:(w + 1) * seq_len]
                yield (out,)

    return stream()


def lm_batches(args, mesh: Optional[Mesh] = None,
               spec: P = None) -> Iterator[Tuple[np.ndarray]]:
    """The shared LM data entry: ``--data /path/tokens.npy`` selects the
    memory-mapped real-token stream, else the synthetic recurrence — one
    switch for transformer/pipeline/moe so the payloads cannot drift.
    With ``mesh`` (and the batch ``spec`` the payload will pass to
    put_global_batch), multi-process jobs read only their own rows of
    the token file (:func:`local_batch_rows`)."""
    data_path = getattr(args, "data", "")
    if data_path:
        local_rows = (local_batch_rows(mesh, args.batch, args.seq_len,
                                       spec=spec)
                      if mesh is not None else None)
        return token_file_lm(data_path, args.seed, args.batch, args.seq_len,
                             vocab=args.vocab, local_rows=local_rows)
    return synthetic_lm(args.seed, args.batch, args.seq_len,
                        vocab=args.vocab)


def device_prefetch(mesh: Mesh, batches, spec: P = None,
                    depth: int = 2, control=None,
                    pipeline: bool = False) -> Iterator[tuple]:
    """Wrap a host-batch iterator into a device-batch iterator that keeps
    up to ``depth`` transfers in flight ahead of consumption.

    ``jax.device_put`` (and the multi-process placement path) is
    asynchronous — it returns immediately with the copy enqueued — so
    issuing the next batches' transfers *before* the current step is
    dispatched overlaps host→device bytes behind device compute, the same
    double-buffering a tf.data/grain input pipeline does on a real TPU VM.

    Depth convention: ``depth > 0`` buffers that many batches; ``depth ==
    0`` is the EXPLICIT unbuffered per-step put (what bench.py's
    pre-staged HBM cycles effectively are — put_global_batch passes
    already-placed arrays through untouched); negative raises. Note the
    spec-level convention differs: ``spec.dataPlane.prefetchDepth: 0``
    means AUTO and is resolved by ``autotune.resolve_prefetch_depth``
    *before* a depth reaches this function — a spec 0 passed through raw
    used to silently degenerate to unbuffered, the opposite of its
    documented meaning.

    ``control`` (autotune.PrefetchControl) makes the buffer RESIZABLE at
    iteration boundaries: the live target depth is re-read before each
    refill, so the closed-loop controller can deepen or shrink the
    in-flight window mid-stream without touching batch order.

    ``pipeline=True`` moves the host-side work — the iterator's
    ``next()`` (batch generation, file I/O) plus the placement call —
    onto a bounded background thread (autotune.HostPipeline): only the
    device transfer overlapped before, the host cost was serialized into
    the step's DATA phase.
    """
    from collections import deque

    if depth < 0:
        raise ValueError(
            f"device_prefetch depth must be >= 0, got {depth} (spec-level "
            f"0=auto is resolved by autotune.resolve_prefetch_depth)")
    it = iter(batches)
    # One sharding per stream, not one per step: batch_sharding builds a
    # NamedSharding (mesh + parsed spec) whose construction cost has no
    # business on the steady step path.
    sharding = batch_sharding(mesh, spec)
    # Identity memo for already-placed streams (bench.py pre-stages a few
    # batches in HBM and cycles them): when a put was a pure pass-through,
    # the SAME input tuple next cycle short-circuits to the same output —
    # a dict hit instead of per-array sharding comparisons. Only
    # pass-throughs are memoized, so the memo holds references exclusively
    # to device arrays the caller's cycle keeps alive anyway; generated
    # host streams never populate it.
    placed: dict = {}

    def place(arrs):
        hit = placed.get(id(arrs))
        if hit is not None and hit[0] is arrs:
            return hit[1]
        out = put_global_batch(mesh, *arrs, spec=spec, sharding=sharding)
        if len(placed) < 64 and len(out) == len(arrs) \
                and all(o is a for o, a in zip(out, arrs)):
            placed[id(arrs)] = (arrs, out)
        return out

    if pipeline:
        from tpu_operator.payload import autotune as autotune_mod

        pl = autotune_mod.HostPipeline(
            fill=lambda: place(next(it)), control=control,
            depth=max(1, depth))
        try:
            while True:
                try:
                    yield pl.get()
                except StopIteration:
                    return
        finally:
            pl.close()

    if control is None and depth == 0:
        for arrs in it:
            yield place(arrs)
        return
    buf: deque = deque()
    exhausted = False
    while True:
        # Refill to the live target at the iteration boundary — a
        # resized control takes effect here: growth fills ahead, a
        # shrink simply stops refilling until the buffer drains down.
        target = depth if control is None else max(1, control.depth)
        while not exhausted and len(buf) < target:
            try:
                buf.append(place(next(it)))
            except StopIteration:
                exhausted = True
        if not buf:
            return
        yield buf.popleft()


def batch_sharding(mesh: Mesh, spec: P = None) -> NamedSharding:
    """Batches shard over the ``data`` axis by default; pass ``spec`` for
    additional dims (e.g. P("data", "seq") for sequence-sharded tokens)."""
    return NamedSharding(mesh, spec if spec is not None else P("data"))


def put_global_batch(mesh: Mesh, *arrays: np.ndarray, spec: P = None,
                     sharding: NamedSharding = None):
    """Place host arrays as global device arrays (default: sharded on
    ``data``; pass ``spec`` to shard more dims, e.g. sequence).

    Single-process: a plain sharded device_put. Multi-process: the synthetic
    generators are seed-deterministic, so every process holds the identical
    *global* batch; passing ``global_shape=arr.shape`` tells JAX exactly
    that, and each process contributes only its addressable slices (the pjit
    programming model for pod slices). Without it, JAX would infer a global
    shape multiplied across processes — wrong on any axis (like ``seq``)
    that spans processes.

    ``sharding`` short-circuits the per-call ``batch_sharding`` build for
    callers that place many batches against one layout (device_prefetch
    constructs it once per stream).
    """
    if sharding is None:
        sharding = batch_sharding(mesh, spec)
    out = []
    multiprocess = jax.process_count() > 1
    for arr in arrays:
        if isinstance(arr, jax.Array) and arr.sharding == sharding:
            # Already placed exactly as requested (e.g. bench.py pre-stages
            # batches in HBM and cycles them back through the train loop):
            # pass through — re-placing is wasted transfer, and
            # make_array_from_process_local_data would reject it.
            out.append(arr)
        elif isinstance(arr, jax.Array) and not arr.is_fully_addressable:
            # A global array we cannot re-place from here (this process only
            # holds its shards) whose layout is NOT the requested one:
            # passing it through would silently train on the wrong
            # partitioning, so fail loudly instead.
            raise ValueError(
                f"put_global_batch: global array sharded {arr.sharding} "
                f"cannot be re-placed to requested {sharding}")
        elif multiprocess:
            out.append(jax.make_array_from_process_local_data(
                sharding, arr, global_shape=arr.shape))
        else:
            out.append(jax.device_put(arr, sharding))
    return tuple(out)
