"""8-bit Adam — block-quantized moments with stochastic rounding.

The flagship's optimizer state at f32 is 2 GB of first moment + 2 GB of
second moment for 491M params; the update pass reads and writes all of it
every step, so moments are both an HBM-capacity and an HBM-bandwidth tax
(docs/benchmarks.md attributes ~134 ms/step to the elementwise/optimizer
bucket). This module stores both Adam moments as int8 with per-block f32
absmax scales — 0.5 GB each for the flagship, a 4x shrink — using
block-wise quantization (public technique: Dettmers et al. 2021, "8-bit
Optimizers via Block-wise Quantization"; the nonlinear quantile code of
that paper is replaced here by TPU-friendly closed-form maps):

- ``m`` (EMA of gradients, signed, roughly zero-centred) quantizes
  linearly: ``q = round(m / scale)`` with ``scale = absmax / 127`` per
  block of 256 elements.
- ``v`` (EMA of squared gradients, non-negative, spans many orders of
  magnitude) quantizes in the **sqrt domain**: ``q = round(sqrt(v) /
  scale)``, halving the dynamic range the 8 bits must cover; the update
  consumes ``sqrt(v)`` anyway, so the quantization error lands exactly
  where the math is least sensitive.
- Both moments round **stochastically**: ``floor(x + u)`` with u ~
  U[0,1). An EMA with decay 0.999 moves ~1e-3 of its magnitude per step
  — far below one int8 ulp — so round-to-nearest would freeze it
  (swamping); stochastic rounding preserves the increment in
  expectation. The PRNG key rides the optimizer state, split per step
  and folded per leaf.

Blocks run along each parameter's **last axis** ([..., nblocks, 256]
values, [..., nblocks] scales), so leading axes — the ones the payloads'
sharding rules partition (pipeline stage stacking, FSDP dim 0, TP) —
survive quantization and the moments shard exactly like their parameter.
Everything is elementwise — one fused XLA pass per leaf, no gathers, no
host work. The reference has no optimizer at all
(its compute plane lives in user images; SURVEY.md §0); this is
beating-the-baseline work on the repo's own measured bottleneck.
"""

from __future__ import annotations

from typing import Any, NamedTuple

# jax/optax import lazily inside functions: the payload entry modules
# (transformer, moe, pipeline) keep module import light so bootstrap can
# set platform env vars before jax initializes, and they import this
# module at parse time for the shared --optimizer flag.

BLOCK = 256


class Quantized(NamedTuple):
    """One int8-quantized tensor in last-axis block layout: values
    ``[..., nblocks, BLOCK]`` plus per-block f32 scales ``[..., nblocks]``.
    Leading axes are the parameter's own — so every path-based sharding
    rule in the payloads (pipeline stage-stacking on dim 0, FSDP dim-0
    sharding, TP on trailing dims) applies to the moments exactly as it
    does to their parameter."""
    q: Any
    scale: Any


class Adam8State(NamedTuple):
    count: Any
    key: Any
    m: Any  # pytree of Quantized
    v: Any  # pytree of Quantized


def _to_blocks(x):
    """[..., n] → [..., nblocks, BLOCK] (last axis zero-padded); scalars
    become (1,) first."""
    import jax.numpy as jnp

    if x.ndim == 0:
        x = x.reshape(1)
    pad = (-x.shape[-1]) % BLOCK
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    return x.reshape(*x.shape[:-1], -1, BLOCK)


def _from_blocks(x, shape):
    """[..., nblocks, BLOCK] → original ``shape`` (drops padding)."""
    flat_last = x.reshape(*x.shape[:-2], -1)
    n_last = shape[-1] if shape else 1
    return flat_last[..., :n_last].reshape(shape)


def _quantize(x, key, sqrt_domain: bool) -> Quantized:
    """Block-quantize f32 [..., nb, BLOCK] → int8. ``sqrt_domain`` stores
    sqrt(x) (x must be >= 0). ``key=None`` rounds to nearest (init)."""
    import jax
    import jax.numpy as jnp

    if sqrt_domain:
        x = jnp.sqrt(x)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    ratio = x / scale
    if key is None:
        q = jnp.round(ratio)
    else:
        u = jax.random.uniform(key, ratio.shape, jnp.float32)
        q = jnp.floor(ratio + u)
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return Quantized(q=q, scale=scale[..., 0])


def _dequantize(t: Quantized, sqrt_domain: bool):
    x = t.q.astype("float32") * t.scale[..., None]
    if sqrt_domain:
        x = x * x
    return x


def from_args(args, default: str = "adam"):
    """Build the payload optimizer from parsed CLI args — the one
    construction site shared by every payload (``--optimizer
    sgd|adam|adam8``, ``--adam-mu-dtype`` for plain adam, ``--momentum``
    for sgd where the payload defines it). ``default`` is the payload's
    own seed-path optimizer: adam for the LM family, sgd for the
    classifier/regression payloads — so an unconfigured flagship keeps
    training exactly as it always has."""
    import jax.numpy as jnp
    import optax

    choice = getattr(args, "optimizer", default) or default
    if choice == "adam8":
        return adam8(args.lr, seed=getattr(args, "seed", 0))
    if choice == "sgd":
        # Pass momentum exactly as the payload defines it (None when the
        # parser has no --momentum, e.g. linear.py): optax.sgd's state
        # tree differs between momentum=None and momentum=0.0, and the
        # seed paths' checkpoints must keep restoring bit-for-bit.
        return optax.sgd(args.lr, momentum=getattr(args, "momentum", None))
    mu_dtype = (jnp.bfloat16
                if getattr(args, "adam_mu_dtype", "f32") == "bf16" else None)
    return optax.adam(args.lr, mu_dtype=mu_dtype)


def add_optimizer_flag(parser, choices=("adam", "adam8"),
                       default: str = "adam") -> None:
    """``--optimizer`` CLI flag, shared by every payload parser. The LM
    payloads keep the historical (adam, adam8) choice set; classifier
    payloads pass ``("sgd", "adam", "adam8")`` with sgd as the seed-path
    default (payload/compute.py owns that wiring)."""
    parser.add_argument(
        "--optimizer", choices=tuple(choices), default=default,
        help="adam8 = int8 block-quantized moments with stochastic "
             "rounding (4x less optimizer HBM than f32 adam; "
             "trajectory-parity-tested)"
             + (" ; sgd = the classifier seed path "
                "(momentum from --momentum where defined)"
                if "sgd" in choices else ""))


def adam8(learning_rate, b1: float = 0.9, b2: float = 0.999,
          eps: float = 1e-8, seed: int = 0):
    """Drop-in :func:`optax.adam` with int8 block-quantized moments.

    The update dequantizes both moments, applies the standard
    bias-corrected Adam step in f32, and requantizes with stochastic
    rounding — per leaf, in one fused elementwise pass over [nb, 256]
    panels. Numerics: tests/test_optimizers.py pins the loss trajectory
    against f32 optax.adam at tolerance over dozens of steps."""
    import jax
    import jax.numpy as jnp
    import optax

    def init(params):
        def q_init(p):
            shape = p.shape or (1,)
            nb = -(-shape[-1] // BLOCK)
            return Quantized(
                q=jnp.zeros((*shape[:-1], nb, BLOCK), jnp.int8),
                scale=jnp.full((*shape[:-1], nb), 1e-12 / 127.0,
                               jnp.float32),
            )

        return Adam8State(
            count=jnp.zeros((), jnp.int32),
            key=jax.random.key_data(jax.random.key(seed)),
            m=jax.tree_util.tree_map(q_init, params),
            v=jax.tree_util.tree_map(q_init, params),
        )

    def update(grads, state, params=None):
        del params
        count = state.count + 1
        step_key = jax.random.fold_in(
            jax.random.wrap_key_data(state.key), count)
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        # schedules see the pre-increment count, matching
        # optax.scale_by_schedule (a warmup-from-0 schedule must yield
        # lr(0) on the first update, not lr(1))
        lr = (learning_rate(state.count) if callable(learning_rate)
              else learning_rate)
        bc1 = 1.0 - b1 ** count.astype(jnp.float32)
        bc2 = 1.0 - b2 ** count.astype(jnp.float32)

        m_leaves = treedef.flatten_up_to(state.m)
        v_leaves = treedef.flatten_up_to(state.v)
        updates, new_m, new_v = [], [], []
        for i, (g, mq, vq) in enumerate(zip(leaves, m_leaves, v_leaves)):
            km, kv = jax.random.split(jax.random.fold_in(step_key, i))
            gp = _to_blocks(g.astype(jnp.float32))
            m = b1 * _dequantize(mq, False) + (1.0 - b1) * gp
            v = b2 * _dequantize(vq, True) + (1.0 - b2) * gp * gp
            # Quantization-noise floor on the denominator. Within a
            # heterogeneous block an element can keep a resolvable m
            # (linear code, ~1/254 of absmax) while its v — scaling as
            # m² — underflows the sqrt-domain code (~1/64516 of absmax)
            # to zero, and m/(sqrt(0)+eps) then explodes the step (seen
            # as loss 1e9 at the flagship; invisible at homogeneous
            # small-test scales). Anything below half an ulp of the v
            # quantizer is unresolvable, so bound the denominator by it
            # instead of trusting a dequantized zero. The stored EMA
            # stays unfloored — this biases only the step size, safely
            # downward, exactly where v carries no information.
            v_floor = b2 * (0.5 * vq.scale[..., None]) ** 2
            upd = -lr * (m / bc1) / (
                jnp.sqrt(jnp.maximum(v, v_floor) / bc2) + eps)
            updates.append(_from_blocks(upd, g.shape).astype(g.dtype))
            new_m.append(_quantize(m, km, False))
            new_v.append(_quantize(v, kv, True))

        return treedef.unflatten(updates), Adam8State(
            count=count,
            key=jax.random.key_data(step_key),
            m=treedef.unflatten(new_m),
            v=treedef.unflatten(new_v),
        )

    return optax.GradientTransformation(init, update)
