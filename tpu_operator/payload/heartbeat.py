"""Training-step heartbeats: payload → operator status server.

The missing liveness signal of the whole reference lineage: a TPU slice
whose JAX group hangs (deadlocked collective, stuck host transfer, wedged
DCN link) keeps every pod Running — kubelet sees a healthy process, the
operator sees healthy pods, and the only symptom is *silence*. The
heartbeat closes that gap from the inside: process 0 of the group posts
step telemetry (step, step-time, tokens/sec, loss) to the operator's
status server (``POST /api/heartbeat``), which surfaces it as per-job
gauges in ``/metrics`` and as ``status.lastHeartbeat`` on the TPUJob — a
stale timestamp there IS the hang alarm, visible from ``kubectl get``.

Strictly best-effort by design: the reporter never raises, never blocks
the step loop beyond a short socket timeout, and rate-limits itself — a
down status server costs the payload one failed connect per interval,
nothing more. The env contract (TPUJOB_STATUS_URL, injected by
trainer/replicas.py when the operator advertises a URL) gates the whole
feature: unset means ``from_env`` returns None and training runs exactly
as before.
"""

from __future__ import annotations

import json
import logging
import math
import os
import time
from typing import Any, Callable, Dict, Optional

log = logging.getLogger(__name__)

DEFAULT_INTERVAL = 10.0  # seconds between posts (per process)
POST_TIMEOUT = 2.0       # socket timeout: never stall a training step


def interval_of(heartbeat: Any) -> float:
    """THE posting cadence of a reporter-shaped object — the single
    definition every consumer shares (the reporter's own ``due()``, the
    train-loop startup ticker, the autotune runtime's host-budget
    pacing). Each used to re-derive it with its own hardcoded fallback
    (``getattr(hb, "interval", 10.0)``), which only agreed with
    DEFAULT_INTERVAL by coincidence; a reporter with a malformed or
    negative interval now resolves identically everywhere (0 stays 0 —
    the explicit every-step cadence tests and benches use)."""
    try:
        interval = float(getattr(heartbeat, "interval", DEFAULT_INTERVAL))
    except (TypeError, ValueError):
        return DEFAULT_INTERVAL
    if not math.isfinite(interval) or interval < 0:
        return DEFAULT_INTERVAL
    return interval


def _http_post(url: str, body: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    import urllib.request

    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=POST_TIMEOUT) as resp:
        # The 200 ACK body is the operator's only control channel back
        # into the payload (the on-demand profile directive rides it);
        # non-JSON bodies are fine — the ACK is then just an ACK.
        try:
            parsed = json.loads(resp.read() or b"{}")
        except (ValueError, json.JSONDecodeError):
            return None
        return parsed if isinstance(parsed, dict) else None


class HeartbeatReporter:
    """Posts step telemetry to ``{base_url}/api/heartbeat``.

    ``tokens_per_batch`` (> 0) turns step cadence into tokens/sec — LM
    payloads pass B·T; payloads without a token notion leave it 0 and the
    field is omitted. ``clock``/``poster`` are injectable for tests.

    ``cadence_only`` is the non-zero-process flavor (straggler
    detection): the beat carries only identity + step cadence + the
    ``stepTiming`` phase digest — no loss/tokens/checkpoint/startup
    payload, which stays process 0's single stream. The controller feeds
    these into its per-process gang cadence map and nothing else."""

    def __init__(self, base_url: str, job_name: str,
                 namespace: str = "default", process_id: int = 0,
                 attempt: int = 0, interval: float = DEFAULT_INTERVAL,
                 tokens_per_batch: int = 0,
                 clock: Callable[[], float] = time.monotonic,
                 poster: Optional[Callable[[str, Dict[str, Any]], None]] = None,
                 cadence_only: bool = False):
        self.url = base_url.rstrip("/") + "/api/heartbeat"
        self.job_name = job_name
        self.namespace = namespace
        self.process_id = process_id
        self.attempt = attempt
        self.interval = interval
        self.tokens_per_batch = tokens_per_batch
        self.cadence_only = cadence_only
        self._clock = clock
        self._poster = poster or _http_post
        self._last_post: Optional[float] = None
        self._last_step: Optional[int] = None
        self._failed_once = False
        # Async host path (payload/autotune.py): when set (an
        # ``AsyncHost.submit``-shaped callable) steady-state posts hand
        # their serialization + socket round-trip to the worker thread
        # and the step thread pays an enqueue. Beats carrying the
        # one-shot ``startup`` breakdown still post synchronously: their
        # ACK/retry protocol (the 503-until-reconciled dance) needs the
        # real result.
        self.async_sink: Optional[Callable[..., bool]] = None
        # On-demand deep-profiling channel (process 0 only): a directive
        # arriving in a heartbeat ACK is stashed here until the train
        # loop takes it; the capture result is attached to every
        # subsequent beat until a 200 ACK clears it (the startup
        # one-shot protocol). Seen ids dedup a directive raced by its
        # own result fold.
        self._profile_directive: Optional[Dict[str, Any]] = None
        self._profile_result: Optional[Dict[str, Any]] = None
        self._profile_seen: set = set()
        # Cooperative-drain channel (process 0 only), same shape as the
        # profile channel: the operator's drain directive arrives in a
        # heartbeat ACK, is stashed until the train loop takes it, and the
        # payload's ``drainAck {id, step}`` one-shot rides every
        # subsequent beat until a 200 clears it — so the operator learns
        # the drain was adopted even across a lossy status-server window.
        self._drain_directive: Optional[Dict[str, Any]] = None
        self._drain_ack: Optional[Dict[str, Any]] = None
        self._drain_seen: set = set()

    def due(self, _step: int) -> bool:
        now = self._clock()
        return self._last_post is None \
            or now - self._last_post >= interval_of(self)

    def report(self, step: int, metrics: Optional[Dict[str, Any]] = None,
               checkpoint: Optional[Dict[str, Any]] = None,
               startup: Optional[Dict[str, Any]] = None,
               steptiming: Optional[Dict[str, Any]] = None,
               dataplane: Optional[Dict[str, Any]] = None,
               serving: Optional[Dict[str, Any]] = None) -> bool:
        """Post one heartbeat; returns True when the post succeeded. Step
        time is averaged over the steps since the previous post, so it is
        meaningful at any reporting interval.

        ``checkpoint`` is the payload's durability state
        (``Checkpointer.stats()``): last verified step, save failures,
        restore fallbacks — surfaced as ``lastCheckpointStep`` /
        ``checkpointSaveFailures`` / ``checkpointRestoreFallbacks`` so the
        operator's restart decisions and ``status.checkpoint`` see which
        step is actually durable.

        ``startup`` is the attempt's startup-phase breakdown
        (``StartupTracker.breakdown()``), attached once after the first
        step — the operator folds it into ``status.startup`` and the
        ``job_startup_seconds`` histograms.

        ``steptiming`` is the flight recorder's windowed phase digest
        (``StepRecorder.summary()``) — per-phase p50/p95/max since the
        previous digest. The operator folds process 0's into
        ``status.stepTiming`` + the ``job_step_phase_seconds`` histograms
        and feeds EVERY process's into the gang straggler detector.

        ``dataplane`` is the self-tuning data plane's current knob state
        (``DataPlaneRuntime.wire()``): live prefetch depth, host-path
        mode, effective checkpoint cadence, and the per-knob adjustment
        counters — the operator folds it into ``status.dataPlane`` +
        the ``job_prefetch_depth`` gauge and the
        ``job_autotune_adjustments_total`` counters."""
        now = self._clock()
        body: Dict[str, Any] = {
            "namespace": self.namespace,
            "name": self.job_name,
            "step": int(step),
            "processId": self.process_id,
            "attempt": self.attempt,
        }
        if steptiming:
            body["stepTiming"] = dict(steptiming)
        if serving:
            # Serving beats come from EVERY replica (each is its own
            # server), so — unlike loss/checkpoint/startup — they ride
            # cadence-only reporters too: readiness and traffic are
            # per-replica facts the controller aggregates.
            body["serving"] = dict(serving)
        if startup and not self.cadence_only:
            body["startup"] = dict(startup)
        if dataplane and not self.cadence_only:
            # Knob state is process 0's stream (one controller per job
            # worth reporting); cadence beats stay minimal.
            body["dataPlane"] = dict(dataplane)
        if self._last_post is not None and self._last_step is not None \
                and step > self._last_step:
            per_step = (now - self._last_post) / (step - self._last_step)
            body["stepTimeSeconds"] = round(per_step, 6)
            if self.tokens_per_batch > 0 and per_step > 0 \
                    and not self.cadence_only:
                body["tokensPerSec"] = round(self.tokens_per_batch / per_step, 3)
        if self.cadence_only:
            # Non-zero processes contribute cadence for straggler
            # detection only; everything else is process 0's stream.
            self._last_post, self._last_step = now, int(step)
            return self._post(body)
        if checkpoint:
            if checkpoint.get("lastCheckpointStep") is not None:
                body["lastCheckpointStep"] = int(
                    checkpoint["lastCheckpointStep"])
            for src, dst in (("saveFailures", "checkpointSaveFailures"),
                             ("restoreFallbacks",
                              "checkpointRestoreFallbacks"),
                             # Remote warm-start store (write-behind
                             # uploader counters, merged into stats()).
                             ("lastUploadedStep", "storeLastUploadedStep"),
                             ("uploadFailures", "storeUploadFailures")):
                if checkpoint.get(src) is not None:
                    body[dst] = int(checkpoint[src])
        loss = (metrics or {}).get("loss")
        if loss is not None:
            try:
                loss = float(loss)
                # A diverged step yields NaN/Inf — the server rejects those
                # (they would poison CRD status JSON), so skip the field and
                # let the heartbeat still carry liveness.
                if math.isfinite(loss):
                    body["loss"] = loss
            except (TypeError, ValueError):
                pass
        self._last_post, self._last_step = now, int(step)
        if self._profile_result is not None:
            body["profile"] = dict(self._profile_result)
        if self._drain_ack is not None:
            body["drainAck"] = dict(self._drain_ack)
        return self._post(body)

    def take_profile_directive(self) -> Optional[Dict[str, Any]]:
        """The pending on-demand profile directive (``{"id", "steps"}``)
        stashed from a heartbeat ACK, consumed exactly once — the train
        loop polls this after each due beat."""
        directive, self._profile_directive = self._profile_directive, None
        return directive

    def attach_profile_result(self, result: Dict[str, Any]) -> None:
        """Attach a finished capture's result to every subsequent beat
        until a 200 ACK clears it (the startup one-shot protocol); the
        id joins the seen set so the directive — still Requested until
        the controller folds this very result — is never re-taken."""
        self._profile_seen.add(str(result.get("id", "")))
        self._profile_result = dict(result)

    def take_drain_directive(self) -> Optional[Dict[str, Any]]:
        """The pending cooperative-drain directive (``{"id", "reason",
        ...}``) stashed from a heartbeat ACK, consumed exactly once — the
        train loop polls this after each due beat and arms the planned-
        drain latch."""
        directive, self._drain_directive = self._drain_directive, None
        return directive

    def attach_drain_ack(self, ack: Dict[str, Any]) -> None:
        """Attach the drain adoption ACK (``{"id", "step"}`` — the
        boundary step the gang agreed to drain at) to every subsequent
        beat until a 200 clears it; the id joins the seen set so the
        directive — resent by the operator until its status folds to
        Acked — is never re-taken."""
        self._drain_seen.add(str(ack.get("id", "")))
        self._drain_ack = dict(ack)

    def _post(self, body: Dict[str, Any]) -> bool:
        """Best-effort POST shared by every report flavor: never raises,
        logs the first failure of a streak rather than a stream. With an
        ``async_sink`` wired (the autotune host worker), steady posts are
        handed off — enqueue-and-return, True = accepted for delivery —
        while ``startup``/``profile``-carrying beats keep the synchronous
        path: their one-shot retry protocol needs the server's actual
        verdict."""
        sink = self.async_sink
        if sink is not None and "startup" not in body \
                and "profile" not in body and "drainAck" not in body:
            return bool(sink(self._post_now, body))
        return self._post_now(body)

    def _post_now(self, body: Dict[str, Any]) -> bool:
        try:
            ack = self._poster(self.url, body)
            self._failed_once = False
            if "profile" in body:
                # The capture result one-shot is ACKed — stop resending.
                self._profile_result = None
            if "drainAck" in body:
                # The drain adoption one-shot is ACKed — stop resending.
                self._drain_ack = None
            if isinstance(ack, dict):
                directive = ack.get("profile")
                if isinstance(directive, dict) and directive.get("id") \
                        and str(directive["id"]) not in self._profile_seen:
                    if len(self._profile_seen) >= 64:
                        # Ids arrive one explicit tpujobctl call at a
                        # time; the cap is a leak backstop, not a policy.
                        self._profile_seen.clear()
                    self._profile_seen.add(str(directive["id"]))
                    self._profile_directive = dict(directive)
                drain = ack.get("drain")
                if isinstance(drain, dict) and drain.get("id") \
                        and str(drain["id"]) not in self._drain_seen:
                    if len(self._drain_seen) >= 64:
                        # One directive in flight at a time; leak backstop.
                        self._drain_seen.clear()
                    self._drain_seen.add(str(drain["id"]))
                    self._drain_directive = dict(drain)
            return True
        except Exception as e:  # noqa: BLE001 — heartbeats never kill training
            if not self._failed_once:
                log.warning("heartbeat post to %s failed: %s", self.url, e)
                self._failed_once = True
            return False

    def report_startup(self, stage: str) -> bool:
        """Post a pre-first-step liveness beat carrying only the in-flight
        ``startupStage`` (RENDEZVOUS/RESTORE/COMPILE/FIRST_STEP): the stall
        watchdog's baseline is the operator's receipt stamp, so these keep
        a long compile from reading as a hang. Deliberately does NOT touch
        the step-cadence bookkeeping (``_last_post``): the first real step
        report must fire immediately, and step-time averaging must not
        span the startup window. Startup liveness is process 0's job —
        cadence-only reporters no-op (the operator would discard the
        post)."""
        if self.cadence_only:
            return False
        return self._post({
            "namespace": self.namespace,
            "name": self.job_name,
            "processId": self.process_id,
            "attempt": self.attempt,
            "startupStage": str(stage),
        })

    def maybe_report(self, step: int,
                     metrics: Optional[Dict[str, Any]] = None,
                     checkpoint: Optional[Dict[str, Any]] = None,
                     serving: Optional[Dict[str, Any]] = None) -> bool:
        if not self.due(step):
            return False
        return self.report(step, metrics, checkpoint=checkpoint,
                           serving=serving)


def from_env(env: Optional[Dict[str, str]] = None,
             tokens_per_batch: int = 0) -> Optional[HeartbeatReporter]:
    """Reporter from the operator's env contract, or None when heartbeats
    are not wired (no TPUJOB_STATUS_URL). Process 0 posts the full
    telemetry stream (one per job, as before); every OTHER process posts
    ``cadence_only`` beats — identity + step cadence + the stepTiming
    phase digest — which the controller's straggler detector compares
    across the gang to find the replica pacing the collective. One small
    POST per process per interval, rate-limited inside the reporter."""
    e = env if env is not None else os.environ
    url = e.get("TPUJOB_STATUS_URL", "")
    job = e.get("TPUJOB_NAME", "")
    if not url or not job:
        return None

    # Best-effort contract: malformed env must not kill training.
    def _num(var: str, default, cast):
        try:
            return cast(e.get(var) or default)
        except ValueError:
            log.warning("ignoring malformed %s=%r", var, e.get(var))
            return default

    process_id = _num("JAX_PROCESS_ID", 0, int)
    if process_id != 0 and str(
            e.get("TPUJOB_STEPTRACE_ENABLED", "1")).lower() in ("0",
                                                                "false"):
        # Cadence beats exist FOR the straggler detector; with the flight
        # recorder explicitly disabled (spec.stepTrace.enabled: false)
        # the controller no-ops every one of them — a 64-process gang
        # would pay 63 discarded POSTs per interval for a feature the
        # user turned off. Process 0's stream is independent telemetry
        # and keeps flowing.
        return None
    return HeartbeatReporter(
        url, job,
        namespace=e.get("TPUJOB_NAMESPACE", "default"),
        process_id=process_id,
        attempt=_num("TPUJOB_ATTEMPT", 0, int),
        interval=_num("TPUJOB_HEARTBEAT_INTERVAL", DEFAULT_INTERVAL, float),
        tokens_per_batch=tokens_per_batch,
        cadence_only=process_id != 0,
    )
