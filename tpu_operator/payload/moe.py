"""Expert-parallel Mixture-of-Experts transformer LM payload.

``python -m tpu_operator.payload.moe`` — the expert-parallelism member of
the payload zoo (SURVEY.md §2 parallelism checklist: the reference expresses
no parallel strategy in-repo; here expert parallelism is a first-class
TPU-native payload running on the operator-bootstrapped process group).

Design — the GShard/Switch recipe, written the XLA way:

- **mesh = (data, expert)**: batch shards over ``data``; the expert weight
  stacks (leading dim E) shard over ``expert``.
- **Routing is dense algebra, not gather/scatter**: top-2 gating builds
  one-hot dispatch/combine tensors [G, n, E, C] and token movement is two
  einsums. Resharding expert inputs from (G sharded over data) to
  (E sharded over expert) is expressed purely as a sharding constraint —
  GSPMD inserts the all-to-all over ICI; no hand-written collective.
- **Static shapes**: capacity C = ceil(2n/E · capacity_factor) per group;
  overflow tokens drop (their combine weights zero — residual carries them),
  keeping every shape static under jit.
- **Load balancing**: Switch-style auxiliary loss E·Σ f_e·p̄_e, exported via
  flax ``sow`` and added to the LM loss with ``--aux-coef``.
- Numerics: house style — bf16 expert matmuls on the MXU, f32 router
  logits/softmax/aux, f32 master params.
"""

from __future__ import annotations

import argparse
import logging
import os
import math
from typing import Optional

from tpu_operator.payload import bootstrap
from tpu_operator.payload import optimizers

log = logging.getLogger(__name__)


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--batch", type=int, default=16, help="global batch size")
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--dim", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--kv-heads", type=int, default=0,
                   help="grouped-query attention K/V heads (0 = MHA, "
                        "1 = MQA); must divide --heads and, under "
                        "--tensor-parallel, the TP degree")
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--experts", type=int, default=4,
                   help="experts per MoE layer (mesh expert axis must divide it)")
    p.add_argument("--expert-parallel", type=int, default=1,
                   help="expert-parallel shards (mesh expert axis size)")
    p.add_argument("--tensor-parallel", type=int, default=1,
                   help="Megatron-style TP shards (mesh model axis) "
                        "*inside* each expert shard: expert FFNs w1/w2 "
                        "column/row-parallel over their hidden dim, "
                        "attention q/k/v/mlp dense layers sharded as in the "
                        "dense transformer — composes with "
                        "--expert-parallel on a 3-axis (data, expert, "
                        "model) mesh")
    p.add_argument("--capacity-factor", type=float, default=1.25)
    p.add_argument("--split-qkv", choices=("auto", "on", "off"),
                   default="auto",
                   help="separate q/k/v projections (auto: on under "
                        "--tensor-parallel so TP shards whole heads; the "
                        "explicit values pin the param-tree layout, e.g. "
                        "for parity tests or checkpoint compatibility)")
    p.add_argument("--aux-coef", type=float, default=1e-2,
                   help="load-balance auxiliary loss coefficient")
    p.add_argument("--router-z-coef", type=float, default=1e-3,
                   help="router z-loss coefficient (ST-MoE): penalizes "
                        "mean(logsumexp(router logits)^2) so logits stay "
                        "in the range where softmax gradients are alive")
    p.add_argument("--dispatch", choices=("einsum", "gather"),
                   default="einsum",
                   help="token→expert dispatch: einsum = GShard one-hot "
                        "matmuls — O(n²·cf·D) FLOPs but the MXU eats "
                        "them (measured ~26 ms/step at the bench shape, "
                        "identical total step time to the scatter "
                        "alternative); gather = scatter/gather through a "
                        "unique-slot buffer — O(n·D) traffic, but TPU "
                        "scatter lowering costs what the einsums cost, "
                        "so it is an option (and einsum-parity-tested), "
                        "not the default")
    p.add_argument("--dtype", choices=("bf16", "f32"), default="bf16")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="accumulate gradients over K sequential "
                        "microbatches inside the jit")
    from tpu_operator.payload import compute

    # --remat / --remat-policy / --optimizer from the shared surface
    # (payload/compute.py) — one flag set across the LM family.
    compute.add_lm_compute_flags(
        p, remat_help="rematerialize each block on backward "
                      "(jax.checkpoint)")
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=50)
    p.add_argument("--data", default=os.environ.get("TPU_DATA_PATH", ""),
                   help="mounted .npy token file (1-D int array): "
                        "memory-mapped real-data stream (data.token_file_lm)"
                        "; empty = synthetic recurrence")
    p.add_argument("--checkpoint-dir", default="",
                   help="checkpoint/resume dir (default: $TPU_CHECKPOINT_DIR)")
    p.add_argument("--checkpoint-every", type=int, default=100)
    p.add_argument("--profile-dir",
                   default=os.environ.get("TPU_PROFILE_DIR", ""),
                   help="jax.profiler trace dir (default: $TPU_PROFILE_DIR)")
    from tpu_operator.payload import autotune

    autotune.add_prefetch_argument(p)
    return p.parse_args(argv)


def make_moe_mesh(num_devices: Optional[int] = None, expert_parallel: int = 1,
                  devices: Optional[list] = None, num_slices: int = 1,
                  tensor_parallel: int = 1):
    """(data, expert) mesh: DP outer, expert-parallel inner — the dispatch
    all-to-all stays within each expert group's adjacent ICI links
    (multi-slice jobs keep every expert group within a slice).

    ``tensor_parallel > 1`` composes EP × TP on a 3-axis (data, expert,
    model) mesh reusing train.make_mesh3's layout and intra-slice guard: TP
    innermost (its psums fire per expert matmul — shortest ICI hops), the
    expert all-to-all around it, DP outermost / across DCN."""
    from tpu_operator.payload import train

    if tensor_parallel > 1:
        return train.make_mesh3(num_devices, seq_parallel=expert_parallel,
                                model_parallel=tensor_parallel,
                                devices=devices, num_slices=num_slices,
                                axis_names=("data", "expert", "model"))
    return train.make_mesh(num_devices, model_parallel=expert_parallel,
                           devices=devices, axis_names=("data", "expert"),
                           num_slices=num_slices)


def top2_routing(logits, capacity: int) -> dict:
    """Top-2 routing in index form — the one routing definition both
    dispatch implementations (one-hot einsum and scatter/gather) consume,
    so they cannot disagree on who goes where. Pure function of f32
    router logits; all shapes static.

    Position bookkeeping is cumsum algebra (no sort): token t's slot in
    expert e is the count of earlier tokens routed to e; slots ≥ C drop.
    Second choices fill after all first choices (Switch convention), so a
    hot expert drops 2nd-choice traffic before any 1st-choice traffic.

    Returns a dict of [G,n] index/gate arrays (``idx``/``slot``/``keep``/
    ``gate`` per choice), the [G,n,E] keep masks the einsum path needs,
    and three scalars:

    - ``aux`` — the Switch load-balance loss E·Σ_e(f_e·p_e); minimized at
      uniform routing, the term that trains drop_frac DOWN.
    - ``z_loss`` — mean(logsumexp(logits)²) (ST-MoE router z-loss, Zoph
      et al. 2022): keeps router logits from drifting to magnitudes where
      f32 softmax saturates and routing gradients vanish.
    - ``drop_frac`` — fraction of routed assignments (2 per token) past
      their expert's capacity; exported per step into training metrics
      (the observability contract tests/test_moe.py pins).
    """
    import jax
    import jax.numpy as jnp

    logits = logits.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                      # [G,n,E]
    num_experts = probs.shape[-1]

    idx1 = jnp.argmax(probs, axis=-1)                            # [G,n]
    mask1 = jax.nn.one_hot(idx1, num_experts, dtype=jnp.float32)
    probs2 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, num_experts, dtype=jnp.float32)

    # Switch aux loss over first choices: E · Σ_e (dispatch fraction × mean prob)
    f_e = mask1.mean(axis=1)                                     # [G,E]
    p_e = probs.mean(axis=1)                                     # [G,E]
    aux = num_experts * jnp.mean(jnp.sum(f_e * p_e, axis=-1))
    z = jax.scipy.special.logsumexp(logits, axis=-1)             # [G,n]
    z_loss = jnp.mean(z * z)

    pos1 = jnp.cumsum(mask1, axis=1) * mask1 - mask1             # slot of each 1st choice
    count1 = mask1.sum(axis=1, keepdims=True)                    # [G,1,E]
    pos2 = (jnp.cumsum(mask2, axis=1) * mask2 - mask2) + count1  # 2nd fills after 1st
    keep1 = mask1 * (pos1 < capacity)
    keep2 = mask2 * (pos2 < capacity)
    # Routed assignments that fell past capacity (comparisons carry no
    # gradient — this is a pure observability scalar).
    n_tokens = logits.shape[0] * logits.shape[1]
    drop_frac = 1.0 - (jnp.sum(keep1) + jnp.sum(keep2)) / (2.0 * n_tokens)

    gate1 = jnp.sum(probs * keep1, axis=-1)                      # [G,n]
    gate2 = jnp.sum(probs * keep2, axis=-1)
    denom = jnp.maximum(gate1 + gate2, 1e-9)
    gate1, gate2 = gate1 / denom, gate2 / denom

    return {
        "idx1": idx1, "idx2": idx2,
        "slot1": jnp.sum(pos1, axis=-1).astype(jnp.int32),       # [G,n]
        "slot2": jnp.sum(pos2 * mask2, axis=-1).astype(jnp.int32),
        "keep1": keep1, "keep2": keep2,                          # [G,n,E]
        "kept1": jnp.sum(keep1, axis=-1),                        # [G,n] 0/1
        "kept2": jnp.sum(keep2, axis=-1),
        "pos1": pos1, "pos2": pos2,
        "gate1": gate1, "gate2": gate2,
        "aux": aux, "z_loss": z_loss, "drop_frac": drop_frac,
    }


def _onehot_tensors(r: dict, capacity: int):
    """Routing dict → (dispatch [G,n,E,C], combine [G,n,E,C], aux,
    drop_frac) one-hot tensors for the einsum dispatch path."""
    import jax
    import jax.numpy as jnp

    def slots(keep, pos):
        # [G,n,E] × slot index → one-hot over capacity: [G,n,E,C]
        return keep[..., None] * jax.nn.one_hot(
            (pos * keep).astype(jnp.int32), capacity, dtype=jnp.float32)

    s1, s2 = slots(r["keep1"], r["pos1"]), slots(r["keep2"], r["pos2"])
    dispatch = s1 + s2
    combine = (r["gate1"][:, :, None, None] * s1
               + r["gate2"][:, :, None, None] * s2)
    return dispatch, combine, r["aux"], r["drop_frac"]


def top2_dispatch(logits, capacity: int):
    """One-hot form of :func:`top2_routing`: (dispatch [G,n,E,C] bool-ish,
    combine [G,n,E,C] f32, aux, drop_frac). The dispatch/combine einsums
    this feeds cost 2·G·n²·cf·D FLOPs each — quadratic in tokens-per-group
    — which is why the scatter/gather path exists (see MoEMLP)."""
    return _onehot_tensors(top2_routing(logits, capacity), capacity)


def _moe_mlp_class(mesh, dtype):
    """Builds the MoEMLP flax module class, closed over the mesh (for the
    all-to-all sharding constraints) and compute dtype. Module-level factory
    so jax imports stay lazy (house convention)."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    class MoEMLP(nn.Module):
            """Expert-parallel FFN: route → all-to-all → expert matmuls →
            all-to-all back. Token groups G = batch rows (already
            data-sharded), so routing math is group-local.

            ``dispatch_mode`` selects how tokens reach their expert slots:

            - ``einsum`` — GShard one-hot [G,n,E,C] matmuls. MXU-friendly
              but 2·G·n²·cf·D FLOPs per direction, *quadratic* in
              tokens-per-group: at the bench shape it nearly doubles the
              layer's FLOPs over the experts' useful math and is the
              active-MFU tax the round-3 suite measured.
            - ``gather`` — scatter-add tokens into a [G, E·C + 2n, D]
              slot buffer (each kept assignment owns a unique slot by
              construction; dropped assignments land in a private dump
              row, so indices are provably unique) and gather expert
              outputs back per token. O(n·D) memory traffic instead of
              the quadratic matmul; differentiable (scatter-add's VJP is
              the gather, and vice versa). Routing indices come from the
              same :func:`top2_routing` as the einsum path, so the two
              modes agree exactly (tests pin this).
            """

            dim: int
            experts: int
            capacity_factor: float
            dispatch_mode: str = "einsum"

            @nn.compact
            def __call__(self, x):
                g, n, d = x.shape
                e = self.experts
                capacity = max(4, int(math.ceil(
                    2 * n * self.capacity_factor / e)))
                hidden = 4 * self.dim

                router = nn.Dense(e, use_bias=False, dtype=jnp.float32,
                                  name="router")
                # batch_axis=0: the expert dim must not count into fan_in,
                # or per-expert init std shrinks by sqrt(E) vs dense blocks.
                init = nn.initializers.lecun_normal(batch_axis=0)
                w1 = self.param("w1", init, (e, d, hidden), jnp.float32)
                w2 = self.param("w2", init, (e, hidden, d), jnp.float32)

                r = top2_routing(router(x), capacity)
                self.sow("intermediates", "aux_loss", r["aux"])
                self.sow("intermediates", "drop_frac", r["drop_frac"])
                self.sow("intermediates", "router_z", r["z_loss"])
                xd = x.astype(dtype)

                if self.dispatch_mode == "gather":
                    rows = e * capacity + 2 * n
                    tok = jnp.arange(n, dtype=jnp.int32)[None, :]
                    f1 = jnp.where(r["kept1"] > 0,
                                   r["idx1"] * capacity + r["slot1"],
                                   e * capacity + tok)
                    f2 = jnp.where(r["kept2"] > 0,
                                   r["idx2"] * capacity + r["slot2"],
                                   e * capacity + n + tok)
                    garange = jnp.arange(g)[:, None]
                    buf = jnp.zeros((g, rows, d), dtype)
                    buf = buf.at[garange,
                                 jnp.concatenate([f1, f2], axis=1)].add(
                        jnp.concatenate([xd, xd], axis=1),
                        unique_indices=True)
                    expert_in = jnp.swapaxes(
                        buf[:, :e * capacity].reshape(g, e, capacity, d),
                        0, 1)                                  # [E,G,C,D]
                else:
                    dispatch, combine, _aux, _drop = _onehot_tensors(
                        r, capacity)
                    # [G,n,E,C] × [G,n,D] → [E,G,C,D]
                    expert_in = jnp.einsum("gnec,gnd->egcd",
                                           dispatch.astype(dtype), xd)

                # The constraint flips the sharded dim from G (data) to E
                # (expert): GSPMD emits the all-to-all.
                expert_in = jax.lax.with_sharding_constraint(
                    expert_in, NamedSharding(mesh, P("expert", "data")))
                h = jnp.einsum("egcd,edf->egcf", expert_in,
                               w1.astype(dtype))
                if "model" in mesh.shape and mesh.shape["model"] > 1:
                    # EP × TP: inside each expert shard the hidden dim is
                    # column-parallel over ``model`` (w1 P(E,·,model)); pin
                    # it so gelu runs sharded and only w2's row-parallel
                    # product gets the one psum per layer.
                    h = jax.lax.with_sharding_constraint(
                        h, NamedSharding(mesh,
                                         P("expert", "data", None, "model")))
                h = nn.gelu(h)
                expert_out = jnp.einsum("egcf,efd->egcd", h, w2.astype(dtype))
                expert_out = jax.lax.with_sharding_constraint(
                    expert_out, NamedSharding(mesh, P("expert", "data")))

                if self.dispatch_mode == "gather":
                    out_flat = jnp.swapaxes(expert_out, 0, 1).reshape(
                        g, e * capacity, d)
                    out_full = jnp.concatenate(
                        [out_flat, jnp.zeros((g, 2 * n, d), dtype)], axis=1)
                    y1 = jnp.take_along_axis(out_full, f1[..., None], axis=1)
                    y2 = jnp.take_along_axis(out_full, f2[..., None], axis=1)
                    return (r["gate1"][..., None].astype(dtype) * y1
                            + r["gate2"][..., None].astype(dtype) * y2)
                # back to token layout: [G,n,E,C] × [E,G,C,D] → [G,n,D]
                return jnp.einsum("gnec,egcd->gnd",
                                  combine.astype(dtype), expert_out)

    return MoEMLP


def _build_model(args, mesh):
    import flax.linen as nn
    import jax.numpy as jnp

    from tpu_operator.payload import flash_attention as fa
    from tpu_operator.payload import models
    from tpu_operator.payload import ring_attention as ring

    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    if args.experts % mesh.shape["expert"] != 0:
        raise ValueError(
            f"--experts {args.experts} not divisible by the mesh expert "
            f"axis ({mesh.shape['expert']})")
    kv_heads = getattr(args, "kv_heads", 0)
    tp = mesh.shape.get("model", 1)
    models.validate_heads_dims(args.heads, kv_heads, args.dim, tp)

    def attend(q, k, v):
        if dtype == jnp.bfloat16 and fa.use_pallas_default():
            return fa.flash_attention(q, k, v, causal=True)
        return ring.reference_attention(q, k, v, causal=True)

    MoEMLP = _moe_mlp_class(mesh, dtype)
    from tpu_operator.payload import compute

    Block = compute.lm_block(args)
    # Under TP, split q/k/v so each model shard owns whole heads
    # (transformer.py's rule — a fused [d,3d] kernel's contiguous column
    # shards would straddle the q/k/v thirds).
    split_qkv = models.resolve_split_qkv(getattr(args, "split_qkv", "auto"),
                                         tp, log)

    def moe_mlp(name):
        return MoEMLP(dim=args.dim, experts=args.experts,
                      capacity_factor=args.capacity_factor,
                      dispatch_mode=getattr(args, "dispatch", "einsum"),
                      name=name)

    class MoELM(nn.Module):
        vocab: int
        dim: int
        heads: int
        layers: int
        max_seq: int

        @nn.compact
        def __call__(self, tokens, train: bool = True):
            _b, t = tokens.shape
            x = nn.Embed(self.vocab, self.dim, dtype=dtype,
                         name="tok_embed")(tokens)
            pos = nn.Embed(self.max_seq, self.dim, dtype=dtype,
                           name="pos_embed")(jnp.arange(t))
            x = x + pos[None]
            for i in range(self.layers):
                # Every other block is MoE (GShard convention): dense blocks
                # keep a gradient path for every token even when hot experts
                # overflow capacity.
                mlp = moe_mlp if i % 2 == 1 else None
                x = Block(self.dim, self.heads, attend,
                          dtype=dtype, mlp=mlp, split_qkv=split_qkv,
                          kv_heads=kv_heads,
                          name=f"block{i}")(x)
            x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
            return nn.Dense(self.vocab, use_bias=False, dtype=dtype,
                            name="lm_head")(x)

    return MoELM(vocab=args.vocab, dim=args.dim, heads=args.heads,
                 layers=args.layers, max_seq=args.seq_len)


def state_shardings(mesh, state):
    """Expert weight stacks (w1/w2 under a ``moe`` path, and their
    params-shaped adam moments) shard their leading E dim over ``expert``;
    everything else replicates.

    On an EP × TP mesh (``model`` axis present) the expert FFNs
    additionally shard their hidden dim over ``model`` — w1 [E, D, 4D]
    column-parallel, w2 [E, 4D, D] row-parallel, the Megatron pairing whose
    products GSPMD psums once per layer — and the dense attention/MLP
    kernels follow transformer.py's TP rule (split q/k/v column-parallel,
    attn_out/mlp_down row-parallel, lm_head over vocab). Routers stay
    replicated: routing is per-token f32 math every shard needs."""
    from jax.sharding import PartitionSpec as P

    from tpu_operator.payload import train

    tp = "model" in mesh.shape and mesh.shape["model"] > 1
    col = ("q", "k", "v", "qkv", "mlp_up", "lm_head")
    row = ("attn_out", "mlp_down")

    def rule(keys, leaf):
        if "moe" in keys and keys[-1] in ("w1", "w2") \
                and getattr(leaf, "ndim", 0) >= 1:
            if tp and getattr(leaf, "ndim", 0) == 3:
                return (P("expert", None, "model") if keys[-1] == "w1"
                        else P("expert", "model", None))
            return P("expert", *(None,) * (leaf.ndim - 1))
        if tp and keys and keys[-1] == "kernel" \
                and getattr(leaf, "ndim", 0) == 2 and "router" not in keys:
            if any(k in col for k in keys):
                return P(None, "model")
            if any(k in row for k in keys):
                return P("model", None)
        return P()

    return train.shardings_from_rule(mesh, state, rule)


def make_moe_train_step(args, model, mesh, state, tx, shardings=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpu_operator.payload import train

    def _mean_sown(inter, name):
        leaves = [leaf for path, leaf in
                  jax.tree_util.tree_flatten_with_path(
                      inter.get("intermediates", {}))[0]
                  if any(getattr(p, "key", str(p)) == name for p in path)]
        return (sum(leaves) / len(leaves)) if leaves else jnp.float32(0.0)

    def loss_fn(params, tokens):
        logits, inter = model.apply({"params": params}, tokens,
                                    mutable=["intermediates"])
        aux = _mean_sown(inter, "aux_loss")
        router_z = _mean_sown(inter, "router_z")
        drop = jax.lax.stop_gradient(_mean_sown(inter, "drop_frac"))
        lm_loss = train.next_token_nll(logits, tokens)
        total = (lm_loss + args.aux_coef * aux
                 + getattr(args, "router_z_coef", 0.0) * router_z)
        return total, {"loss": lm_loss, "aux_loss": aux,
                       "router_z": router_z, "drop_frac": drop,
                       "total_loss": total}

    return train.make_loss_train_step(
        loss_fn, tx, mesh, state, shardings or state_shardings(mesh, state),
        batch_spec=P("data", None),
        grad_accum=getattr(args, "grad_accum", 1))


def build(args, mesh=None, num_slices: int = 1):
    """(mesh, model, state, train_step, batches) for the given config."""
    import jax
    import jax.numpy as jnp

    from tpu_operator.payload import data as data_mod
    from tpu_operator.payload import train

    mesh = mesh or make_moe_mesh(
        expert_parallel=args.expert_parallel, num_slices=num_slices,
        tensor_parallel=getattr(args, "tensor_parallel", 1))
    model = _build_model(args, mesh)
    tx = optimizers.from_args(args)
    sample = jnp.zeros((args.batch, args.seq_len), jnp.int32)
    state = train.create_train_state(model, jax.random.key(args.seed), sample, tx)
    shardings = state_shardings(mesh, state)
    state = train.place_state(mesh, state, shardings)
    step = make_moe_train_step(args, model, mesh, state, tx, shardings)
    from jax.sharding import PartitionSpec as P

    batches = data_mod.lm_batches(args, mesh=mesh, spec=P("data", None))
    return mesh, model, state, step, batches


def run(info: bootstrap.ProcessInfo, args=None) -> dict:
    from tpu_operator.payload import autotune, checkpoint, train

    args = args or parse_args([])
    mesh, _model, state, step, batches = build(
        args, num_slices=info.num_slices)
    log.info("mesh: %s over %d devices; %d experts, capacity factor %.2f",
             dict(zip(mesh.axis_names, mesh.devices.shape)),
             mesh.devices.size, args.experts, args.capacity_factor)
    ckpt = checkpoint.from_env_or_args(args.checkpoint_dir,
                                       save_every=args.checkpoint_every)
    if ckpt is not None and ckpt.latest_step() is not None:
        log.info("attempt %d: resuming from %s (latest step: %d)",
                 info.attempt, ckpt.directory, ckpt.latest_step())
    try:
        state, metrics = train.train_loop(
            mesh, step, state, batches, args.steps,
            log_every=args.log_every,
            log_fn=lambda i, m: log.info(
                "step %d loss %.4f aux %.4f drop %.3f", i, m["loss"],
                m["aux_loss"], m["drop_frac"]),
            checkpointer=ckpt,
            profile_dir=args.profile_dir,
            prefetch=autotune.resolve_prefetch_depth(args.prefetch_depth),
        )
    finally:
        if ckpt is not None:
            ckpt.close()
    log.info("final: loss %.4f", metrics.get("loss", float("nan")))
    return metrics


def main() -> None:
    args = parse_args()
    bootstrap.main_wrapper(lambda info: run(info, args))


if __name__ == "__main__":
    main()
