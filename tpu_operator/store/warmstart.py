"""The job-scoped warm-start store: checkpoints + compilation cache over a
blob backend.

Remote layout under one job prefix (``<namespace>/<job>`` by default):

    checkpoints/<step>/manifest.json + data/...   — one chunked snapshot
                                                    per durable step
    checkpoints/<step>.corrupt                    — quarantine marker
    cache/<entry>                                 — persistent XLA
                                                    compilation cache
                                                    entries, one object
                                                    per cache file

Design decisions:

- **No central index object.** A read-modify-write index file would race
  across uploader attempts and the quarantine path; instead presence =
  the snapshot's committed manifest, and corruption = a marker object
  written FIRST (before the manifest is deleted), so there is no window
  in which a condemned step looks healthy to a fresh-node prefetch.
- **Quarantine parity with the local walk.** When PR 4's restore walk
  quarantines ``<step>`` locally (``<step>.corrupt-N``), the checkpointer
  tells this store to :meth:`mark_corrupt` the remote copy — a fresh node
  must never re-download a step an earlier attempt already proved bad.
  Prefetch ALSO skips steps the local directory has quarantined, covering
  the window before the async mark lands.
- **Integrity fallback.** A snapshot whose chunks fail verification after
  the one retry is marked corrupt and the prefetch falls back to the
  next-oldest step — the newest→oldest discipline of the local restore
  walk, applied to the remote side.
- **Cache entries are immutable.** XLA names persistent-cache files by
  content hash, so sync is pure set-difference: upload what the remote
  lacks, download what the local dir lacks. No versioning, no manifest.
"""

from __future__ import annotations

import logging
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from tpu_operator.store import transfer
from tpu_operator.store.blob import BlobBackend, BlobError, BlobNotFound

log = logging.getLogger(__name__)

CHECKPOINT_PREFIX = "checkpoints"
CACHE_PREFIX = "cache"
ARTIFACT_PREFIX = "artifacts"
CORRUPT_SUFFIX = ".corrupt"

# Local quarantine directory names the PR 4 restore walk writes
# (checkpoint.QUARANTINE_SUFFIX): "<step>.corrupt-<n>".
_LOCAL_QUARANTINE_RE = re.compile(r"^(\d+)\.corrupt(-\d+)?$")


class WarmStartStore:
    """Checkpoint + compilation-cache persistence for ONE job."""

    def __init__(self, backend: BlobBackend, prefix: str = "",
                 upload_parallelism: int = transfer.DEFAULT_PARALLELISM,
                 chunk_size: int = transfer.DEFAULT_CHUNK_SIZE):
        self.backend = backend
        self.prefix = prefix.strip("/")
        self.upload_parallelism = max(1, int(upload_parallelism))
        self.chunk_size = max(1, int(chunk_size))

    def _key(self, *parts: str) -> str:
        bits = [self.prefix] if self.prefix else []
        bits.extend(parts)
        return "/".join(bits)

    def _step_prefix(self, step: int) -> str:
        return self._key(CHECKPOINT_PREFIX, str(int(step)))

    # -- checkpoints: write side ----------------------------------------------

    def upload_checkpoint(self, local_step_dir: str, step: int) -> None:
        """Ship one verified local step directory as a remote snapshot
        (chunks first, manifest last = commit). Raises BlobError flavors
        on failure — the write-behind uploader owns counting/escalation.

        A fresh upload CLEARS any ``.corrupt`` marker for the step: the
        marker condemned the OLD bytes; a job that quarantined step N,
        resumed from N-k, replayed, and re-saved a newly VERIFIED step N
        must not have that step invisible to prefetch forever (it would
        replay the same k steps after every preemption while heartbeats
        advertise N as remotely durable). Cleared only AFTER the new
        manifest commits, so there is no window in which the old bad
        snapshot looks healthy."""
        step = int(step)
        transfer.upload_tree(
            self.backend, local_step_dir, self._step_prefix(step),
            parallelism=self.upload_parallelism,
            chunk_size=self.chunk_size,
            meta={"step": step})
        self.backend.delete(self._key(CHECKPOINT_PREFIX,
                                      f"{step}{CORRUPT_SUFFIX}"))

    def retain(self, keep: int) -> int:
        """Retention GC: condemn-then-delete verified snapshots beyond the
        newest ``keep`` (0/negative = keep everything). Returns steps
        removed.

        Ordering is the PR-8 marker-first discipline: the ``.corrupt``
        marker lands BEFORE any chunk of the victim is deleted, so there
        is no window in which a half-deleted snapshot looks committed to
        a fresh-node prefetch or the serve-mode hot-reload watcher. Once
        the tree (manifest included) is gone the marker itself is removed
        — a GC'd step is *absence*, not quarantine: leaving the marker
        would grow an unbounded marker tree, the very thing this GC
        exists to prevent. A crash between tree-delete and marker-delete
        leaves a stray marker over nothing, which the next retain() pass
        ignores (the step is no longer in checkpoint_steps)."""
        if keep < 1:
            return 0
        steps = self.checkpoint_steps()
        victims = steps[:-keep] if len(steps) > keep else []
        removed = 0
        for step in victims:
            marker = self._key(CHECKPOINT_PREFIX, f"{step}{CORRUPT_SUFFIX}")
            self.backend.put(marker, b"retention gc")
            transfer.delete_tree(self.backend, self._step_prefix(step))
            self.backend.delete(marker)
            removed += 1
            log.info("remote store: retention GC removed snapshot step %d "
                     "(keeping newest %d)", step, keep)
        return removed

    def mark_corrupt(self, step: int, reason: str = "") -> None:
        """Condemn a remote step: marker first (no healthy-looking
        window), then the snapshot itself. Idempotent and best-effort on
        the chunk sweep; the marker is the load-bearing part."""
        step = int(step)
        self.backend.put(self._key(CHECKPOINT_PREFIX,
                                   f"{step}{CORRUPT_SUFFIX}"),
                         (reason or "quarantined").encode())
        transfer.delete_tree(self.backend, self._step_prefix(step))
        log.warning("remote store: marked checkpoint step %d corrupt (%s)",
                    step, reason or "local quarantine")

    # -- artifacts (postmortem step traces etc.) ------------------------------

    def upload_artifact(self, local_path: str, name: str) -> None:
        """Ship one small file under the job's ``artifacts/`` prefix as a
        single object (postmortem step-trace dumps are a few hundred KB —
        no chunking needed; the backend's put is atomic per object).
        Raises BlobError flavors / OSError on failure — the write-behind
        worker owns the best-effort handling."""
        with open(local_path, "rb") as f:
            data = f.read()
        self.backend.put(self._key(ARTIFACT_PREFIX, name), data)

    def list_artifacts(self) -> List[str]:
        """Names of uploaded artifacts (postmortem discovery)."""
        base = self._key(ARTIFACT_PREFIX) + "/"
        return sorted(key[len(base):] for key in self.backend.list(base))

    # -- checkpoints: read side -----------------------------------------------

    def checkpoint_steps(self) -> List[int]:
        """Committed, non-condemned remote steps, ascending."""
        base = self._key(CHECKPOINT_PREFIX) + "/"
        steps, corrupt = set(), set()
        for key in self.backend.list(base):
            rest = key[len(base):]
            head = rest.split("/", 1)[0]
            if head.endswith(CORRUPT_SUFFIX):
                stem = head[:-len(CORRUPT_SUFFIX)]
                if stem.isdigit():
                    corrupt.add(int(stem))
                continue
            if head.isdigit() and rest == f"{head}/{transfer.MANIFEST_KEY}":
                steps.add(int(head))
        return sorted(steps - corrupt)

    def last_uploaded_step(self) -> Optional[int]:
        steps = self.checkpoint_steps()
        return steps[-1] if steps else None

    @staticmethod
    def _locally_quarantined(local_dir: str) -> set:
        """Steps the LOCAL restore walk already condemned: the remote copy
        of those must never be preferred, even before the async
        mark_corrupt lands (or when it failed)."""
        out = set()
        try:
            for name in os.listdir(local_dir):
                m = _LOCAL_QUARANTINE_RE.match(name)
                if m:
                    out.add(int(m.group(1)))
        except OSError:
            pass
        return out

    def prefetch_checkpoint(self, local_dir: str
                            ) -> Tuple[Optional[int], int]:
        """Materialize the newest healthy remote step into ``local_dir``
        (the local verified-restore walk then finds it like any other
        on-disk checkpoint). Returns ``(step, fallbacks)`` — step None
        when nothing usable exists remotely.

        Walks newest→oldest: a snapshot whose chunks fail verification
        after the per-chunk retry is marked corrupt remotely and the walk
        continues to the next-oldest (counted in ``fallbacks``)."""
        os.makedirs(local_dir, exist_ok=True)
        condemned = self._locally_quarantined(local_dir)
        fallbacks = 0
        for step in reversed(self.checkpoint_steps()):
            if step in condemned:
                log.warning(
                    "prefetch: skipping remote step %d (locally "
                    "quarantined); marking it corrupt remotely", step)
                try:
                    self.mark_corrupt(step, "locally quarantined")
                except BlobError as e:
                    log.warning("prefetch: remote corrupt-mark of step %d "
                                "failed: %s", step, e)
                continue
            target = os.path.join(local_dir, str(step))
            if os.path.isdir(target):
                # Already materialized (a peer process on a shared dir, or
                # the attempt's own training history): nothing to fetch —
                # the verified-restore walk will judge it as usual.
                return step, fallbacks
            # Stage under a NON-NUMERIC name and rename the COMPLETE dir
            # into place: orbax's step scan (and PR 4's verified walk)
            # must never observe a half-materialized step directory — a
            # prefetch outliving its bounded join races the restore walk,
            # and a torn step dir seen there would be quarantined locally
            # AND condemned remotely, destroying a healthy snapshot.
            staging = f"{target}.prefetch.{os.getpid()}"
            try:
                transfer.download_tree(
                    self.backend, self._step_prefix(step), staging,
                    parallelism=self.upload_parallelism)
                try:
                    os.rename(staging, target)
                except OSError:
                    # A peer renamed its complete copy first: same bytes.
                    self._scrub_partial(staging)
                return step, fallbacks
            except BlobNotFound:
                self._scrub_partial(staging)
                continue  # raced a concurrent mark/GC; older step next
            except transfer.IntegrityError as e:
                fallbacks += 1
                log.error("prefetch: remote step %d failed verification "
                          "(%s); falling back to next-oldest", step, e)
                try:
                    self.mark_corrupt(step, f"prefetch verification: {e}")
                except BlobError as e2:
                    log.warning("prefetch: corrupt-mark of step %d failed: "
                                "%s", step, e2)
                self._scrub_partial(staging)
            except BlobError:
                # Transient backend failure mid-download (network blip,
                # mount hiccup): scrub the staging dir and let the caller
                # proceed cold — it says nothing about the snapshot, so
                # no condemnation and no further walking.
                self._scrub_partial(staging)
                raise
        return None, fallbacks

    @staticmethod
    def _scrub_partial(target: str) -> None:
        """Remove a partially-materialized step dir so the local verified
        walk never sees a torn, manifest-less directory as a candidate."""
        import shutil

        shutil.rmtree(target, ignore_errors=True)

    # -- compilation cache ----------------------------------------------------

    def upload_cache(self, cache_dir: str) -> int:
        """Sync new local cache entries up; returns files uploaded.
        Entries are content-named by XLA, so exists == identical."""
        if not cache_dir or not os.path.isdir(cache_dir):
            return 0
        try:
            remote = set(self.backend.list(self._key(CACHE_PREFIX) + "/"))
        except BlobError as e:
            log.warning("cache upload: listing remote failed: %s", e)
            return 0
        uploaded = 0
        for relpath in transfer.iter_local_files(cache_dir):
            key = self._key(CACHE_PREFIX, relpath)
            if key in remote:
                continue
            path = os.path.join(cache_dir, *relpath.split("/"))
            try:
                with open(path, "rb") as f:
                    self.backend.put(key, f.read())
                uploaded += 1
            except (OSError, BlobError) as e:
                log.warning("cache upload of %s failed: %s", relpath, e)
        return uploaded

    def prefetch_cache(self, cache_dir: str) -> int:
        """Sync missing cache entries down; returns files downloaded.
        Strictly best-effort: a failed entry degrades that compile to
        cold, never the attempt."""
        if not cache_dir:
            return 0
        os.makedirs(cache_dir, exist_ok=True)
        base = self._key(CACHE_PREFIX) + "/"
        try:
            remote = self.backend.list(base)
        except BlobError as e:
            log.warning("cache prefetch: listing remote failed: %s", e)
            return 0
        downloaded = 0
        for key in remote:
            relpath = key[len(base):]
            if not relpath or relpath.startswith("/") \
                    or ".." in relpath.split("/"):
                continue
            target = os.path.join(cache_dir, *relpath.split("/"))
            if os.path.exists(target):
                continue
            try:
                data = self.backend.get(key)
            except BlobError as e:
                log.warning("cache prefetch of %s failed: %s", relpath, e)
                continue
            tmp = f"{target}.{os.getpid()}.tmp"
            try:
                os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, target)
                downloaded += 1
            except OSError as e:
                log.warning("cache prefetch write of %s failed: %s",
                            relpath, e)
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        return downloaded

    # -- introspection --------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        steps = self.checkpoint_steps()
        return {
            "prefix": self.prefix,
            "backend": type(self.backend).__name__,
            "checkpointSteps": steps,
            "cacheEntries": len(
                self.backend.list(self._key(CACHE_PREFIX) + "/")),
        }
