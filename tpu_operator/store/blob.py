"""The blob-backend contract: a minimal object-store-shaped API.

Everything above this layer (chunked transfer, the warm-start store)
speaks only these five verbs over flat string keys:

    put(key, data)   — whole-object write, atomic per object
    get(key)         — whole-object read (BlobNotFound when absent)
    delete(key)      — idempotent remove
    list(prefix)     — keys under a prefix, sorted
    exists(key)      — cheap presence probe

That is deliberately the intersection of GCS/S3/ABS object semantics: no
append, no rename, no partial read — so a cloud backend is a thin SDK
wrapper with nothing clever in it. Two backends ship in-repo:

- :class:`LocalFSBackend` — keys are files under a root directory (any
  shared filesystem mount: NFS, Filestore, a gcsfuse mount). Writes are
  tmp-file + ``os.replace``, so an object is either absent or complete —
  the atomicity the transfer layer's resume logic relies on.
- :class:`FakeBackend` — in-process dict with injectable per-op latency
  and fault hooks, for tests and the write-behind bench guard.

Cloud schemes are *gated*: ``from_uri("gs://...")`` raises a clear error
naming :func:`register_backend` instead of importing an SDK this image
does not ship (the container constraint: stub or gate missing deps).
"""

from __future__ import annotations

import os
import time
import urllib.parse
from typing import Callable, Dict, List, Optional
from tpu_operator.util import lockdep

# Longest key accepted (object stores cap around 1024; ours are short).
_MAX_KEY = 512


class BlobError(Exception):
    """A blob-backend operation failed."""


class BlobNotFound(BlobError):
    """The requested key does not exist."""


def _check_key(key: str) -> str:
    """Keys are '/'-separated relative paths: no empties, no absolute
    paths, no traversal — a malicious or buggy key must not be able to
    escape a filesystem-backed root."""
    if not key or len(key) > _MAX_KEY:
        raise BlobError(f"invalid blob key {key!r}")
    parts = key.split("/")
    if any(p in ("", ".", "..") for p in parts):
        raise BlobError(f"invalid blob key {key!r} (empty/dot segment)")
    return key


class BlobBackend:
    """Abstract backend. Subclasses implement the five verbs; all are
    expected to be thread-safe (the transfer layer fans calls across a
    pool)."""

    scheme = "abstract"

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def list(self, prefix: str = "") -> List[str]:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        try:
            self.get(key)
            return True
        except BlobNotFound:
            return False


class LocalFSBackend(BlobBackend):
    """Objects as files under a root directory (shared-filesystem remote).

    Atomicity: put writes ``<path>.<pid>.tmp`` then ``os.replace``s it, so
    concurrent writers of the same key last-win with complete bytes and a
    reader never observes a torn object. ``*.tmp`` files are invisible to
    list/exists/get."""

    scheme = "file"

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, *_check_key(key).split("/"))

    def put(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as e:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise BlobError(f"put {key!r}: {e}") from e

    def get(self, key: str) -> bytes:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise BlobNotFound(key) from None
        except OSError as e:
            raise BlobError(f"get {key!r}: {e}") from e

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass
        except OSError as e:
            raise BlobError(f"delete {key!r}: {e}") from e

    def list(self, prefix: str = "") -> List[str]:
        # Descend only the subtree the prefix pins: on a shared mount
        # holding MANY jobs' stores, walking the whole root per list
        # (the write-behind worker lists after every verified save) would
        # cost O(all objects of all jobs) in getdents round-trips. The
        # last '/'-segment may be a partial key component, so the walk
        # starts at its parent and the exact-prefix filter finishes the
        # job.
        comps = [c for c in prefix.split("/") if c]
        if comps and not prefix.endswith("/"):
            comps = comps[:-1]
        base = os.path.join(self.root, *comps) if comps else self.root
        out: List[str] = []
        for dirpath, _dirs, files in os.walk(base):
            for fn in files:
                if fn.endswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), self.root)
                key = rel.replace(os.sep, "/")
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    def exists(self, key: str) -> bool:
        return os.path.isfile(self._path(key))


class FakeBackend(BlobBackend):
    """In-process backend for tests and benches: a dict plus the two knobs
    real object stores hurt with — per-op latency and injected faults.

    ``latency`` sleeps (off-lock) on every op, standing in for a network
    round trip; the write-behind bench guard uses it to prove uploads
    never ride the step loop. ``fault_hook(op, key)`` may raise to inject
    failures (torn uploads, flaky reads); ``corrupt_once(key)`` arms a
    one-shot bit-flip on the next get of ``key`` — the transient-corruption
    case the chunk retry exists for. ``op_counts`` records traffic so
    tests can assert resume actually skipped re-uploads."""

    scheme = "fake"

    def __init__(self, latency: float = 0.0,
                 fault_hook: Optional[Callable[[str, str], None]] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.latency = latency
        self.fault_hook = fault_hook
        self._sleep = sleep
        self._lock = lockdep.lock("FakeBackend._lock")
        self._objects: Dict[str, bytes] = {}  # guarded-by: _lock
        self._corrupt_once: set = set()  # guarded-by: _lock
        self.op_counts: Dict[str, int] = {}  # guarded-by: _lock

    def _op(self, op: str, key: str) -> None:
        with self._lock:
            self.op_counts[op] = self.op_counts.get(op, 0) + 1
        if self.latency > 0:
            self._sleep(self.latency)
        if self.fault_hook is not None:
            self.fault_hook(op, key)

    def corrupt_once(self, key: str) -> None:
        with self._lock:
            self._corrupt_once.add(key)

    def corrupt(self, key: str, data: bytes = b"\xde\xad\xbe\xef") -> None:
        """Permanently replace a stored object's bytes (keeps the key)."""
        with self._lock:
            if key in self._objects:
                self._objects[key] = data

    def put(self, key: str, data: bytes) -> None:
        _check_key(key)
        self._op("put", key)
        with self._lock:
            self._objects[key] = bytes(data)

    def get(self, key: str) -> bytes:
        self._op("get", key)
        with self._lock:
            if key not in self._objects:
                raise BlobNotFound(key)
            data = self._objects[key]
            if key in self._corrupt_once:
                self._corrupt_once.discard(key)
                return b"\x00" * len(data) if data else b"\x00"
            return data

    def delete(self, key: str) -> None:
        self._op("delete", key)
        with self._lock:
            self._objects.pop(key, None)

    def list(self, prefix: str = "") -> List[str]:
        self._op("list", prefix)
        with self._lock:
            return sorted(k for k in self._objects if k.startswith(prefix))

    def exists(self, key: str) -> bool:
        self._op("exists", key)
        with self._lock:
            return key in self._objects


# --- URI resolution ----------------------------------------------------------

# Named in-process fake backends: fake://<name> resolves to one shared
# instance per name, so a payload and the test driving it can see the same
# "remote" store without any filesystem.
_fake_lock = lockdep.lock("blob._fake_lock")
_fake_registry: Dict[str, FakeBackend] = {}  # guarded-by: _fake_lock

# Deployment-registered schemes (the cloud-SDK hook): scheme -> factory
# taking the full URI.
_scheme_lock = lockdep.lock("blob._scheme_lock")
_scheme_registry: Dict[str, Callable[[str], BlobBackend]] = {}  # guarded-by: _scheme_lock


def register_backend(scheme: str,
                     factory: Callable[[str], BlobBackend]) -> None:
    """Register a backend factory for a URI scheme (``gs``, ``s3``, ...).
    This is the gate for cloud SDKs the images do not ship: a deployment
    registers its own wrapper at payload/operator start instead of this
    repo importing boto/google-cloud-storage."""
    with _scheme_lock:
        _scheme_registry[scheme.lower()] = factory


def fake_backend(name: str, latency: float = 0.0) -> FakeBackend:
    """The shared named fake instance (created on first use)."""
    with _fake_lock:
        backend = _fake_registry.get(name)
        if backend is None:
            backend = FakeBackend(latency=latency)
            _fake_registry[name] = backend
        return backend


def reset_fake_backends() -> None:
    """Test hook: drop every named fake instance."""
    with _fake_lock:
        _fake_registry.clear()


def from_uri(uri: str) -> BlobBackend:
    """Resolve a store URI to a backend.

    - ``file:///shared/warmstore`` or a bare absolute path → LocalFS
    - ``fake://name[?latency=0.05]`` → the shared named in-process fake
    - a registered scheme (``register_backend``) → its factory
    - anything else → a BlobError naming the registration hook, NOT an
      import error at job runtime.
    """
    if not uri:
        raise BlobError("empty store URI")
    if uri.startswith("/"):
        return LocalFSBackend(uri)
    parsed = urllib.parse.urlparse(uri)
    scheme = (parsed.scheme or "").lower()
    if scheme == "file":
        path = parsed.path or parsed.netloc
        if not path.startswith("/"):
            raise BlobError(f"file:// store URI must be absolute: {uri!r}")
        return LocalFSBackend(path)
    if scheme == "fake":
        params = dict(urllib.parse.parse_qsl(parsed.query))
        try:
            latency = float(params.get("latency", 0.0))
        except ValueError:
            latency = 0.0
        return fake_backend(parsed.netloc or "default", latency=latency)
    with _scheme_lock:
        factory = _scheme_registry.get(scheme)
    if factory is not None:
        return factory(uri)
    raise BlobError(
        f"no blob backend for scheme {scheme!r} ({uri!r}): this build "
        f"ships file:// and fake:// only; register a cloud backend via "
        f"tpu_operator.store.blob.register_backend({scheme!r}, factory)")
