"""Async write-behind uploader: remote persistence OFF the step loop.

The PR 4 durability contract keeps verification off the step path (a
background verify thread, reaped at save boundaries); this applies the
same shape to remote uploads. The step loop's only interaction is
:meth:`enqueue` — a lock-guarded dict update that never touches the
backend — while a single daemon worker drains the queue:

- **Last-wins coalescing.** Under backpressure (slow remote, fast save
  cadence) pending uploads coalesce per kind: only the NEWEST pending
  checkpoint step uploads; superseded ones are dropped (the remote store
  is a warm-start source, not an archive — the newest durable step is the
  one a fresh node wants).
- **Failure accounting + escalation contract.** Upload failures are
  counted exactly like local save failures (total + consecutive); the
  step-loop side (payload/checkpoint.py) polls :meth:`escalated` at save
  boundaries and converts a persistent streak into the retryable exit
  (143), handing the broken remote to the operator's restart machinery —
  a transient blip costs nothing but a skipped upload.
- **Cache piggyback.** After each checkpoint upload the worker also syncs
  new compilation-cache entries (content-named files, set-difference
  cheap), so a fresh node's cache prefetch finds the executables the
  attempt compiled without a separate upload schedule.

The worker is a daemon: process exit never blocks on a wedged remote.
``close(flush=True)`` (end-of-run) waits up to a bounded timeout for the
final step to land — best-effort, a completed run is never converted to a
failure by its upload tail.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, Optional

from tpu_operator.store.warmstart import WarmStartStore
from tpu_operator.util import lockdep, yieldpoints

log = logging.getLogger(__name__)

# Consecutive upload failures tolerated before the step loop escalates —
# the same default discipline as local save failures (checkpoint.py
# DEFAULT_FAIL_AFTER).
DEFAULT_FAIL_AFTER = 3

# close(flush=True) bound: a final-checkpoint upload slower than this is
# abandoned (the run already succeeded locally).
DEFAULT_FLUSH_TIMEOUT = 120.0


class WriteBehindUploader:
    """One background worker shipping verified checkpoints (and cache
    entries) to a :class:`WarmStartStore`."""

    def __init__(self, store: WarmStartStore,
                 fail_after: int = DEFAULT_FAIL_AFTER,
                 cache_dir_fn: Optional[Any] = None,
                 keep_snapshots: int = 0):
        self.store = store
        self.fail_after = max(1, int(fail_after))
        # Retention GC (spec.store.keepSnapshots): after each successful
        # commit the worker condemns-then-deletes verified snapshots
        # beyond the newest N (0 = keep everything). Runs on the worker
        # thread, after the commit — the step loop never pays it, and a
        # failed upload never GCs (the newest durable step must not lose
        # older fallbacks to a retention pass it didn't earn).
        self.keep_snapshots = max(0, int(keep_snapshots))
        self.gc_removed = 0  # guarded-by: _cond
        # Zero-arg callable resolving the live compilation-cache dir at
        # upload time (bootstrap enables the cache after the uploader may
        # already exist); None/"" = no cache sync.
        self._cache_dir_fn = cache_dir_fn
        self._cond = lockdep.condition("WriteBehindUploader._cond")
        # kind -> pending task; "checkpoint" holds (step, dir) last-wins,
        # "corrupt" holds a set of steps to mark, "artifacts" maps remote
        # name -> local path (postmortem step traces; last-wins per name).
        self._pending_step: Optional[tuple] = None  # guarded-by: _cond
        self._pending_corrupt: set = set()  # guarded-by: _cond
        self._pending_artifacts: Dict[str, str] = {}  # guarded-by: _cond
        self._busy = False  # guarded-by: _cond
        self._closed = False  # guarded-by: _cond
        # Counters (read by stats()/escalated() from the step loop).
        self.uploads = 0  # guarded-by: _cond
        self.upload_failures = 0  # guarded-by: _cond
        self.consecutive_failures = 0  # guarded-by: _cond
        self.last_uploaded_step: Optional[int] = None  # guarded-by: _cond
        self.cache_files_uploaded = 0  # guarded-by: _cond
        self.dropped_superseded = 0  # guarded-by: _cond
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="store-writebehind")
        self._thread.start()

    # -- step-loop side (never blocks on the backend) --------------------------

    def enqueue(self, step: int, step_dir: str) -> bool:
        """Queue one verified step for upload. Non-blocking by
        construction: a pending older step is superseded (dropped).
        Returns False when the uploader is closed (the step was REFUSED,
        not queued) — before the explicit refusal, a caller racing
        ``close()`` could not tell a stranded enqueue from an accepted
        one (seeded-schedule finding)."""
        with self._cond:
            if self._closed:
                return False
            if self._pending_step is not None \
                    and self._pending_step[0] != int(step):
                self.dropped_superseded += 1
            self._pending_step = (int(step), step_dir)
            self._cond.notify()
            return True

    def mark_corrupt(self, step: int) -> None:
        """Queue a remote quarantine mark (restore-path hook); async so a
        slow remote cannot stall the restore walk."""
        with self._cond:
            if self._closed:
                return
            self._pending_corrupt.add(int(step))
            if self._pending_step is not None \
                    and self._pending_step[0] == int(step):
                self._pending_step = None  # never upload a condemned step
            self._cond.notify()

    def enqueue_artifact(self, path: str, name: str = "") -> None:
        """Queue one small file (a postmortem step-trace dump) for remote
        upload under the job's ``artifacts/`` prefix. Same non-blocking
        discipline as checkpoints; failures are logged, never counted
        toward escalation — an artifact is a postmortem aid, not
        durability."""
        with self._cond:
            if self._closed:
                return
            self._pending_artifacts[name or os.path.basename(path)] = path
            self._cond.notify()

    def escalated(self) -> bool:
        """True when the remote has failed ``fail_after`` consecutive
        uploads — the step loop converts this to the retryable exit, the
        same contract as persistent local save failures."""
        with self._cond:
            return self.consecutive_failures >= self.fail_after

    def stats(self) -> Dict[str, int]:
        """Heartbeat-facing counters (merged into Checkpointer.stats())."""
        with self._cond:
            out: Dict[str, int] = {
                "uploadFailures": int(self.upload_failures),
            }
            if self.last_uploaded_step is not None:
                out["lastUploadedStep"] = int(self.last_uploaded_step)
            return out

    def idle(self) -> bool:
        with self._cond:
            return (self._pending_step is None
                    and not self._pending_corrupt
                    and not self._pending_artifacts and not self._busy)

    def flush(self, timeout: float = DEFAULT_FLUSH_TIMEOUT) -> bool:
        """Wait (bounded) until the queue drains; True when it did."""
        import time

        deadline = time.monotonic() + max(0.0, timeout)
        with self._cond:
            while (self._pending_step is not None
                   or self._pending_corrupt
                   or self._pending_artifacts or self._busy):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(0.1, remaining))
            return True

    def close(self, flush: bool = False,
              timeout: float = DEFAULT_FLUSH_TIMEOUT) -> None:
        """Stop accepting work; optionally drain what was accepted
        (bounded).

        The close mark lands BEFORE the drain, not after: the original
        drain-then-mark order had a window — flush() observes an empty
        queue, the checkpoint verify thread enqueues the final verified
        step, close() marks closed and returns — where an ACCEPTED
        enqueue was stranded behind a returned close, and the process
        exit tore down the daemon worker mid-upload. Found by the
        deterministic interleaving harness (writebehind close/enqueue
        schedule); with mark-first, a racing enqueue either lands before
        the mark (the flush below waits for its upload) or is refused
        outright (enqueue returns False) — never silently stranded."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        yieldpoints.pause("writebehind.close.marked")
        if flush:
            self.flush(timeout)

    # -- worker ----------------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while (self._pending_step is None
                       and not self._pending_corrupt
                       and not self._pending_artifacts and not self._closed):
                    self._cond.wait()
                if self._closed and self._pending_step is None \
                        and not self._pending_corrupt \
                        and not self._pending_artifacts:
                    return
                task_step = self._pending_step
                self._pending_step = None
                corrupt = set(self._pending_corrupt)
                self._pending_corrupt.clear()
                artifacts = dict(self._pending_artifacts)
                self._pending_artifacts.clear()
                self._busy = True
            # Scheduling-sensitive window: the task is popped (queue looks
            # empty) but not yet uploaded — the interleaving harness
            # parks the worker here to drive enqueue/close through it.
            yieldpoints.pause("writebehind.popped")
            try:
                for step in sorted(corrupt):
                    try:
                        self.store.mark_corrupt(step, "local quarantine")
                    except Exception as e:  # noqa: BLE001 — best-effort mark
                        log.warning("remote corrupt-mark of step %d failed: "
                                    "%s", step, e)
                for name, path in sorted(artifacts.items()):
                    try:
                        self.store.upload_artifact(path, name)
                    except Exception as e:  # noqa: BLE001 — postmortem aid
                        log.warning("artifact upload of %s failed: %s",
                                    name, e)
                if task_step is not None:
                    self._upload(*task_step)
                    # Cache sync is INDEPENDENT of the checkpoint upload's
                    # outcome (entries compiled this attempt are valuable
                    # even when the snapshot failed to ship) — a failed
                    # upload must not also forfeit the fresh-node warm
                    # compile.
                    self._sync_cache()
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def _upload(self, step: int, step_dir: str) -> None:
        try:
            self.store.upload_checkpoint(step_dir, step)
        except Exception as e:  # noqa: BLE001 — counted, never propagates
            with self._cond:
                self.upload_failures += 1
                self.consecutive_failures += 1
                consecutive = self.consecutive_failures
                total = self.upload_failures
            log.warning(
                "remote checkpoint upload of step %d failed (%d "
                "consecutive, %d total): %s", step, consecutive, total, e)
            return
        with self._cond:
            self.uploads += 1
            self.consecutive_failures = 0
            self.last_uploaded_step = int(step)
        log.info("remote store: uploaded checkpoint step %d", step)
        if self.keep_snapshots:
            try:
                n = self.store.retain(self.keep_snapshots)
            except Exception as e:  # noqa: BLE001 — GC is best-effort
                log.warning("retention GC after step %d failed: %s",
                            step, e)
                return
            if n:
                with self._cond:
                    self.gc_removed += n

    def _sync_cache(self) -> None:
        cache_dir = ""
        if self._cache_dir_fn is not None:
            try:
                cache_dir = str(self._cache_dir_fn() or "")
            except Exception:  # noqa: BLE001 — cache sync is best-effort
                cache_dir = ""
        if not cache_dir:
            return
        try:
            n = self.store.upload_cache(cache_dir)
        except Exception as e:  # noqa: BLE001 — best-effort
            log.warning("compilation-cache upload failed: %s", e)
            return
        if n:
            with self._cond:
                self.cache_files_uploaded += n
            log.info("remote store: uploaded %d compilation-cache "
                     "entries", n)
