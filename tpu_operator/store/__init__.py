"""Remote warm-start store: a pluggable blob backend for checkpoints and
the persistent compilation cache.

PR 5's warm restart (persistent XLA cache, 2.54x TTFS) and PR 4's durable
checkpoints both live on node-local directories, so they only survive
*same-node* rescheduling — while the PR 7 fleet scheduler deliberately
preempts and re-places gangs across nodes. This package is the missing
remote half: an object-store-shaped blob API (``blob.py``), chunked
parallel transfer with per-chunk sha256 integrity (``transfer.py``), a
job-scoped warm-start store layering checkpoints + compilation-cache sync
+ a corrupt-step index on top (``warmstart.py``), and an async write-behind
uploader that keeps remote persistence off the training step path
(``writebehind.py``).

Stdlib-only by design: the package is imported by both the operator image
(controller-side introspection) and the payload image (upload/prefetch),
and must drag neither jax nor any cloud SDK into either. Cloud backends
(gs://, s3://) are deliberately *gated*, not vendored: ``blob.from_uri``
raises a clear error naming the registration hook
(``blob.register_backend``) so a deployment wires its own SDK-backed
implementation instead of this repo growing a dependency.
"""

from tpu_operator.store.blob import (  # noqa: F401
    BlobBackend,
    BlobError,
    BlobNotFound,
    FakeBackend,
    LocalFSBackend,
    from_uri,
    register_backend,
)
from tpu_operator.store.transfer import (  # noqa: F401
    IntegrityError,
    TransferError,
    download_tree,
    upload_tree,
)
from tpu_operator.store.warmstart import WarmStartStore  # noqa: F401
from tpu_operator.store.writebehind import WriteBehindUploader  # noqa: F401
