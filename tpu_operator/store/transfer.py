"""Chunked, parallel, integrity-checked directory transfer over a blob
backend.

One local directory tree ↔ one remote *snapshot* under a key prefix:

    <prefix>/manifest.json                      — commit marker, written LAST
    <prefix>/data/<relpath>/<idx>-<sha16>       — one object per chunk

The manifest records every file's size plus each chunk's size and sha256 —
the same per-file integrity discipline PR 4's local checkpoint manifest
established, applied to the wire. Properties the warm-start store builds
on:

- **Commit marker.** Chunks upload first, the manifest last: a snapshot
  without a manifest does not exist (a killed upload leaves harmless
  orphan chunks, never a half-snapshot a restore could prefer).
- **Torn-upload resume.** Chunk keys embed the chunk's own sha256 prefix,
  so an object that ``exists`` is *provably* the right bytes (backends
  write atomically) — a retried upload skips straight past everything the
  torn attempt landed and pays only the missing tail.
- **Per-chunk verification + one retry.** Every downloaded chunk is
  re-hashed; a mismatch is re-fetched once (transient corruption — a torn
  read, a flaky proxy) before :class:`IntegrityError` aborts the snapshot,
  at which point the caller (warmstart.py) falls back to the next-oldest
  snapshot rather than restoring known-bad bytes.
- **Bounded parallelism.** Chunks fan out across a thread pool
  (``parallelism``), first-error propagation, so a multi-GB checkpoint
  moves at aggregate-stream rather than single-stream throughput.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from tpu_operator.store.blob import BlobBackend, BlobError, BlobNotFound

log = logging.getLogger(__name__)

MANIFEST_KEY = "manifest.json"
DATA_PREFIX = "data"

# 8 MiB chunks: large enough that per-object overhead amortizes, small
# enough that parallelism has units to work with on checkpoint-sized files.
DEFAULT_CHUNK_SIZE = 8 << 20
DEFAULT_PARALLELISM = 4


class TransferError(BlobError):
    """A chunked transfer failed."""


class IntegrityError(TransferError):
    """A chunk's bytes failed verification after the retry — the snapshot
    must not be restored."""


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def iter_local_files(local_dir: str) -> List[str]:
    """Relative paths of every transferable file (tmp files skipped)."""
    out: List[str] = []
    for dirpath, _dirs, files in os.walk(local_dir):
        for fn in files:
            if fn.endswith(".tmp"):
                continue
            out.append(os.path.relpath(os.path.join(dirpath, fn), local_dir)
                       .replace(os.sep, "/"))
    return sorted(out)


def _chunk_key(prefix: str, relpath: str, idx: int, sha: str) -> str:
    return f"{prefix}/{DATA_PREFIX}/{relpath}/{idx}-{sha[:16]}"


def _run_pool(tasks: List, parallelism: int) -> None:
    """Run thunks across a bounded pool with first-error propagation (the
    replicas.run_creates discipline, minus the cancel bookkeeping: chunk
    puts/gets are idempotent, so completing in-flight work is harmless)."""
    if not tasks:
        return
    if parallelism <= 1 or len(tasks) == 1:
        for t in tasks:
            t()
        return
    with ThreadPoolExecutor(max_workers=min(parallelism, len(tasks)),
                            thread_name_prefix="blob-xfer") as pool:
        for future in [pool.submit(t) for t in tasks]:
            future.result()


def upload_tree(backend: BlobBackend, local_dir: str, prefix: str,
                parallelism: int = DEFAULT_PARALLELISM,
                chunk_size: int = DEFAULT_CHUNK_SIZE,
                meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Upload ``local_dir`` as the snapshot at ``prefix``; returns the
    manifest. Chunks whose content-addressed key already exists are
    skipped (torn-upload resume)."""
    chunk_size = max(1, int(chunk_size))
    files: List[Dict[str, Any]] = []
    tasks = []
    for relpath in iter_local_files(local_dir):
        path = os.path.join(local_dir, *relpath.split("/"))
        chunks: List[Dict[str, Any]] = []
        try:
            # Pass 1 streams the file once to hash chunk spans (bytes
            # discarded); each pool task re-reads ITS OWN span at put
            # time — peak memory is parallelism × chunk_size, never the
            # whole tree (a multi-GB checkpoint buffered in closures
            # would sit in the training process's RSS for the entire
            # upload). Step dirs are immutable post-verification, so the
            # two passes see the same bytes; a mutation between them
            # would fail the downloader's per-chunk verification anyway.
            with open(path, "rb") as f:
                idx, offset = 0, 0
                while True:
                    data = f.read(chunk_size)
                    if not data and idx > 0:
                        break
                    sha = _sha256(data)
                    key = _chunk_key(prefix, relpath, idx, sha)
                    chunks.append({"idx": idx, "size": len(data),
                                   "sha256": sha})

                    def put(key=key, path=path, offset=offset,
                            size=len(data)):
                        # exists-then-put: the common resume case pays one
                        # cheap probe instead of re-shipping the chunk; a
                        # racing writer of the same key writes identical
                        # bytes (content-addressed), so skip is safe.
                        if backend.exists(key):
                            return
                        with open(path, "rb") as g:
                            g.seek(offset)
                            backend.put(key, g.read(size))

                    tasks.append(put)
                    offset += len(data)
                    idx += 1
                    if not data:
                        break
        except OSError as e:
            raise TransferError(f"reading {path}: {e}") from e
        files.append({"path": relpath,
                      "size": sum(c["size"] for c in chunks),
                      "chunks": chunks})
    _run_pool(tasks, parallelism)
    manifest: Dict[str, Any] = {"files": files}
    if meta:
        manifest["meta"] = dict(meta)
    backend.put(f"{prefix}/{MANIFEST_KEY}",
                json.dumps(manifest, sort_keys=True).encode())
    return manifest


def read_manifest(backend: BlobBackend, prefix: str) -> Dict[str, Any]:
    """The snapshot's manifest (BlobNotFound when the snapshot was never
    committed; TransferError when the manifest bytes are unparseable)."""
    raw = backend.get(f"{prefix}/{MANIFEST_KEY}")
    try:
        doc = json.loads(raw)
        if not isinstance(doc, dict):
            raise ValueError("manifest must be a JSON object")
        return doc
    except ValueError as e:
        raise TransferError(f"unreadable manifest at {prefix}: {e}") from e


def _fetch_chunk(backend: BlobBackend, key: str, want_sha: str,
                 want_size: int) -> bytes:
    """One chunk, verified; a mismatched read is retried exactly once."""
    for attempt in (0, 1):
        data = backend.get(key)
        if len(data) == want_size and _sha256(data) == want_sha:
            return data
        if attempt == 0:
            log.warning("chunk %s failed verification; re-downloading once",
                        key)
    raise IntegrityError(f"chunk {key} failed verification after retry")


def download_tree(backend: BlobBackend, prefix: str, local_dir: str,
                  parallelism: int = DEFAULT_PARALLELISM,
                  manifest: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
    """Materialize the snapshot at ``prefix`` into ``local_dir``; returns
    the manifest. Files already present locally with matching bytes are
    skipped (idempotent across the gang's processes on a shared
    filesystem); each file is assembled in a pid-suffixed tmp and
    ``os.replace``d, so concurrent downloaders last-win complete files."""
    if manifest is None:
        manifest = read_manifest(backend, prefix)
    # Each chunk task fetches, verifies, and pwrite()s its span into a
    # preallocated pid-suffixed tmp — chunk-level parallelism WITHOUT
    # buffering the snapshot in memory (peak = parallelism × chunk_size;
    # the old gather-then-write shape held the whole tree in RAM).
    pending: List[Tuple[int, str, str]] = []  # (fd, tmp, target)
    fetch_tasks = []
    try:
        for entry in manifest.get("files", []):
            relpath = str(entry.get("path", ""))
            if not relpath or relpath.startswith("/") \
                    or ".." in relpath.split("/"):
                raise TransferError(f"manifest names unsafe path {relpath!r}")
            target = os.path.join(local_dir, *relpath.split("/"))
            if _local_file_matches(target, entry):
                continue
            os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
            tmp = f"{target}.{os.getpid()}.tmp"
            try:
                fd = os.open(tmp, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o644)
                os.ftruncate(fd, int(entry.get("size", 0)))
            except OSError as e:
                raise TransferError(f"preparing {tmp}: {e}") from e
            pending.append((fd, tmp, target))
            offset = 0
            for chunk in entry.get("chunks", []):
                key = _chunk_key(prefix, relpath, int(chunk["idx"]),
                                 str(chunk["sha256"]))

                def fetch(fd=fd, key=key, chunk=chunk, offset=offset):
                    data = _fetch_chunk(backend, key, str(chunk["sha256"]),
                                        int(chunk["size"]))
                    if data:
                        os.pwrite(fd, data, offset)

                fetch_tasks.append(fetch)
                offset += int(chunk["size"])
        _run_pool(fetch_tasks, parallelism)
        while pending:
            # pop-then-process: each fd is closed exactly once (a second
            # close of a released fd number could hit an unrelated file
            # another thread just opened), and the error-path scrub below
            # only ever sees genuinely unprocessed entries.
            fd, tmp, target = pending.pop()
            try:
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)
                os.replace(tmp, target)
            except OSError as e:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise TransferError(f"writing {target}: {e}") from e
    finally:
        for fd, tmp, _target in pending:  # error path: scrub partials
            try:
                os.close(fd)
            except OSError:
                pass
            try:
                os.remove(tmp)
            except OSError:
                pass
    return manifest


def _local_file_matches(target: str, entry: Dict[str, Any]) -> bool:
    """Skip-if-present probe: the local file byte-matches the manifest
    entry (size, then each chunk's sha over the recorded chunk spans) —
    what makes gang-wide prefetch into one shared directory idempotent."""
    try:
        if not os.path.isfile(target) \
                or os.path.getsize(target) != entry.get("size"):
            return False
        with open(target, "rb") as f:
            for chunk in entry.get("chunks", []):
                data = f.read(int(chunk["size"]))
                if _sha256(data) != str(chunk["sha256"]):
                    return False
        return True
    except OSError:
        return False


def delete_tree(backend: BlobBackend, prefix: str) -> int:
    """Best-effort removal of a snapshot: the manifest FIRST (the snapshot
    stops existing atomically), then its chunks. Returns objects deleted."""
    deleted = 0
    try:
        backend.delete(f"{prefix}/{MANIFEST_KEY}")
        deleted += 1
    except BlobNotFound:
        pass
    except BlobError as e:
        log.warning("deleting manifest under %s: %s", prefix, e)
    try:
        for key in backend.list(f"{prefix}/{DATA_PREFIX}/"):
            try:
                backend.delete(key)
                deleted += 1
            except BlobError as e:
                log.warning("deleting chunk %s: %s", key, e)
    except BlobError as e:
        log.warning("listing chunks under %s: %s", prefix, e)
    return deleted
