"""Fleet observability plane: unified job timelines, on-demand deep
profiling support, and the fleet goodput rollup (docs/design.md "Fleet
observability")."""
